"""Continuous-batching scheduler: the serving control loop.

Every loop iteration is one decode step of the whole engine batch:

1. **admit** — arrived requests claim free decode slots in order; each
   gets its WHOLE page span (``ceil((prompt + max_new) / page_size)``
   pages) up front.  With the prefix cache on, the prompt's page-aligned
   prefix is hashed first and every cached page maps straight into the
   new sequence's page table by reference (claimed, never copied) — only
   the cold tail is prefilled.  When the pool or the slots are exhausted
   the head request waits (``admission_blocked`` counts the
   backpressure) — a running decode can never die from page exhaustion.
2. **chunked prefill** (``engine.prefill_chunk > 0``) — every slot still
   filling its prompt advances ONE ``[1, C]`` chunk, so a long cold
   prompt costs the running decode streams at most one chunk of latency
   per step instead of its whole prefill wall.  The final chunk's sample
   is the slot's first token, drawn at the same absolute position the
   monolithic prefill samples at.  With chunking off, admission prefills
   the whole prompt inline exactly as before.
3. **decode** — ONE call of the fixed-shape decode program advances every
   decoding slot a token; free and still-prefilling slots ride along
   masked (their writes go to the trash page).
4. **evict** — slots whose new token is ``eos_id`` or whose budget is
   spent release their page references (an unshared page returns to the
   allocator head — the recycle the tests assert; a shared or cached
   page survives) and free the slot for the next admission.

Sampling keys derive from (seed, request id, position) only — slot and
batch-composition independent — so a request decodes the identical token
stream whether it ran alone or packed with others (the
batched-vs-single gate), and a prefix-cache hit decodes the identical
stream as its cold-cache twin (the PR 17 gate).

Latency telemetry splits per request into TTFT (admission → first
token — covers prefill, however it is scheduled) and per-DECODE-token
gaps; both distributions zero-fill to 0.0 on empty runs, like
``sync_ms``.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Optional

import numpy as np

from .cache import page_prefix_keys
from .engine import ServeEngine


@dataclasses.dataclass
class Request:
    rid: int
    prompt: list
    max_new_tokens: int = 16
    temperature: float = 0.0
    arrival_s: float = 0.0        # offset from scheduler start (0 = now)


@dataclasses.dataclass
class Completion:
    rid: int
    prompt_len: int
    tokens: list                  # generated ids (incl. the eos, if hit)
    reason: str                   # "eos" | "length" | "timeout"
    ttft_s: Optional[float]       # admission -> first token (None: none)
    decode_latencies_s: list      # inter-token gaps, first token excluded


@dataclasses.dataclass
class _Slot:
    rid: int
    pages: list
    row: np.ndarray               # page-table row [pages_per_seq]
    prompt: np.ndarray            # the full prompt (chunked refill source)
    plen: int
    filled: int                   # prompt tokens already in the cache
    length: int                   # decode-visible tokens in cache
    temperature: float
    max_new: int
    generated: list
    decode_lat: list
    keys: list                    # content keys of the full prompt pages
    registered: int               # prefix pages already published
    t_last: float
    t_admit: float = 0.0          # wall clock at admission (timeout base)
    ttft_s: Optional[float] = None

    @property
    def prefilling(self) -> bool:
        return self.filled < self.plen


class ContinuousBatchingScheduler:
    """Drives one ``ServeEngine``.  ``max_active`` caps concurrently
    decoding slots below ``engine.max_batch`` — ``max_active=1`` is the
    naive sequential-request baseline the bench A/Bs against."""

    def __init__(self, engine: ServeEngine, *, eos_id: int = -1,
                 max_active: Optional[int] = None,
                 request_timeout: float = 0.0):
        self.engine = engine
        self.eos_id = int(eos_id)
        self.max_active = min(int(max_active or engine.max_batch),
                              engine.max_batch)
        # per-request wall-clock budget (ISSUE 8 satellite): an admitted
        # sequence still decoding past this many seconds is evicted
        # (reason "timeout") so a stuck request frees its slot and pages
        # instead of pinning them forever; 0 disables
        self.request_timeout = float(request_timeout)
        if self.request_timeout < 0:
            raise ValueError(
                f"request_timeout must be >= 0, got {request_timeout}")
        self.stats = {"admitted": 0, "evicted": 0, "admission_blocked": 0,
                      "decode_steps": 0, "tokens_generated": 0,
                      "timed_out": 0, "prefill_chunks": 0,
                      "prefix_hit_pages": 0, "prefix_prompt_pages": 0,
                      "prefill_tokens_saved": 0}
        self._occupancy: list[int] = []

    # -- request validation (fail at submit, not mid-run) ---------------
    def _validate(self, r: Request) -> None:
        eng = self.engine
        plen = len(r.prompt)
        if plen < 1 or r.max_new_tokens < 1:
            raise ValueError(f"request {r.rid}: prompt and max_new_tokens "
                             "must be non-empty/positive")
        ids = np.asarray(r.prompt)
        if ids.min() < 0 or ids.max() >= eng.spec.vocab:
            # jnp gather would silently clamp/wrap out-of-range ids into
            # a confidently-wrong decode — fail at submit instead
            raise ValueError(
                f"request {r.rid}: prompt ids must lie in "
                f"[0, {eng.spec.vocab}); got range "
                f"[{int(ids.min())}, {int(ids.max())}]")
        if not eng.prefill_chunk and plen > eng.prompt_buckets[-1]:
            # the chunk program covers any length; the bucket bound only
            # applies to the monolithic per-bucket prefill (a prefix-hit
            # tail always fits a bucket the full prompt fits)
            raise ValueError(
                f"request {r.rid}: prompt length {plen} exceeds the "
                f"largest prefill bucket {eng.prompt_buckets[-1]}")
        total = plen + r.max_new_tokens
        if total > eng.max_seq:
            raise ValueError(
                f"request {r.rid}: prompt + max_new ({total}) exceeds "
                f"max_seq {eng.max_seq}")
        if eng.pages_for(total) > eng.allocator.max_pages - 1:
            raise ValueError(
                f"request {r.rid}: needs {eng.pages_for(total)} pages but "
                f"the pool holds {eng.allocator.max_pages - 1} — raise "
                "--serve_max_pages or lower max_new_tokens")

    # -- one admission attempt ------------------------------------------
    def _admit(self, r: Request, slots: list, t0: float) -> bool:
        eng = self.engine
        free_slot = next((i for i, s in enumerate(slots) if s is None),
                         None)
        if (free_slot is None
                or sum(s is not None for s in slots) >= self.max_active):
            return False
        plen = len(r.prompt)
        keys: list = []
        hits: list = []
        if eng.prefix_cache:
            keys = page_prefix_keys(r.prompt, eng.page_size)
            # never reuse past (plen - 1): the tail prefill must keep at
            # least one real token so it produces the first-token logits
            hits = eng.allocator.lookup(keys[:(plen - 1) // eng.page_size])
        # claim the hits BEFORE the fresh alloc: alloc may evict
        # refcount-0 cached pages to cover a shortfall, and a claimed
        # page can never be on that LRU
        for p in hits:
            eng.allocator.claim(p)
        fresh = eng.allocator.alloc(
            eng.pages_for(plen + r.max_new_tokens) - len(hits))
        if fresh is None:
            if hits:
                eng.allocator.free(hits)
            self.stats["admission_blocked"] += 1
            return False
        pages = hits + fresh
        row = eng.table_row(pages)
        hit_tok = len(hits) * eng.page_size
        if eng.prefix_cache:
            self.stats["prefix_hit_pages"] += len(hits)
            self.stats["prefix_prompt_pages"] += eng.pages_for(plen)
            self.stats["prefill_tokens_saved"] += hit_tok
        t_adm = time.perf_counter()
        slot = _Slot(rid=r.rid, pages=pages, row=row,
                     prompt=np.asarray(r.prompt, np.int32), plen=plen,
                     filled=hit_tok, length=plen,
                     temperature=r.temperature, max_new=r.max_new_tokens,
                     generated=[], decode_lat=[], keys=keys,
                     registered=len(hits), t_last=t_adm, t_admit=t_adm)
        if not eng.prefill_chunk:
            first, _ = eng.prefill(slot.prompt[hit_tok:], row,
                                   r.temperature, r.rid, offset=hit_tok)
            now = time.perf_counter()
            slot.generated = [first]
            slot.filled = plen
            slot.ttft_s = now - t_adm
            slot.t_last = now
            self.stats["tokens_generated"] += 1
            self._register_prefix(slot)
        slots[free_slot] = slot
        self.stats["admitted"] += 1
        self._occupancy.append(eng.allocator.in_use)
        return True

    def _register_prefix(self, slot: _Slot) -> None:
        """Publish the content keys of every FULL prompt page the slot
        has finished writing (hit pages arrive pre-registered); the
        partial last page and all decode pages stay private — this
        sequence keeps writing into them."""
        if not self.engine.prefix_cache or not slot.keys:
            return
        nfull = min(slot.filled // self.engine.page_size, len(slot.keys))
        for i in range(slot.registered, nfull):
            self.engine.allocator.register(slot.keys[i], slot.pages[i])
        slot.registered = max(slot.registered, nfull)

    def _advance_chunk(self, slot: _Slot) -> None:
        """One ``[1, C]`` chunk of this slot's prompt into the cache; the
        final chunk's sample becomes the slot's first generated token."""
        eng = self.engine
        start = slot.filled
        end = min(start + eng.prefill_chunk, slot.plen)
        tok, _ = eng.prefill_chunk_step(slot.prompt[start:end], start,
                                        slot.row, slot.temperature,
                                        slot.rid)
        slot.filled = end
        self.stats["prefill_chunks"] += 1
        self._register_prefix(slot)
        if end >= slot.plen:
            now = time.perf_counter()
            slot.generated = [tok]
            slot.ttft_s = now - slot.t_admit
            slot.t_last = now
            self.stats["tokens_generated"] += 1

    def _finish(self, slot: _Slot, reason: str) -> Completion:
        self.engine.allocator.free(slot.pages)
        self.stats["evicted"] += 1
        return Completion(rid=slot.rid, prompt_len=slot.plen,
                          tokens=slot.generated, reason=reason,
                          ttft_s=slot.ttft_s,
                          decode_latencies_s=slot.decode_lat)

    def _stop_reason(self, slot: _Slot) -> Optional[str]:
        if not slot.generated:
            return None
        if self.eos_id >= 0 and slot.generated[-1] == self.eos_id:
            return "eos"
        if len(slot.generated) >= slot.max_new:
            return "length"
        return None

    # -- the loop --------------------------------------------------------
    def run(self, requests: list[Request]) -> dict:
        """Serve ``requests`` to completion; returns the telemetry dict
        (the ``results["serve"]`` payload) with ``completions`` attached
        in request order."""
        eng = self.engine
        rids = [r.rid for r in requests]
        if len(set(rids)) != len(rids):
            # rids key slot lookup, eviction, and the completions dict —
            # a duplicate would silently cross-wire two requests
            raise ValueError(
                f"request ids must be unique, got duplicates in {rids}")
        for r in requests:
            self._validate(r)
        queue = deque(sorted(requests, key=lambda r: (r.arrival_s, r.rid)))
        slots: list[Optional[_Slot]] = [None] * eng.max_batch
        done: dict[int, Completion] = {}
        t0 = time.perf_counter()
        while queue or any(s is not None for s in slots):
            now = time.perf_counter() - t0
            if self.request_timeout > 0:
                # evict sequences over their wall-clock budget BEFORE this
                # iteration's admissions and decode dispatch: the freed
                # slot + pages are immediately available to the queue
                # behind them, so one stuck request cannot starve it
                t_now = time.perf_counter()
                for i, s in enumerate(slots):
                    if (s is not None
                            and t_now - s.t_admit > self.request_timeout):
                        self.stats["timed_out"] += 1
                        done[s.rid] = self._finish(s, "timeout")
                        slots[i] = None
            # admit every due request a slot + pages can take, in order
            while queue and queue[0].arrival_s <= now:
                if not self._admit(queue[0], slots, t0):
                    break
                r = queue.popleft()
                slot = next(s for s in slots if s is not None
                            and s.rid == r.rid)
                reason = self._stop_reason(slot)
                if reason:   # eos on the very first token / max_new == 1
                    done[slot.rid] = self._finish(slot, reason)
                    slots[slots.index(slot)] = None
            # chunked prefill: every filling slot advances one chunk per
            # iteration, interleaved with the decode step below
            for i, s in enumerate(slots):
                if s is None or not s.prefilling:
                    continue
                self._advance_chunk(s)
                reason = self._stop_reason(s)
                if reason:   # first token was eos / max_new == 1
                    done[s.rid] = self._finish(s, reason)
                    slots[i] = None
            active_idx = [i for i, s in enumerate(slots)
                          if s is not None and not s.prefilling]
            if not active_idx:
                if queue and not any(s is not None for s in slots):
                    # waiting on a future arrival (pages/slots cannot be
                    # the blocker with nothing active — the pool is empty)
                    time.sleep(max(0.0, min(
                        0.001, queue[0].arrival_s - now)))
                continue
            b = eng.max_batch
            tokens = np.zeros(b, np.int32)
            lengths = np.zeros(b, np.int32)
            table = np.zeros((b, eng.pages_per_seq), np.int32)
            temps = np.zeros(b, np.float32)
            rids = np.zeros(b, np.int32)
            active = np.zeros(b, bool)
            for i in active_idx:
                s = slots[i]
                tokens[i] = s.generated[-1]
                lengths[i] = s.length
                table[i] = s.row
                temps[i] = s.temperature
                rids[i] = s.rid
                active[i] = True
            nxt, _logits = eng.decode(tokens, lengths, table, temps,
                                      rids, active)
            self.stats["decode_steps"] += 1
            t_now = time.perf_counter()
            for i in active_idx:
                s = slots[i]
                s.length += 1
                s.generated.append(int(nxt[i]))
                s.decode_lat.append(t_now - s.t_last)
                s.t_last = t_now
                self.stats["tokens_generated"] += 1
                reason = self._stop_reason(s)
                if reason:
                    done[s.rid] = self._finish(s, reason)
                    slots[i] = None
            self._occupancy.append(eng.allocator.in_use)
        wall = time.perf_counter() - t0
        return self._telemetry(requests, done, wall)

    # -- telemetry -------------------------------------------------------
    def _telemetry(self, requests, done: dict, wall: float) -> dict:
        eng = self.engine
        dec_ms = sorted(1e3 * x for c in done.values()
                        for x in c.decode_latencies_s)
        ttft_ms = sorted(1e3 * c.ttft_s for c in done.values()
                         if c.ttft_s is not None)

        def dist(samples_ms):
            # zero-filled schema on empty runs (the sync_ms convention):
            # consumers always see the same keys with float values
            def pct(p):
                if not samples_ms:
                    return 0.0
                return round(samples_ms[min(len(samples_ms) - 1,
                                            int(p / 100.0
                                                * len(samples_ms)))], 3)
            return {"p50": pct(50), "p99": pct(99),
                    "mean": (round(float(np.mean(samples_ms)), 3)
                             if samples_ms else 0.0)}

        occ = self._occupancy or [0]
        page_bytes = eng.page_bytes()
        hit_pages = self.stats["prefix_hit_pages"]
        prompt_pages = self.stats["prefix_prompt_pages"]
        out = {
            "enabled": True,
            "requests": len(requests),
            "admitted": self.stats["admitted"],
            "evicted": self.stats["evicted"],
            "admission_blocked": self.stats["admission_blocked"],
            "timed_out": self.stats["timed_out"],
            "decode_steps": self.stats["decode_steps"],
            "tokens_generated": self.stats["tokens_generated"],
            "wall_s": round(wall, 4),
            "tokens_per_s": round(
                self.stats["tokens_generated"] / max(wall, 1e-9), 2),
            "prefill_buckets": sorted(eng.compiled_buckets),
            "prefill_chunks": self.stats["prefill_chunks"],
            "max_batch": eng.max_batch,
            # per-DECODE-token gaps only; the first token's wall (which
            # includes prefill) lives in ttft_ms — inline prefill no
            # longer pollutes the per-token percentiles
            "latency_ms": dist(dec_ms),
            "ttft_ms": dist(ttft_ms),
            "page_reuse_ratio": (round(hit_pages / prompt_pages, 4)
                                 if prompt_pages else 0.0),
            "prefill_tokens_saved": self.stats["prefill_tokens_saved"],
            # byte-exact page accounting: in_use sampled after every
            # admission/step x the per-page pin across both pools
            "pages": {"page_size": eng.page_size,
                      "max_pages": eng.allocator.max_pages,
                      "page_bytes": page_bytes,
                      "peak_in_use": max(occ),
                      "mean_in_use": round(float(np.mean(occ)), 2),
                      "peak_bytes": max(occ) * page_bytes,
                      "cached_pages": eng.allocator.cached_pages,
                      "cache_evictions": eng.allocator.cache_evictions,
                      "leaked": eng.allocator.in_use},
        }
        out["completions"] = [done[r.rid] for r in requests
                              if r.rid in done]
        return out
