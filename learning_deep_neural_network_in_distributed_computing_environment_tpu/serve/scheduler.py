"""Continuous-batching scheduler: the serving control loop.

Every loop iteration is one decode step of the whole engine batch:

1. **admit** — arrived requests claim free decode slots in order; each
   gets its WHOLE page span (``ceil((prompt + max_new) / page_size)``
   pages) up front.  With the prefix cache on, the prompt's page-aligned
   prefix is hashed first and every cached page maps straight into the
   new sequence's page table by reference (claimed, never copied) — only
   the cold tail is prefilled.  When the pool or the slots are exhausted
   the head request waits (``admission_blocked`` counts the
   backpressure) — a running decode can never die from page exhaustion.
2. **chunked prefill** (``engine.prefill_chunk > 0``) — every slot still
   filling its prompt advances ONE ``[1, C]`` chunk, so a long cold
   prompt costs the running decode streams at most one chunk of latency
   per step instead of its whole prefill wall.  The final chunk's sample
   is the slot's first token, drawn at the same absolute position the
   monolithic prefill samples at.  With chunking off, admission prefills
   the whole prompt inline exactly as before.
3. **decode** — ONE call of the fixed-shape decode program advances every
   decoding slot a token; free and still-prefilling slots ride along
   masked (their writes go to the trash page).
4. **evict** — slots whose new token is ``eos_id`` or whose budget is
   spent release their page references (an unshared page returns to the
   allocator head — the recycle the tests assert; a shared or cached
   page survives) and free the slot for the next admission.

Sampling keys derive from (seed, request id, position) only — slot and
batch-composition independent — so a request decodes the identical token
stream whether it ran alone or packed with others (the
batched-vs-single gate), and a prefix-cache hit decodes the identical
stream as its cold-cache twin (the PR 17 gate).

With a draft engine paired (ISSUE 18) step 3 becomes one SPECULATION
tick: k fixed-shape greedy draft decode steps propose d_1..d_k (the
draft pool advancing in lockstep), one fused ``[B, k+1]`` verify scores
the pending token + proposals through the target and returns the
accepted prefix + bonus per slot, and the commit advances both pools'
position counters by ``acc + 1`` — acceptance is capped at k-1 (the
bonus then equals the k-th draft, so the emitted stream is unchanged)
which keeps both caches exactly filled to the new length every tick:
rollback is pure page-table arithmetic, rejected KV rows are recycled
in place by the next burst's masked writes, and the greedy stream stays
bitwise equal to the non-speculative twin's.  Admission claims the full
span in BOTH pools all-or-nothing (``cache.paired_admit``) so a running
pair can never deadlock on pages.

Latency telemetry splits per request into TTFT (admission → first
token — covers prefill, however it is scheduled) and per-DECODE-token
gaps; both distributions zero-fill to 0.0 on empty runs, like
``sync_ms``.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Optional

import numpy as np

from .cache import page_prefix_keys, paired_admit
from .engine import ServeEngine


@dataclasses.dataclass
class Request:
    rid: int
    prompt: list
    max_new_tokens: int = 16
    temperature: float = 0.0
    arrival_s: float = 0.0        # offset from scheduler start (0 = now)


@dataclasses.dataclass
class Completion:
    rid: int
    prompt_len: int
    tokens: list                  # generated ids (incl. the eos, if hit)
    reason: str                   # "eos" | "length" | "timeout"
    ttft_s: Optional[float]       # admission -> first token (None: none)
    decode_latencies_s: list      # inter-token gaps, first token excluded


@dataclasses.dataclass
class _Slot:
    rid: int
    pages: list
    row: np.ndarray               # page-table row [pages_per_seq]
    prompt: np.ndarray            # the full prompt (chunked refill source)
    plen: int
    filled: int                   # prompt tokens already in the cache
    length: int                   # decode-visible tokens in cache
    temperature: float
    max_new: int
    generated: list
    decode_lat: list
    keys: list                    # content keys of the full prompt pages
    registered: int               # prefix pages already published
    t_last: float
    t_admit: float = 0.0          # wall clock at admission (timeout base)
    ttft_s: Optional[float] = None
    draft_pages: Optional[list] = None   # draft-pool twin span (spec mode)
    draft_row: Optional[np.ndarray] = None

    @property
    def prefilling(self) -> bool:
        return self.filled < self.plen


class ContinuousBatchingScheduler:
    """Drives one ``ServeEngine``.  ``max_active`` caps concurrently
    decoding slots below ``engine.max_batch`` — ``max_active=1`` is the
    naive sequential-request baseline the bench A/Bs against."""

    def __init__(self, engine: ServeEngine, *, eos_id: int = -1,
                 max_active: Optional[int] = None,
                 request_timeout: float = 0.0):
        self.engine = engine
        self.eos_id = int(eos_id)
        self.max_active = min(int(max_active or engine.max_batch),
                              engine.max_batch)
        # per-request wall-clock budget (ISSUE 8 satellite): an admitted
        # sequence still decoding past this many seconds is evicted
        # (reason "timeout") so a stuck request frees its slot and pages
        # instead of pinning them forever; 0 disables
        self.request_timeout = float(request_timeout)
        if self.request_timeout < 0:
            raise ValueError(
                f"request_timeout must be >= 0, got {request_timeout}")
        self.stats = {"admitted": 0, "evicted": 0, "admission_blocked": 0,
                      "decode_steps": 0, "tokens_generated": 0,
                      "timed_out": 0, "prefill_chunks": 0,
                      "prefix_hit_pages": 0, "prefix_prompt_pages": 0,
                      "prefill_tokens_saved": 0,
                      # speculation counters (stay 0 without a draft):
                      # drafted = k per active slot per tick; accepted =
                      # the committed draft tokens (the bonus is a
                      # TARGET token and never counts); emitted = all
                      # committed tokens of the decode phase
                      "draft_steps": 0, "verify_steps": 0,
                      "spec_drafted": 0, "spec_accepted": 0,
                      "spec_emitted": 0}
        self._occupancy: list[int] = []

    # -- request validation (fail at submit, not mid-run) ---------------
    def _validate(self, r: Request) -> None:
        eng = self.engine
        plen = len(r.prompt)
        if plen < 1 or r.max_new_tokens < 1:
            raise ValueError(f"request {r.rid}: prompt and max_new_tokens "
                             "must be non-empty/positive")
        ids = np.asarray(r.prompt)
        if ids.min() < 0 or ids.max() >= eng.spec.vocab:
            # jnp gather would silently clamp/wrap out-of-range ids into
            # a confidently-wrong decode — fail at submit instead
            raise ValueError(
                f"request {r.rid}: prompt ids must lie in "
                f"[0, {eng.spec.vocab}); got range "
                f"[{int(ids.min())}, {int(ids.max())}]")
        if not eng.prefill_chunk and plen > eng.prompt_buckets[-1]:
            # the chunk program covers any length; the bucket bound only
            # applies to the monolithic per-bucket prefill (a prefix-hit
            # tail always fits a bucket the full prompt fits)
            raise ValueError(
                f"request {r.rid}: prompt length {plen} exceeds the "
                f"largest prefill bucket {eng.prompt_buckets[-1]}")
        if eng.draft is not None and r.temperature > 0.0:
            raise ValueError(
                f"request {r.rid}: temperature {r.temperature} under "
                "speculative decoding — acceptance is greedy argmax "
                "equality against the verify logits; temperature "
                "sampling needs the stochastic rejection-sampling rule "
                "v1 does not implement.  Serve it at temperature 0 or "
                "without --serve_draft_ckpt")
        # a speculating sequence's verify program writes up to position
        # C + k, so its page span (in BOTH pools) covers k extra tokens
        total = plen + r.max_new_tokens + eng.spec_tokens
        if total > eng.max_seq:
            raise ValueError(
                f"request {r.rid}: prompt + max_new"
                + (f" + spec_tokens ({total})" if eng.spec_tokens
                   else f" ({total})")
                + f" exceeds max_seq {eng.max_seq}")
        if eng.pages_for(total) > eng.allocator.max_pages - 1:
            raise ValueError(
                f"request {r.rid}: needs {eng.pages_for(total)} pages but "
                f"the pool holds {eng.allocator.max_pages - 1} — raise "
                "--serve_max_pages or lower max_new_tokens")

    # -- one admission attempt ------------------------------------------
    def _admit(self, r: Request, slots: list, t0: float) -> bool:
        eng = self.engine
        free_slot = next((i for i, s in enumerate(slots) if s is None),
                         None)
        if (free_slot is None
                or sum(s is not None for s in slots) >= self.max_active):
            return False
        plen = len(r.prompt)
        dra = eng.draft
        keys: list = []
        hits: list = []
        d_hits: list = []
        if eng.prefix_cache:
            keys = page_prefix_keys(r.prompt, eng.page_size)
            # never reuse past (plen - 1): the tail prefill must keep at
            # least one real token so it produces the first-token logits
            lim = keys[:(plen - 1) // eng.page_size]
            hits = eng.allocator.lookup(lim)
            if dra is not None:
                # both pools prefill from ONE shared filled offset, so
                # the usable hit run is the shorter of the two pools'
                d_hits = dra.allocator.lookup(lim)
                nj = min(len(hits), len(d_hits))
                hits, d_hits = hits[:nj], d_hits[:nj]
        count = eng.pages_for(plen + r.max_new_tokens + eng.spec_tokens)
        d_pages: Optional[list] = None
        if dra is None:
            # claim the hits BEFORE the fresh alloc: alloc may evict
            # refcount-0 cached pages to cover a shortfall, and a claimed
            # page can never be on that LRU
            for p in hits:
                eng.allocator.claim(p)
            fresh = eng.allocator.alloc(count - len(hits))
            if fresh is None:
                if hits:
                    eng.allocator.free(hits)
                self.stats["admission_blocked"] += 1
                return False
            pages = hits + fresh
        else:
            # speculative pair: the whole span in BOTH pools or nothing
            got = paired_admit(eng.allocator, dra.allocator, hits,
                               d_hits, count)
            if got is None:
                self.stats["admission_blocked"] += 1
                return False
            pages, d_pages = got
        row = eng.table_row(pages)
        hit_tok = len(hits) * eng.page_size
        if eng.prefix_cache:
            self.stats["prefix_hit_pages"] += len(hits)
            self.stats["prefix_prompt_pages"] += eng.pages_for(plen)
            self.stats["prefill_tokens_saved"] += hit_tok
        t_adm = time.perf_counter()
        slot = _Slot(rid=r.rid, pages=pages, row=row,
                     prompt=np.asarray(r.prompt, np.int32), plen=plen,
                     filled=hit_tok, length=plen,
                     temperature=r.temperature, max_new=r.max_new_tokens,
                     generated=[], decode_lat=[], keys=keys,
                     registered=len(hits), t_last=t_adm, t_admit=t_adm,
                     draft_pages=d_pages,
                     draft_row=(eng.table_row(d_pages)
                                if d_pages is not None else None))
        if not eng.prefill_chunk:
            first, _ = eng.prefill(slot.prompt[hit_tok:], row,
                                   r.temperature, r.rid, offset=hit_tok)
            if dra is not None:
                # the draft pool prefills the same prompt span so both
                # caches sit at one filled offset; its sampled token is
                # discarded — the pending token is ALWAYS the target's
                dra.prefill(slot.prompt[hit_tok:], slot.draft_row,
                            0.0, r.rid, offset=hit_tok)
            now = time.perf_counter()
            slot.generated = [first]
            slot.filled = plen
            slot.ttft_s = now - t_adm
            slot.t_last = now
            self.stats["tokens_generated"] += 1
            self._register_prefix(slot)
        slots[free_slot] = slot
        self.stats["admitted"] += 1
        self._occupancy.append(eng.allocator.in_use)
        return True

    def _register_prefix(self, slot: _Slot) -> None:
        """Publish the content keys of every FULL prompt page the slot
        has finished writing (hit pages arrive pre-registered); the
        partial last page and all decode pages stay private — this
        sequence keeps writing into them."""
        if not self.engine.prefix_cache or not slot.keys:
            return
        nfull = min(slot.filled // self.engine.page_size, len(slot.keys))
        for i in range(slot.registered, nfull):
            self.engine.allocator.register(slot.keys[i], slot.pages[i])
            if slot.draft_pages is not None:
                # token-content keys are pool-agnostic: the draft pool's
                # twin page publishes under the SAME key in its own
                # allocator, so both pools hit together on reuse
                self.engine.draft.allocator.register(
                    slot.keys[i], slot.draft_pages[i])
        slot.registered = max(slot.registered, nfull)

    def _advance_chunk(self, slot: _Slot) -> None:
        """One ``[1, C]`` chunk of this slot's prompt into the cache; the
        final chunk's sample becomes the slot's first generated token."""
        eng = self.engine
        start = slot.filled
        end = min(start + eng.prefill_chunk, slot.plen)
        tok, _ = eng.prefill_chunk_step(slot.prompt[start:end], start,
                                        slot.row, slot.temperature,
                                        slot.rid)
        if eng.draft is not None:
            # same chunk through the draft pool (sample discarded): the
            # two caches advance through the prompt in lockstep
            eng.draft.prefill_chunk_step(slot.prompt[start:end], start,
                                         slot.draft_row, 0.0, slot.rid)
        slot.filled = end
        self.stats["prefill_chunks"] += 1
        self._register_prefix(slot)
        if end >= slot.plen:
            now = time.perf_counter()
            slot.generated = [tok]
            slot.ttft_s = now - slot.t_admit
            slot.t_last = now
            self.stats["tokens_generated"] += 1

    def _spec_step(self, slots: list, active_idx: list, done: dict
                   ) -> None:
        """One speculation tick for every decoding slot (ISSUE 18).

        The cache invariant both pools share at tick entry: positions
        ``0 .. C-1`` are filled (C = ``slot.length``) and the pending
        token ``g = generated[-1]`` belongs at position C.  Draft step
        j feeds token ``y_{j-1}`` at offset ``C+j-1`` (``y_0 = g``),
        writing its KV and proposing ``d_j``; after k steps the draft
        pool holds ``0 .. C+k-1``.  The fused verify scores
        ``[g, d_1..d_k]`` at offset C, writes the target KV for
        ``C .. C+k``, and returns the accepted prefix length (capped at
        k-1) plus the bonus — committing ``acc+1`` tokens leaves BOTH
        pools filled exactly to the new C (the cap's whole point); the
        rejected tail is garbage at positions >= C' that the next
        burst's writes replace before the causal mask can read them."""
        eng = self.engine
        dra = eng.draft
        k = eng.spec_tokens
        b = eng.max_batch
        tokens = np.zeros(b, np.int32)
        lengths = np.zeros(b, np.int32)
        table = np.zeros((b, eng.pages_per_seq), np.int32)
        d_table = np.zeros((b, eng.pages_per_seq), np.int32)
        temps = np.zeros(b, np.float32)     # greedy: spec is temp-0 only
        rids = np.zeros(b, np.int32)
        active = np.zeros(b, bool)
        for i in active_idx:
            s = slots[i]
            tokens[i] = s.generated[-1]
            lengths[i] = s.length
            table[i] = s.row
            d_table[i] = s.draft_row
            rids[i] = s.rid
            active[i] = True
        burst = np.empty((b, k + 1), np.int32)
        burst[:, 0] = tokens
        y = tokens
        for j in range(k):
            y, _ = dra.decode(y, lengths + j, d_table, temps, rids,
                              active)
            burst[:, j + 1] = y
        emitted, acc = eng.verify(burst, lengths, table, active)
        self.stats["decode_steps"] += 1     # one target dispatch per tick
        self.stats["verify_steps"] += 1
        self.stats["draft_steps"] += k
        t_now = time.perf_counter()
        for i in active_idx:
            s = slots[i]
            e = int(acc[i]) + 1
            self.stats["spec_drafted"] += k
            self.stats["spec_accepted"] += int(acc[i])
            # commit one token at a time so an eos / budget stop
            # truncates the burst exactly where the twin would have
            # stopped; the tick's latency gap splits evenly across it
            gap = (t_now - s.t_last) / e
            reason = None
            for tok in emitted[i, :e]:
                s.generated.append(int(tok))
                s.decode_lat.append(gap)
                self.stats["tokens_generated"] += 1
                self.stats["spec_emitted"] += 1
                reason = self._stop_reason(s)
                if reason:
                    break
            s.t_last = t_now
            if reason:
                done[s.rid] = self._finish(s, reason)
                slots[i] = None
            else:
                s.length += e

    def _finish(self, slot: _Slot, reason: str) -> Completion:
        self.engine.allocator.free(slot.pages)
        if slot.draft_pages is not None:
            self.engine.draft.allocator.free(slot.draft_pages)
        self.stats["evicted"] += 1
        return Completion(rid=slot.rid, prompt_len=slot.plen,
                          tokens=slot.generated, reason=reason,
                          ttft_s=slot.ttft_s,
                          decode_latencies_s=slot.decode_lat)

    def _stop_reason(self, slot: _Slot) -> Optional[str]:
        if not slot.generated:
            return None
        if self.eos_id >= 0 and slot.generated[-1] == self.eos_id:
            return "eos"
        if len(slot.generated) >= slot.max_new:
            return "length"
        return None

    # -- the loop --------------------------------------------------------
    def run(self, requests: list[Request]) -> dict:
        """Serve ``requests`` to completion; returns the telemetry dict
        (the ``results["serve"]`` payload) with ``completions`` attached
        in request order."""
        eng = self.engine
        rids = [r.rid for r in requests]
        if len(set(rids)) != len(rids):
            # rids key slot lookup, eviction, and the completions dict —
            # a duplicate would silently cross-wire two requests
            raise ValueError(
                f"request ids must be unique, got duplicates in {rids}")
        for r in requests:
            self._validate(r)
        queue = deque(sorted(requests, key=lambda r: (r.arrival_s, r.rid)))
        slots: list[Optional[_Slot]] = [None] * eng.max_batch
        done: dict[int, Completion] = {}
        t0 = time.perf_counter()
        while queue or any(s is not None for s in slots):
            now = time.perf_counter() - t0
            if self.request_timeout > 0:
                # evict sequences over their wall-clock budget BEFORE this
                # iteration's admissions and decode dispatch: the freed
                # slot + pages are immediately available to the queue
                # behind them, so one stuck request cannot starve it
                t_now = time.perf_counter()
                for i, s in enumerate(slots):
                    if (s is not None
                            and t_now - s.t_admit > self.request_timeout):
                        self.stats["timed_out"] += 1
                        done[s.rid] = self._finish(s, "timeout")
                        slots[i] = None
            # admit every due request a slot + pages can take, in order
            while queue and queue[0].arrival_s <= now:
                if not self._admit(queue[0], slots, t0):
                    break
                r = queue.popleft()
                slot = next(s for s in slots if s is not None
                            and s.rid == r.rid)
                reason = self._stop_reason(slot)
                if reason:   # eos on the very first token / max_new == 1
                    done[slot.rid] = self._finish(slot, reason)
                    slots[slots.index(slot)] = None
            # chunked prefill: every filling slot advances one chunk per
            # iteration, interleaved with the decode step below
            for i, s in enumerate(slots):
                if s is None or not s.prefilling:
                    continue
                self._advance_chunk(s)
                reason = self._stop_reason(s)
                if reason:   # first token was eos / max_new == 1
                    done[s.rid] = self._finish(s, reason)
                    slots[i] = None
            active_idx = [i for i, s in enumerate(slots)
                          if s is not None and not s.prefilling]
            if not active_idx:
                if queue and not any(s is not None for s in slots):
                    # waiting on a future arrival (pages/slots cannot be
                    # the blocker with nothing active — the pool is empty)
                    time.sleep(max(0.0, min(
                        0.001, queue[0].arrival_s - now)))
                continue
            if eng.draft is not None:
                self._spec_step(slots, active_idx, done)
                self._occupancy.append(eng.allocator.in_use)
                continue
            b = eng.max_batch
            tokens = np.zeros(b, np.int32)
            lengths = np.zeros(b, np.int32)
            table = np.zeros((b, eng.pages_per_seq), np.int32)
            temps = np.zeros(b, np.float32)
            rids = np.zeros(b, np.int32)
            active = np.zeros(b, bool)
            for i in active_idx:
                s = slots[i]
                tokens[i] = s.generated[-1]
                lengths[i] = s.length
                table[i] = s.row
                temps[i] = s.temperature
                rids[i] = s.rid
                active[i] = True
            nxt, _logits = eng.decode(tokens, lengths, table, temps,
                                      rids, active)
            self.stats["decode_steps"] += 1
            t_now = time.perf_counter()
            for i in active_idx:
                s = slots[i]
                s.length += 1
                s.generated.append(int(nxt[i]))
                s.decode_lat.append(t_now - s.t_last)
                s.t_last = t_now
                self.stats["tokens_generated"] += 1
                reason = self._stop_reason(s)
                if reason:
                    done[s.rid] = self._finish(s, reason)
                    slots[i] = None
            self._occupancy.append(eng.allocator.in_use)
        wall = time.perf_counter() - t0
        return self._telemetry(requests, done, wall)

    # -- telemetry -------------------------------------------------------
    def _telemetry(self, requests, done: dict, wall: float) -> dict:
        eng = self.engine
        dec_ms = sorted(1e3 * x for c in done.values()
                        for x in c.decode_latencies_s)
        ttft_ms = sorted(1e3 * c.ttft_s for c in done.values()
                         if c.ttft_s is not None)

        def dist(samples_ms):
            # zero-filled schema on empty runs (the sync_ms convention):
            # consumers always see the same keys with float values
            def pct(p):
                if not samples_ms:
                    return 0.0
                return round(samples_ms[min(len(samples_ms) - 1,
                                            int(p / 100.0
                                                * len(samples_ms)))], 3)
            return {"p50": pct(50), "p99": pct(99),
                    "mean": (round(float(np.mean(samples_ms)), 3)
                             if samples_ms else 0.0)}

        occ = self._occupancy or [0]
        page_bytes = eng.page_bytes()
        hit_pages = self.stats["prefix_hit_pages"]
        prompt_pages = self.stats["prefix_prompt_pages"]
        out = {
            "enabled": True,
            "requests": len(requests),
            "admitted": self.stats["admitted"],
            "evicted": self.stats["evicted"],
            "admission_blocked": self.stats["admission_blocked"],
            "timed_out": self.stats["timed_out"],
            "decode_steps": self.stats["decode_steps"],
            "tokens_generated": self.stats["tokens_generated"],
            "wall_s": round(wall, 4),
            "tokens_per_s": round(
                self.stats["tokens_generated"] / max(wall, 1e-9), 2),
            "prefill_buckets": sorted(eng.compiled_buckets),
            "prefill_chunks": self.stats["prefill_chunks"],
            "max_batch": eng.max_batch,
            # per-DECODE-token gaps only; the first token's wall (which
            # includes prefill) lives in ttft_ms — inline prefill no
            # longer pollutes the per-token percentiles
            "latency_ms": dist(dec_ms),
            "ttft_ms": dist(ttft_ms),
            "page_reuse_ratio": (round(hit_pages / prompt_pages, 4)
                                 if prompt_pages else 0.0),
            "prefill_tokens_saved": self.stats["prefill_tokens_saved"],
            # speculative decoding (ISSUE 18): zero-filled on
            # non-speculative runs, the sync_ms convention — consumers
            # always see the same keys.  acceptance_rate counts COMMITTED
            # draft tokens over drafted ones (the bonus is a target
            # token); target_steps_per_token is the headline — verify
            # ticks a sequence sat through per token it emitted
            # (spec_drafted / k sums active slots over ticks, so the
            # ratio is batch-width independent): 1.0 means speculation
            # bought nothing over plain decode, 1/k is the floor
            "spec": {
                "acceptance_rate": (
                    round(self.stats["spec_accepted"]
                          / self.stats["spec_drafted"], 4)
                    if self.stats["spec_drafted"] else 0.0),
                "draft_steps": self.stats["draft_steps"],
                "verify_steps": self.stats["verify_steps"],
                "target_steps_per_token": (
                    round(self.stats["spec_drafted"] / eng.spec_tokens
                          / self.stats["spec_emitted"], 4)
                    if self.stats["spec_emitted"] else 0.0),
            },
            # byte-exact page accounting: in_use sampled after every
            # admission/step x the per-page pin across both pools
            "pages": {"page_size": eng.page_size,
                      "max_pages": eng.allocator.max_pages,
                      "page_bytes": page_bytes,
                      "peak_in_use": max(occ),
                      "mean_in_use": round(float(np.mean(occ)), 2),
                      "peak_bytes": max(occ) * page_bytes,
                      "cached_pages": eng.allocator.cached_pages,
                      "cache_evictions": eng.allocator.cache_evictions,
                      "leaked": eng.allocator.in_use,
                      # the draft pool's occupancy (zero-filled when no
                      # draft is paired): joint admission means its
                      # in_use mirrors the target's while running, and
                      # leaked must end 0 just the same
                      "draft_peak_in_use": (
                          eng.draft.allocator.peak_in_use
                          if eng.draft is not None else 0),
                      "draft_leaked": (eng.draft.allocator.in_use
                                       if eng.draft is not None else 0)},
        }
        out["completions"] = [done[r.rid] for r in requests
                              if r.rid in done]
        return out
