"""Driver surface for the serving engine: ``main.py serve`` / ``run_serve``.

Self-configures the model from the checkpoint manifest metadata (the
ISSUE 7 checkpoint satellite): the user points at ``--checkpoint_dir``
and the ``--serve_*`` group; restating ``--model`` is optional and
cross-checked (mismatch is a hard error, not a silent override).

``--sanitize`` arms the serving twin of the round-loop retrace budget:
after a warmup has compiled the workload's programs (every prefill
bucket — all configured buckets under ``--serve_prefix_cache``, since a
partial hit prefills its tail at a smaller bucket — or the single
``[1, C]`` chunk program under ``--serve_prefill_chunk``, plus the
decode step), the measured run must add ZERO jaxpr traces / backend
compiles — the continuous-batching loop re-dispatches fixed programs,
nothing else.
"""

from __future__ import annotations

import json
import logging
import os
import sys
from typing import Any, Optional

import numpy as np

log = logging.getLogger(__name__)


def build_requests(cfg, vocab: int) -> list:
    """Requests from the CLI surface: ``--serve_prompt`` (comma-separated
    token ids, replicated ``--serve_requests`` times) or per-request
    synthetic prompts drawn from the served vocabulary."""
    from .scheduler import Request
    n = max(1, int(cfg.serve_requests))
    rng = np.random.default_rng(cfg.seed)
    out = []
    for i in range(n):
        if cfg.serve_prompt:
            ids = [int(t) for t in cfg.serve_prompt.split(",") if t.strip()]
        else:
            lo = min(4, cfg.parse_prompt_buckets()[0])
            plen = int(rng.integers(lo, cfg.parse_prompt_buckets()[0] + 1))
            ids = rng.integers(0, vocab, plen).tolist()
        out.append(Request(rid=i, prompt=ids,
                           max_new_tokens=cfg.serve_max_new_tokens,
                           temperature=cfg.serve_temperature))
    return out


def run_serve(cfg, requests: Optional[list] = None, *,
              model_flag_given: Optional[bool] = None) -> dict[str, Any]:
    """Load the checkpoint onto the serving mesh and serve ``requests``
    (built from the config when None).  Returns ``{"serve": telemetry,
    "completions": [...], "engine": ServeEngine}``.

    ``model_flag_given`` — whether the user EXPLICITLY passed ``--model``
    (``serve_main`` inspects argv; library callers default to "given iff
    not the dataclass default").  Explicit + metadata mismatch is a hard
    error; explicit + a metadata-less (pre-metadata) checkpoint is the
    supported fallback — the arch rebuilds from the registry name with
    num_classes recovered from the manifest leaf shapes."""
    import jax

    from .. import checkpoint as ckpt_lib
    from .engine import ServeEngine, manifest_num_classes
    from .scheduler import ContinuousBatchingScheduler

    if not cfg.checkpoint_dir:
        raise ValueError("serve needs --checkpoint_dir (the sharded "
                         "checkpoint to load)")
    path = cfg.checkpoint_dir
    if not os.path.isfile(os.path.join(path, ckpt_lib.MANIFEST)):
        resolved = ckpt_lib.latest_checkpoint(path)
        if resolved is None:
            raise FileNotFoundError(
                f"no committed checkpoint under {cfg.checkpoint_dir}")
        path = resolved
    meta = ckpt_lib.manifest_metadata(path) or {}
    if model_flag_given is None:
        # compare against the dataclass default, not a hardcoded name —
        # one source of truth if the Config default ever changes
        import dataclasses
        default_model = next(f.default
                             for f in dataclasses.fields(type(cfg))
                             if f.name == "model")
        model_flag_given = cfg.model != default_model
    if model_flag_given and meta.get("model") and cfg.model != meta["model"]:
        raise ValueError(
            f"--model {cfg.model} does not match the checkpoint's "
            f"recorded model {meta['model']!r} ({path}); drop --model — "
            "serve self-configures from the manifest metadata")
    model = None
    if not meta:
        if not model_flag_given:
            raise ValueError(
                f"checkpoint {path} carries no serve metadata (saved by "
                "a pre-metadata engine?) — restate --model gpt_*/llama_* "
                "to serve it")
        ncls = manifest_num_classes(path)
        if ncls is None:
            raise ValueError(
                f"checkpoint {path} has no tok_emb params leaf — not an "
                "autoregressive-family checkpoint, nothing to serve")
        from ..models import get_model
        kw: dict[str, Any] = dict(num_classes=ncls, scan_layers=True)
        if cfg.num_kv_heads:
            kw["num_kv_heads"] = cfg.num_kv_heads
        if cfg.num_experts:
            kw["num_experts"] = cfg.num_experts
            kw["capacity_factor"] = cfg.expert_capacity_factor
        model = get_model(cfg.model, **kw)
        log.info("serve: no manifest metadata; rebuilt %s (vocab %d from "
                 "manifest leaf shapes)", cfg.model, ncls)
    buckets = cfg.parse_prompt_buckets()
    # identical geometry for both engines of a speculative pair (the
    # pairing check enforces it): one page-table schedule, one filled
    # offset, joint admission.  max_seq grows by k — the verify program
    # writes up to position C + k
    engine_kw = dict(
        max_batch=cfg.serve_max_batch, page_size=cfg.serve_page_size,
        max_pages=cfg.serve_max_pages, prompt_buckets=buckets,
        max_seq=(buckets[-1] + cfg.serve_max_new_tokens
                 + cfg.serve_spec_tokens),
        seed=cfg.seed, prefix_cache=cfg.serve_prefix_cache,
        prefill_chunk=cfg.serve_prefill_chunk)
    draft = None
    if cfg.serve_draft_ckpt:
        # the draft self-configures from ITS manifest metadata (there is
        # only one --model flag, and it belongs to the target); every
        # pairing rejection — vocab mismatch, MoE draft — fires inside
        # the ServeEngine constructor below, before any request runs
        draft = ServeEngine.from_checkpoint(cfg.serve_draft_ckpt,
                                            **engine_kw)
    engine = ServeEngine.from_checkpoint(
        path, model=model, draft=draft,
        spec_tokens=cfg.serve_spec_tokens, **engine_kw)
    if requests is None:
        requests = build_requests(cfg, engine.spec.vocab)

    sanitize = cfg.sanitize or (
        os.environ.get("JAX_GRAFT_SANITIZE", "").strip().lower()
        not in ("", "0", "false", "off", "no"))
    counter_ok = False
    warmup_counts = None
    if sanitize:
        from ..xla_flags import (compile_event_counts,
                                 install_compile_counter)
        counter_ok = install_compile_counter()
        if counter_ok:
            from ..utils.batching import pick_bucket
            from .scheduler import Request
            mnt = min(2, cfg.serve_max_new_tokens)
            if engine.prefill_chunk:
                # chunked prefill: ONE [1, C] chunk program covers every
                # prompt length — a single longest-prompt request (>= 2
                # chunks when possible) compiles it + the decode step
                r0 = max(requests, key=lambda r: len(r.prompt),
                         default=None)
                warm = ([Request(rid=10_000_000, prompt=r0.prompt,
                                 max_new_tokens=mnt,
                                 temperature=r0.temperature)]
                        if r0 is not None else [])
            else:
                # warmup: ONE request per distinct prefill bucket
                # compiles every program the workload uses (+ the shared
                # decode step) off the measured run — warming all N
                # requests would scale startup with N for no extra
                # compile coverage.  With the prefix cache on, a
                # measured request can HIT pages and prefill only its
                # tail at a SMALLER bucket than its full length picks —
                # cover every configured bucket, not just the full-
                # length ones, so a partial hit can never retrace.
                per_bucket = {}
                for r in requests:
                    per_bucket.setdefault(
                        pick_bucket(len(r.prompt), engine.prompt_buckets),
                        r)
                warm = [Request(rid=10_000_000 + i, prompt=r.prompt,
                                max_new_tokens=min(2, r.max_new_tokens),
                                temperature=r.temperature)
                        for i, r in enumerate(per_bucket.values())]
                if engine.prefix_cache:
                    rng = np.random.default_rng(cfg.seed)
                    warm += [
                        Request(rid=11_000_000 + i,
                                prompt=rng.integers(
                                    0, engine.spec.vocab, b).tolist(),
                                max_new_tokens=mnt,
                                temperature=cfg.serve_temperature)
                        for i, b in enumerate(engine.prompt_buckets)
                        if b not in per_bucket]
            if warm:
                ContinuousBatchingScheduler(
                    engine, eos_id=cfg.serve_eos_id).run(warm)
            warmup_counts = compile_event_counts()

    sched = ContinuousBatchingScheduler(
        engine, eos_id=cfg.serve_eos_id,
        request_timeout=cfg.serve_request_timeout)
    telemetry = sched.run(requests)
    completions = telemetry.pop("completions")
    # compiled-memory observability (ISSUE 15): the serve twin of the
    # driver's results["memory"] — memory_analysis of the decode-step
    # executable + every compiled prefill bucket (no analytic resident
    # model: serve state is the params + the byte-exact page accounting
    # the scheduler already reports)
    from ..probe import memory_report
    telemetry["memory"] = memory_report(engine.memory_programs())
    telemetry["retrace_count"] = 0
    telemetry["recompile_count"] = 0
    telemetry["sanitized"] = bool(sanitize and counter_ok)
    if sanitize and counter_ok:
        from ..xla_flags import compile_event_counts
        counts = compile_event_counts()
        telemetry["retrace_count"] = (counts["traces"]
                                      - warmup_counts["traces"])
        telemetry["recompile_count"] = (counts["compiles"]
                                        - warmup_counts["compiles"])
        if telemetry["retrace_count"] or telemetry["recompile_count"]:
            raise RuntimeError(
                f"serve sanitizer: the steady-state decode run added "
                f"{telemetry['retrace_count']} trace(s) / "
                f"{telemetry['recompile_count']} compile(s) past the "
                "warmup — the loop must re-dispatch only the prefill-"
                "bucket and decode-step programs")
        log.info("serve sanitizer clean: 0 post-warmup retraces across "
                 "%d decode steps", telemetry["decode_steps"])
    return {"serve": telemetry, "completions": completions,
            "engine": engine}


def serve_main(argv=None) -> int:
    """``python -m ...main serve`` entry: serve off a checkpoint, print
    one JSON telemetry line plus per-request decoded ids."""
    from ..config import config_from_args
    args = sys.argv[1:] if argv is None else list(argv)
    cfg = config_from_args(args)
    logging.basicConfig(
        level=getattr(logging, cfg.log_level.upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    # explicit --model (even restating the dataclass default) engages the
    # mismatch check / metadata-less fallback; absent means self-configure
    given = any(a == "--model" or a.startswith("--model=") for a in args)
    results = run_serve(cfg, model_flag_given=given)
    for c in results["completions"]:
        print(f"request {c.rid}: prompt_len={c.prompt_len} "
              f"reason={c.reason} tokens={','.join(map(str, c.tokens))}")
    print("SERVE " + json.dumps(results["serve"]), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(serve_main())
