"""Page pool bookkeeping for the serving engine's paged KV cache.

The device-side cache layout and attention live in ``models/decode.py``;
this module is the HOST side: which pages belong to which sequence, and
the byte-exact occupancy accounting the telemetry/bench gate on.  Page id
0 is the trash page (``models.decode.TRASH_PAGE``): masked writes from
prefill padding and inactive decode slots land there, so the allocator
never hands it out.

PR 17 makes pages content-addressed.  A page's key is the rolling hash of
the token prefix it CLOSES (``page_prefix_keys``), so two sequences that
share a page-aligned prompt prefix resolve to the same keys and can share
physical pages by reference.  The allocator grows refcounts plus a
hash → page index: ``alloc`` hands out fresh referenced pages, ``claim``
takes an extra reference on a cache hit, ``free`` drops a reference, and
a keyed page whose refcount reaches zero is RETAINED on an LRU instead of
returning to the free list — eviction happens lazily inside ``alloc``,
oldest refcount-0 page first, only when the free list runs short.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict

import numpy as np

from ..models.decode import TRASH_PAGE


def pages_needed(total_tokens: int, page_size: int) -> int:
    """Pages a sequence reaching ``total_tokens`` positions needs."""
    return max(1, -(-int(total_tokens) // int(page_size)))


def page_prefix_keys(tokens, page_size: int) -> list[bytes]:
    """Content keys for a prompt's page-aligned prefix.

    ``keys[i]`` identifies the page holding tokens
    ``[i*page_size, (i+1)*page_size)`` — but the hash covers the WHOLE
    prefix up to and including that page (a rolling blake2b, updated one
    page at a time), so a page only matches when everything before it
    matches too.  Only full pages get a key: a partial trailing page is
    never shareable because its remaining rows will be filled by this
    sequence's own decode writes.
    """
    arr = np.ascontiguousarray(np.asarray(tokens, dtype=np.int32))
    ps = int(page_size)
    h = hashlib.blake2b(digest_size=16)
    keys: list[bytes] = []
    for i in range(arr.shape[0] // ps):
        h.update(arr[i * ps:(i + 1) * ps].tobytes())
        keys.append(h.digest())
    return keys


class PageAllocator:
    """Refcounted free-list allocator over the page pool (page 0 reserved).

    Allocation is all-or-nothing per request: a sequence gets every page
    its ``prompt + max_new_tokens`` span can reach up front, so a running
    decode can never die mid-generation from pool exhaustion — admission
    is the only place that blocks.  Freed ids return to the HEAD of the
    free list, so the recycle tests can assert an evicted sequence's
    pages are literally the next ones handed out.

    With the prefix cache in play a page has three states:

    * referenced (refcount >= 1): owned by live sequences; never evicted.
    * cached (refcount 0, has a content key): parked on the LRU, its KV
      bytes intact; a future ``claim`` resurrects it, or ``alloc``
      evicts it (oldest first) when the free list runs short.
    * free: on the free list, contents meaningless.

    ``in_use`` counts referenced pages only — cached pages are reported
    separately via ``cached_pages`` so the byte-exact occupancy identity
    ``in_use + cached_pages + free_pages == max_pages - 1`` always holds.
    """

    def __init__(self, max_pages: int):
        if max_pages < 2:
            raise ValueError(
                f"max_pages must be >= 2 (page {TRASH_PAGE} is the "
                f"reserved trash page), got {max_pages}")
        self.max_pages = int(max_pages)
        self._free = list(range(1, self.max_pages))
        self._ref: dict[int, int] = {}
        self._index: dict[bytes, int] = {}      # content key -> page
        self._key_of: dict[int, bytes] = {}     # page -> content key
        self._lru: OrderedDict[int, None] = OrderedDict()  # refcount-0 keyed
        self.peak_in_use = 0
        self.cache_evictions = 0

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def cached_pages(self) -> int:
        return len(self._lru)

    @property
    def in_use(self) -> int:
        return (self.max_pages - 1) - len(self._free) - len(self._lru)

    def refcount(self, page: int) -> int:
        return self._ref.get(page, 0)

    def alloc(self, count: int) -> list[int] | None:
        """``count`` fresh page ids (each refcount 1), or None when the
        pool cannot cover them even after evicting every refcount-0
        cached page (the caller keeps the request queued — admission
        backpressure).  The free list is consumed first; cached pages
        are evicted oldest-first only to cover the shortfall."""
        if count > len(self._free) + len(self._lru):
            return None
        take = min(count, len(self._free))
        got, self._free = self._free[:take], self._free[take:]
        while len(got) < count:
            page, _ = self._lru.popitem(last=False)
            del self._index[self._key_of.pop(page)]
            self.cache_evictions += 1
            got.append(page)
        for p in got:
            self._ref[p] = 1
        self.peak_in_use = max(self.peak_in_use, self.in_use)
        return got

    def free(self, pages: list[int]) -> None:
        """Drop one reference per page.  A page reaching refcount 0 goes
        back to the HEAD of the free list — unless it carries a content
        key, in which case it is parked on the LRU with its KV intact."""
        for p in pages:
            if p == TRASH_PAGE or p >= self.max_pages:
                raise ValueError(f"freeing invalid page id {p}")
            if self._ref.get(p, 0) < 1:
                raise ValueError(f"double free of page {p}")
        released: list[int] = []
        for p in pages:
            self._ref[p] -= 1
            if self._ref[p] == 0:
                del self._ref[p]
                if p in self._key_of:
                    self._lru[p] = None
                else:
                    released.append(p)
        self._free = released + self._free

    def claim(self, page: int) -> None:
        """Take one more reference on a page (prefix-cache hit).  Works
        on referenced pages (another live sequence shares it) and on
        cached refcount-0 pages (resurrected off the LRU)."""
        if page in self._lru:
            del self._lru[page]
            self._ref[page] = 1
        elif page in self._ref:
            self._ref[page] += 1
        else:
            raise ValueError(f"claiming page {page} that is neither "
                             f"referenced nor cached")
        self.peak_in_use = max(self.peak_in_use, self.in_use)

    def register(self, key: bytes, page: int) -> bool:
        """Publish a referenced page's content key so future admissions
        can hit it.  First writer wins: if the key is already indexed
        (a racing twin registered first) or the page already carries a
        key, this is a no-op and the page stays unkeyed / keeps its key.
        Returns True when the registration took."""
        if self._ref.get(page, 0) < 1:
            raise ValueError(
                f"registering page {page} with no live reference")
        if key in self._index or page in self._key_of:
            return False
        self._index[key] = page
        self._key_of[page] = key
        return True

    def lookup(self, keys: list[bytes]) -> list[int]:
        """Longest consecutive run of cached pages matching ``keys``
        from the start — the prompt's reusable page-aligned prefix.
        Pages are returned WITHOUT claiming them; the caller must
        ``claim`` each before any ``alloc`` could evict them."""
        hits: list[int] = []
        for k in keys:
            p = self._index.get(k)
            if p is None:
                break
            hits.append(p)
        return hits


def paired_admit(target: PageAllocator, draft: PageAllocator,
                 hits_t: list[int], hits_d: list[int], count: int
                 ) -> tuple[list[int], list[int]] | None:
    """All-or-nothing admission across a (target, draft) allocator pair
    (ISSUE 18, speculative decoding).

    A speculating sequence needs its FULL page span in BOTH pools before
    it may start: the draft writes positions ``C .. C+k-1`` and the
    verify writes ``C .. C+k`` every tick, so a pair that ran out of
    pages mid-decode in either pool would deadlock (each pool's pages
    are pinned by sequences waiting on the other).  This claims the
    prefix-cache hits and allocates the fresh pages target-first, and on
    ANY failure rolls BOTH pools back to their entry state — the request
    stays queued (admission backpressure), and a running pair can never
    wait on pages.

    ``hits_t``/``hits_d`` must cover the same token prefix (the caller
    trims both to the shorter run, so the two pools share one filled
    offset); ``count`` is the page span per pool.  Returns
    ``(target_pages, draft_pages)`` or None.
    """
    if len(hits_t) != len(hits_d):
        raise ValueError(
            f"paired admission needs hit runs of equal length (one "
            f"shared filled offset), got {len(hits_t)}/{len(hits_d)}")
    for p in hits_t:
        target.claim(p)
    fresh_t = target.alloc(count - len(hits_t))
    if fresh_t is None:
        if hits_t:
            target.free(hits_t)
        return None
    for p in hits_d:
        draft.claim(p)
    fresh_d = draft.alloc(count - len(hits_d))
    if fresh_d is None:
        if hits_d:
            draft.free(hits_d)
        target.free(hits_t + fresh_t)
        return None
    return hits_t + fresh_t, hits_d + fresh_d


def page_table_row(pages: list[int], pages_per_seq: int) -> np.ndarray:
    """A sequence's page-table row: its pages in position order, the
    unreachable tail pointed at the trash page."""
    if len(pages) > pages_per_seq:
        raise ValueError(
            f"{len(pages)} pages exceed the table width {pages_per_seq}")
    row = np.full(pages_per_seq, TRASH_PAGE, np.int32)
    row[:len(pages)] = pages
    return row
