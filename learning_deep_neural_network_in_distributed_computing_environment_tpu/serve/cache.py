"""Page pool bookkeeping for the serving engine's paged KV cache.

The device-side cache layout and attention live in ``models/decode.py``;
this module is the HOST side: which pages belong to which sequence, and
the byte-exact occupancy accounting the telemetry/bench gate on.  Page id
0 is the trash page (``models.decode.TRASH_PAGE``): masked writes from
prefill padding and inactive decode slots land there, so the allocator
never hands it out.
"""

from __future__ import annotations

import numpy as np

from ..models.decode import TRASH_PAGE


def pages_needed(total_tokens: int, page_size: int) -> int:
    """Pages a sequence reaching ``total_tokens`` positions needs."""
    return max(1, -(-int(total_tokens) // int(page_size)))


class PageAllocator:
    """Free-list allocator over the page pool (page 0 reserved).

    Allocation is all-or-nothing per request: a sequence gets every page
    its ``prompt + max_new_tokens`` span can reach up front, so a running
    decode can never die mid-generation from pool exhaustion — admission
    is the only place that blocks.  Freed ids return to the HEAD of the
    free list, so the recycle tests can assert an evicted sequence's
    pages are literally the next ones handed out."""

    def __init__(self, max_pages: int):
        if max_pages < 2:
            raise ValueError(
                f"max_pages must be >= 2 (page {TRASH_PAGE} is the "
                f"reserved trash page), got {max_pages}")
        self.max_pages = int(max_pages)
        self._free = list(range(1, self.max_pages))
        self.peak_in_use = 0

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return (self.max_pages - 1) - len(self._free)

    def alloc(self, count: int) -> list[int] | None:
        """``count`` page ids, or None when the pool cannot cover them
        (the caller keeps the request queued — admission backpressure)."""
        if count > len(self._free):
            return None
        got, self._free = self._free[:count], self._free[count:]
        self.peak_in_use = max(self.peak_in_use, self.in_use)
        return got

    def free(self, pages: list[int]) -> None:
        for p in pages:
            if p == TRASH_PAGE or p >= self.max_pages:
                raise ValueError(f"freeing invalid page id {p}")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
        self._free = list(pages) + self._free


def page_table_row(pages: list[int], pages_per_seq: int) -> np.ndarray:
    """A sequence's page-table row: its pages in position order, the
    unreachable tail pointed at the trash page."""
    if len(pages) > pages_per_seq:
        raise ValueError(
            f"{len(pages)} pages exceed the table width {pages_per_seq}")
    row = np.full(pages_per_seq, TRASH_PAGE, np.int32)
    row[:len(pages)] = pages
    return row
