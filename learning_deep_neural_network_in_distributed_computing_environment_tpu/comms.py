"""The synchronization matrix: one pytree-level ``aggregate`` for all 12 DP
sync modes.

The reference splits this across three ``communication.py`` flavors with
asymmetric interfaces (model-level for all-reduce,
``Balanced All-Reduce/communication.py:4-31``; tensor-level with the trainer
iterating parameters for ring/double-ring,
``Balanced Ring/communication.py:5-62``, ``Balanced Double-Ring/
communication.py:5-77``) over two backends (torch.distributed, mpi4py).
Here it is a single pure function on pytrees, executed *inside*
``shard_map``/``jit`` with XLA collectives over the mesh's data axis:

- ``allreduce`` -> ``lax.pmean`` / ``lax.psum`` (NCCL/gloo all_reduce
  equivalent, rides ICI);
- ``ring``      -> ``lax.ppermute`` shift-by-1 (the reference's 1-neighbor
  Isend/Irecv gossip, ``Balanced Ring/communication.py:19-25``);
- ``double_ring`` -> two ``ppermute`` shifts (1 and 2) (2-neighbor gossip,
  ``Balanced Double-Ring/communication.py:5-40``).

Semantics notes (SURVEY.md 2.5):

- "Ring" is one gossip exchange per sync — NOT a reduce-scatter/all-gather
  ring all-reduce; consensus emerges over repeated global epochs.  That is
  the observable behavior being reproduced.
- The reference's ring gossip silently no-ops on GPU (2.5.2); the behavior
  matched here is the correct CPU path.
- ``weighted`` all-reduce (2.5.10): ``new = w*own + (1-w)*(sum-own)/(N-1)``
  — the self-exclusive peer mean blended with the own value.  The reference
  divides by zero when N == 1; here N == 1 returns the own value unchanged
  (every topology is the identity on a single worker).
"""

from __future__ import annotations

import math
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .compat import axis_size, optimization_barrier, psum_scatter, shard_map
from .mesh import DATA_AXIS, SLICE_AXIS

PyTree = Any

TOPOLOGIES = ("allreduce", "ring", "double_ring")
HOWS = ("equal", "weighted")
BYS = ("gradients", "weights")

# Wire hops per gossip round: ring sends each bucket once (shift-1);
# double-ring sends it twice (shift-1 and shift-2, issued concurrently).
GOSSIP_HOPS = {"ring": 1, "double_ring": 2}

# Default sharded-sync bucket size.  Buckets batch many small parameter
# leaves into one collective so the per-collective launch overhead
# amortizes, while staying small enough that reduce-scatter/all-gather of
# one bucket pipelines against the pack/unpack of the next under XLA's
# scheduler.
DEFAULT_BUCKET_BYTES = 4 << 20


def ring_neighbors(n: int, shift: int = 1) -> list[tuple[int, int]]:
    """The gossip ring's ppermute permutation for ``n`` workers: rank i
    sends to ``(i + shift) % n``.  Derived from the AXIS SIZE alone —
    which is what makes the ring elastic (ISSUE 8): a membership change
    rebuilds the round program on the resized mesh and this table is
    re-derived for the new ``n``, so the ring always closes over exactly
    the live workers and a departed rank can never strand a neighbor
    waiting on it.  Exposed for the elastic tests/telemetry to assert
    that property (a valid table is a single cycle covering 0..n-1 when
    gcd(n, shift) == 1)."""
    return [(i, (i + shift) % n) for i in range(n)]


def _shift(x: jnp.ndarray, n: int, shift: int, axis_name: str) -> jnp.ndarray:
    """Receive the value of ``rank - shift`` (mod n): each rank i sends to
    ``i + shift``, matching the reference's Isend(to rank+1)/Irecv(from
    rank-1) gossip pattern."""
    return lax.ppermute(x, axis_name, ring_neighbors(n, shift))


def aggregate(tree: PyTree, *, how: str = "equal",
              topology: str = "allreduce", local_weight: float = 0.5,
              axis_name: str = DATA_AXIS, poison=None):
    """Aggregate a per-worker pytree across the data axis.

    Must be called inside ``shard_map`` (or any context where ``axis_name``
    is bound).  Works on parameter or gradient pytrees alike — the
    gradients/weights choice ("aggregation_by") is the caller's, matching
    the reference's dispatch (``Balanced All-Reduce/trainer.py:141-150``).

    ``poison`` (ISSUE 12 integrity screen): when not None, this worker's
    contribution is screened sender-side (poisoned/non-finite values
    enter the collectives as exact zeros) and every blend renormalizes
    over the valid contributions — the dense twin of the fast engines'
    screen, so the quarantine semantics are identical whichever sync
    path a chaos run resolves.  Clean rounds select the unscreened
    arithmetic (bitwise-identical).  The return is then
    ``(aggregated, ok)`` with ``ok`` this worker's fp32 0/1 flag.
    """
    if how not in HOWS:
        raise ValueError(f"how must be one of {HOWS}, got {how!r}")
    if topology not in TOPOLOGIES:
        raise ValueError(f"topology must be one of {TOPOLOGIES}, got {topology!r}")
    n = axis_size(axis_name)
    if n == 1:
        if poison is not None:
            ok1 = _contribution_ok(
                poison, jax.tree_util.tree_leaves(tree), None)
            return tree, ok1.astype(jnp.float32)
        return tree
    w = local_weight
    ok = okf = valid = None
    if poison is not None:
        ok = _contribution_ok(poison, jax.tree_util.tree_leaves(tree),
                              None)
        okf = ok.astype(jnp.float32)
        valid = jnp.maximum(lax.psum(okf, axis_name), 1.0)
        all_ok = valid >= n
        ok1f = _shift(okf, n, 1, axis_name)
        ok2f = (_shift(okf, n, 2, axis_name)
                if topology == "double_ring" else None)

    def per_leaf(x: jnp.ndarray) -> jnp.ndarray:
        xs = x if ok is None else jnp.where(ok, x, jnp.zeros_like(x))
        if topology == "allreduce":
            if how == "equal":
                out = lax.pmean(x, axis_name)
                if ok is None:
                    return out
                return jnp.where(all_ok, out,
                                 lax.psum(xs, axis_name) / valid)
            total = lax.psum(xs, axis_name)
            peers_mean = (total - x) / (n - 1)
            out = w * x + (1.0 - w) * peers_mean
            if ok is None:
                return out
            peers = jnp.maximum(valid - 1.0, 1.0)
            screened = jnp.where(
                ok, w * x + (1.0 - w) * (total - xs) / peers,
                total / valid)
            return jnp.where(all_ok, out, screened)
        if topology == "ring":
            r = _shift(xs, n, 1, axis_name)
            out = (x + r) / 2.0 if how == "equal" \
                else w * x + (1.0 - w) * r
            if ok is None:
                return out
            r_ok = ok1f > 0
            if how == "equal":
                cnt = okf + ok1f
                screened = jnp.where(
                    cnt > 0, (xs + r) / jnp.maximum(cnt, 1.0), x)
            else:
                screened = jnp.where(
                    jnp.logical_and(ok, r_ok), out,
                    jnp.where(r_ok, r, x))
            return jnp.where(jnp.logical_and(ok, r_ok), out, screened)
        # double_ring: blend with the two predecessors
        r1 = _shift(xs, n, 1, axis_name)
        r2 = _shift(xs, n, 2, axis_name)
        out = (x + r1 + r2) / 3.0 if how == "equal" \
            else w * x + ((1.0 - w) / 2.0) * (r1 + r2)
        if ok is None:
            return out
        every = jnp.logical_and(ok, jnp.logical_and(ok1f > 0, ok2f > 0))
        cnt = okf + ok1f + ok2f
        if how == "equal":
            screened = jnp.where(
                cnt > 0, (xs + r1 + r2) / jnp.maximum(cnt, 1.0), x)
        else:
            pc = ok1f + ok2f
            pmean = (r1 + r2) / jnp.maximum(pc, 1.0)
            screened = jnp.where(
                ok, jnp.where(pc > 0, w * x + (1.0 - w) * pmean, x),
                jnp.where(pc > 0, pmean, x))
        return jnp.where(every, out, screened)

    agg = jax.tree_util.tree_map(per_leaf, tree)
    if poison is not None:
        return agg, okf
    return agg


# --------------------------------------------------------------------------
# Simulated many-worker aggregation (ISSUE 14): the flat-primitives
# reference path as PURE STACKED MATH — no mesh, no axis names
# --------------------------------------------------------------------------
# ``aggregate`` above runs inside shard_map with one real device per
# worker; ``aggregate_sim`` runs the SAME arithmetic on worker-stacked
# [N, ...] leaves living on a single chip (the scenario-lab engine,
# sim.py).  The two are bitwise-identical in fp32 because every collective
# has an exact stacked twin on XLA:
#
# - psum/pmean accumulate in RANK ORDER (a sequential left-fold over the
#   participants) — ``sim_fold`` reproduces that fold with a lax.scan over
#   the leading axis (a reassociating ``jnp.sum`` does NOT match, which is
#   why the fold is spelled out);
# - ppermute's receive-from-(rank - shift) is ``jnp.roll(x, shift,
#   axis=0)`` — pure data movement, trivially bitwise;
# - the blends are elementwise and identical by construction.
#
# The ``ok`` mask is the dense path's poison/validity screen reused as the
# scenario surface: client sampling and worker dropout exclude rows from
# the blend exactly the way a quarantined contribution is excluded, and a
# mask of all-ones selects the unscreened VALUES (the same all_ok-select
# construction ``aggregate`` uses; equal blends bitwise, weighted blends
# to fp32 FMA-contraction tolerance — the masked program's extra branches
# change LLVM's fusion context).  The parity gate never sees a mask at
# all: scenario knobs at their defaults compile none of this machinery
# (sim.SimEngine.scenario_on).


def sim_fold(x: jnp.ndarray) -> jnp.ndarray:
    """Sequential left-fold of a stacked [N, ...] array over its leading
    axis, in row order — the stacked twin of ``lax.psum`` (XLA's
    all-reduce accumulates participants in rank order, so ``x[0] + x[1] +
    ... + x[N-1]`` reproduces it bitwise; asserted against the real
    collective in tests/test_sim.py)."""
    if x.shape[0] == 1:
        return x[0]
    def add(acc, row):
        return acc + row, None
    acc, _ = lax.scan(add, x[0], x[1:])
    return acc


def _sim_rows(v: jnp.ndarray, leaf: jnp.ndarray) -> jnp.ndarray:
    """A per-worker [N] vector broadcast against a stacked [N, ...] leaf."""
    return v.reshape(v.shape[0], *([1] * (leaf.ndim - 1)))


def sim_wire_bytes(tree: PyTree, n: int, *, topology: str = "allreduce",
                   wire_dtype=None) -> int:
    """Per-worker bytes ONE simulated worker's sync WOULD move per round
    — the ``results["sim"]`` accounting of the fabric the simulation
    stands in for.  Per-leaf wire model: every leaf rides the fabric once
    per hop (gossip: ``GOSSIP_HOPS``; allreduce: one injection, the dense
    accounting), in ``wire_dtype`` when the simulated wire is compressed.
    fp32 equals ``sync_wire_bytes(mode="dense")`` exactly."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves or n <= 1:
        return 0
    hops = GOSSIP_HOPS.get(topology, 1)
    item = lambda x: (jnp.dtype(wire_dtype).itemsize
                      if wire_dtype is not None
                      else jnp.dtype(x.dtype).itemsize)
    return hops * sum(_leaf_size(x) * item(x) for x in leaves)


def aggregate_sim(tree: PyTree, *, how: str = "equal",
                  topology: str = "allreduce", local_weight: float = 0.5,
                  ok: jnp.ndarray | None = None, wire_dtype=None,
                  residual: PyTree | None = None
                  ) -> tuple[PyTree, PyTree | None]:
    """``aggregate`` on a worker-STACKED pytree: every leaf is [N, ...]
    and the collectives are stacked math on the leading axis (no mesh).

    fp32 with no mask is BITWISE the dense reference path (the module
    note above says why); that is the simulator's correctness gate.

    ``ok`` — optional [N] per-worker contribution-validity mask (bool or
    0/1 float): masked-out rows are excluded from every blend and the
    survivors renormalize, mirroring ``aggregate``'s poison screen
    row-for-row (an all-ones mask selects the unscreened values via the
    all_ok construction).  The scenario lab drives it with the
    client-sampling x dropout draw.

    ``wire_dtype`` + ``residual`` — the simulated compressed wire
    (bfloat16/int8) with single-stage error feedback: each worker's
    TRANSMITTED payload is encoded per worker row (int8: per-row
    symmetric max/127 scale), every value received from the fabric is
    the decoded fp32 payload, own values blend exactly, and the residual
    carries each worker's own transmission rounding into the next round
    — the gossip engine's wire model (comms.gossip_sync), applied
    per-leaf and extended to the allreduce topology (where the fabric's
    reduce likewise sees only wire payloads).  The bucketed engines'
    per-bucket scales/two-stage EF are engine artifacts the simulation
    does not reproduce; compressed parity is semantic, not bitwise
    (docs/ARCHITECTURE.md).  Returns ``(aggregated, new_residual)`` —
    ``new_residual`` is None when no error feedback is armed.
    """
    if how not in HOWS:
        raise ValueError(f"how must be one of {HOWS}, got {how!r}")
    if topology not in TOPOLOGIES:
        raise ValueError(
            f"topology must be one of {TOPOLOGIES}, got {topology!r}")
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return tree, residual
    n = int(leaves[0].shape[0])
    compressed = (wire_dtype is not None
                  and jnp.dtype(wire_dtype) != jnp.dtype(jnp.float32))
    ef = compressed and residual is not None
    if n == 1:
        return tree, residual
    w = local_weight
    okf = okb = valid = all_ok = ok1f = ok2f = None
    if ok is not None:
        okf = ok.astype(jnp.float32)
        okb = okf > 0
        valid = jnp.maximum(sim_fold(okf), 1.0)
        all_ok = valid >= n
        ok1f = jnp.roll(okf, 1, axis=0)
        if topology == "double_ring":
            ok2f = jnp.roll(okf, 2, axis=0)
    if compressed:
        _, encode = _wire_codec(jnp.dtype(wire_dtype))
        enc_rows = jax.vmap(lambda r: encode(r)[1])   # decoded payloads

    def per_leaf(x: jnp.ndarray, res):
        x32 = x.astype(jnp.float32)
        contrib = x32 + res if ef else x32
        if compressed:
            dec = enc_rows(contrib)
            new_res = contrib - dec if ef else None
        else:
            dec, new_res = contrib, None
        rows = lambda v: _sim_rows(v, x)
        xs = dec if okb is None else jnp.where(rows(okb), dec,
                                               jnp.zeros_like(dec))
        if topology == "allreduce":
            if how == "equal":
                out = jnp.broadcast_to(sim_fold(dec) / n, x.shape)
                if okb is None:
                    return out, new_res
                screened = jnp.broadcast_to(sim_fold(xs) / valid, x.shape)
                return jnp.where(all_ok, out, screened), new_res
            total = sim_fold(xs)
            peers_mean = (total - dec) / (n - 1)
            out = w * x + (1.0 - w) * peers_mean
            if okb is None:
                return out, new_res
            peers = jnp.maximum(valid - 1.0, 1.0)
            screened = jnp.where(
                rows(okb), w * x + (1.0 - w) * (total - xs) / peers,
                jnp.broadcast_to(total / valid, x.shape))
            return jnp.where(all_ok, out, screened), new_res
        if topology == "ring":
            r = jnp.roll(xs, 1, axis=0)
            out = (x + r) / 2.0 if how == "equal" else w * x + (1.0 - w) * r
            if okb is None:
                return out, new_res
            r_ok = rows(ok1f > 0)
            both = jnp.logical_and(rows(okb), r_ok)
            if how == "equal":
                cnt = rows(okf + ok1f)
                screened = jnp.where(
                    cnt > 0, (xs + r) / jnp.maximum(cnt, 1.0), x)
            else:
                screened = jnp.where(both, out, jnp.where(r_ok, r, x))
            return jnp.where(both, out, screened), new_res
        # double_ring: blend with the two predecessors
        r1 = jnp.roll(xs, 1, axis=0)
        r2 = jnp.roll(xs, 2, axis=0)
        out = (x + r1 + r2) / 3.0 if how == "equal" \
            else w * x + ((1.0 - w) / 2.0) * (r1 + r2)
        if okb is None:
            return out, new_res
        every = jnp.logical_and(rows(okb), jnp.logical_and(
            rows(ok1f > 0), rows(ok2f > 0)))
        cnt = rows(okf + ok1f + ok2f)
        if how == "equal":
            screened = jnp.where(
                cnt > 0, (xs + r1 + r2) / jnp.maximum(cnt, 1.0), x)
        else:
            pc = rows(ok1f + ok2f)
            pmean = (r1 + r2) / jnp.maximum(pc, 1.0)
            screened = jnp.where(
                rows(okb), jnp.where(pc > 0, w * x + (1.0 - w) * pmean, x),
                jnp.where(pc > 0, pmean, x))
        return jnp.where(every, out, screened), new_res

    flat, treedef = jax.tree_util.tree_flatten(tree)
    res_flat = (jax.tree_util.tree_leaves(residual) if ef
                else [None] * len(flat))
    outs = [per_leaf(x, r) for x, r in zip(flat, res_flat)]
    agg = jax.tree_util.tree_unflatten(treedef, [o[0] for o in outs])
    new_residual = (jax.tree_util.tree_unflatten(
        treedef, [o[1] for o in outs]) if ef else None)
    return agg, new_residual


def _wire_codec(wdt):
    """Wire codec for one bucket's dtype: ``(quantized, encode)``.

    ``encode(x32)`` -> (wire payload, fp32 decode of the payload,
    per-bucket fp32 scale or None).  bf16 is a plain downcast; int8 is
    symmetric round-to-nearest on a max|x|/127 grid with the sender's
    fp32 scale riding next to the payload."""
    quantized = wdt == jnp.dtype(jnp.int8)

    def encode(x32):
        if not quantized:
            y = x32.astype(wdt)
            return y, y.astype(jnp.float32), None
        scale = jnp.maximum(jnp.max(jnp.abs(x32)) / 127.0,
                            jnp.float32(1e-30))
        q = jnp.clip(jnp.round(x32 / scale), -127.0, 127.0).astype(
            jnp.int8)
        return q, q.astype(jnp.float32) * scale, scale

    return quantized, encode


# --------------------------------------------------------------------------
# Semi-synchronous delivery blend (ISSUE 16)
# --------------------------------------------------------------------------
# Under ``--sync_staleness K`` the standalone sync program no longer hands
# its blend straight back as the next round's params — round R+1 has
# already dispatched off the PRE-sync params T_R by the time sync R
# finishes.  Instead the sync emits the consensus DELTA
#
#     D_R = blend(T_R) - T_R
#
# and the engine folds it into whatever params exist when the delta is
# delivered (the entry of round R+K+1):  params' = params + D_R.  The two
# halves below are the whole contract:
#
# * additivity is what makes the schedule composable — K deltas in flight
#   fold in any params state without re-reading T_R (whose buffers round
#   R+1's donated round program has already consumed);
# * at K=0 the pair is exact identity in fp32 IF the engine skips it
#   entirely (x + (b - x) == b does NOT hold bitwise in floating point),
#   which is why the K=0 path never routes through these helpers — the
#   bitwise gate is structural, not arithmetic;
# * EF residuals compose because the residual update is a function of the
#   sync's OWN wire rounding, computed inside the sync program against
#   T_R — the delta just carries the post-EF blend's displacement;
# * weighted (straggler-proportional) blends compose for the same reason:
#   the blend weights are resolved inside the sync program, the delta is
#   its output displacement;
# * scatter-resident params do NOT compose (delivery needs full
#   replicated trees on both sides) — config rejects / auto-demotes.


def stale_delta(blended: PyTree, base: PyTree) -> PyTree:
    """Consensus displacement ``blended - base`` per leaf, in the leaf's
    own dtype — the payload a stale sync program returns instead of the
    blend itself (``base`` is the pre-sync params snapshot the sync was
    computed from)."""
    return jax.tree_util.tree_map(lambda b, t: b - t, blended, base)


def deliver_stale(params: PyTree, delta: PyTree) -> PyTree:
    """Fold a stale consensus delta into freshly trained params:
    ``params + delta`` per leaf.  Pure elementwise math — the engine jits
    it with both inputs donated (the delta dies here; the params buffer
    is replaced by the delivered tree)."""
    return jax.tree_util.tree_map(lambda p, d: p + d, params, delta)


# --------------------------------------------------------------------------
# Sharded round sync: flatten-and-bucket -> reduce-scatter -> scale the
# 1/N shard -> all-gather (ISSUE 2 tentpole)
# --------------------------------------------------------------------------
# The dense path above all-reduces every fully-replicated parameter, so each
# worker's per-round wire traffic is the whole model, and the scale/average
# arithmetic runs on all S elements per worker.  The reduce-scatter form
# ("Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
# Training", PAPERS.md) assigns each worker ownership of a contiguous 1/N
# shard of every bucket: the scatter sums each shard on its owner, the
# average (or straggler-weighted blend) runs on S/N elements, and the
# all-gather redistributes the result.  Per-worker send traffic is
# 2(N-1)/N x S x wire_bytes per bucket (the two phases each move (N-1)/N of
# the bucket) versus the dense path's full replicated buffer per collective,
# and — unlike the dense form — the reduction work itself parallelizes
# across the worker axis.  In fp32 the result is BIT-IDENTICAL to the dense
# all-reduce: both sum the same N addends through the same XLA reduction
# and divide by N (asserted by tests/test_sync.py).


class _Bucket(NamedTuple):
    """One contiguous 1D collective segment of the flattened pytree."""

    dtype: Any                 # numpy dtype of every leaf in the bucket
    padded: int                # total elements incl. zero padding; % n == 0
    items: tuple               # ((leaf_index, offset, size), ...)


def _leaf_size(x) -> int:
    return int(math.prod(x.shape)) if x.shape else 1


def bucket_plan(leaves, n: int, bucket_bytes: int = DEFAULT_BUCKET_BYTES
                ) -> list[_Bucket]:
    """Greedy bucketing of flattened leaves into ~``bucket_bytes`` segments.

    Leaves are taken in pytree-flatten order and grouped by dtype (a bucket
    is one collective; mixed dtypes would force a common wire type).  A
    bucket closes once it reaches the target byte size; a single leaf larger
    than the target gets its own bucket (leaves are never split, so every
    leaf occupies one contiguous segment).  Each bucket is padded with zeros
    to a multiple of ``n`` so the reduce-scatter tiles evenly; padding
    participates in the collectives (it sums to zero) and is dropped at
    unpack, so the round trip is exact.
    """
    groups: dict[Any, list[int]] = {}
    for i, leaf in enumerate(leaves):
        groups.setdefault(jnp.dtype(leaf.dtype), []).append(i)
    out: list[_Bucket] = []
    for dtype, idxs in groups.items():
        target = max(1, int(bucket_bytes) // max(1, dtype.itemsize))
        items: list[tuple] = []
        offset = 0
        for i in idxs:
            size = _leaf_size(leaves[i])
            items.append((i, offset, size))
            offset += size
            if offset >= target:
                out.append(_Bucket(dtype, -(-offset // n) * n, tuple(items)))
                items, offset = [], 0
        if items:
            out.append(_Bucket(dtype, -(-offset // n) * n, tuple(items)))
    return out


def sync_wire_bytes(tree: PyTree, n: int, *, mode: str = "sharded",
                    wire_dtype=None,
                    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                    topology: str = "allreduce") -> int:
    """Per-worker bytes SENT by one round sync of ``tree`` (shapes only —
    leaves may be arrays or ShapeDtypeStructs).

    Accounting model (one number per worker, per round):

    - ``dense``: every collective carries the full replicated buffer — each
      worker injects S x 4 bytes (the dense path is always fp32), once per
      gossip hop for ring/double-ring topologies;
    - ``sharded``: reduce-scatter sends (N-1)/N of each padded bucket and
      all-gather sends its (N-1)/N again, in the wire dtype —
      2(N-1)/N x padded x itemsize per bucket (int8's per-bucket fp32
      scale adds 8 bytes per worker per bucket — noise next to the
      payload; excluded from the accounting);
    - ``gossip``: each hop ppermutes every packed bucket once in the wire
      dtype — hops x filled x itemsize per bucket (no padding: ppermute
      has no tiling constraint; the int8 scale scalar is again excluded).
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves or n <= 1:
        return 0
    hops = GOSSIP_HOPS.get(topology, 1)
    if mode == "dense":
        return hops * sum(_leaf_size(x) * jnp.dtype(x.dtype).itemsize
                          for x in leaves)
    wire_item = lambda b: (jnp.dtype(wire_dtype).itemsize
                           if wire_dtype is not None else b.dtype.itemsize)
    if mode == "gossip":
        return sum(hops * sum(size for (_i, _off, size) in b.items)
                   * wire_item(b)
                   for b in bucket_plan(leaves, n, bucket_bytes))
    return sum(2 * (n - 1) * (b.padded // n) * wire_item(b)
               for b in bucket_plan(leaves, n, bucket_bytes))


def sharded_sync(tree: PyTree, *, how: str = "equal",
                 local_weight: float = 0.5, axis_name: str = DATA_AXIS,
                 wire_dtype=None, residual: PyTree | None = None,
                 bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                 opt_placement: str = "sharded",
                 residency: str = "replicated"
                 ) -> tuple[PyTree, PyTree | None]:
    """Sharded all-reduce aggregation of a per-worker pytree.

    Must be called inside ``shard_map`` (``axis_name`` bound), like
    ``aggregate``.  Semantics match ``aggregate(topology="allreduce")``:
    ``equal`` is the cross-worker mean, ``weighted`` the self-exclusive
    peer-mean blend — in fp32 both are bit-identical to the dense path.

    ``wire_dtype`` compresses the two collective phases: bfloat16 halves
    the wire bytes (plain downcast); int8 quarters them via symmetric
    per-bucket quantization — each worker scales its bucket by
    ``max|x| / 127`` (an fp32 scalar riding a tiny all-gather next to the
    int8 payload), rounds to the nearest int8 step, and receivers
    dequantize with the sender's scale before the fp32 accumulation, so
    the sum is exact in fp32 given the quantized contributions.
    ``residual`` enables error feedback for the compression:
    each worker carries (a) the fp32 rounding error of its own compressed
    contribution and (b) n x the rounding error of the gathered mean over
    the shard it owns, both re-injected through next round's sum — so
    quantization error accumulates in the residual instead of in the
    parameters, and sub-quantum parameter movement still gets through.
    Returns ``(synced_tree, new_residual)`` — ``new_residual`` is
    ``residual`` unchanged (possibly None) when no error feedback is
    active.

    ``opt_placement`` places the apply stage (the blend scaling between
    the two collective phases — ISSUE 9): ``"sharded"`` scales on the
    1/N psum_scatter shard so only post-update values ride the
    all_gather; ``"replicated"`` gathers the raw shard sums and scales
    the full buffer on every worker — the ZeRO-1 paper's A/B twin,
    bit-identical in fp32 (elementwise scaling commutes with the gather
    bit-for-bit).  Compressed wires require the sharded placement: the
    gathered payload IS the encoded mean, so the scale must run before
    the encode on the shard (config.py validates).
    """
    synced, new_res, _ = sharded_opt_sync(
        tree, how=how, local_weight=local_weight, axis_name=axis_name,
        wire_dtype=wire_dtype, residual=residual,
        bucket_bytes=bucket_bytes, opt_placement=opt_placement,
        residency=residency)
    return synced, new_res


# Round-optimizer tracker (ISSUE 9): torch.optim.Adam moment defaults,
# matching the engine's per-batch Adam (train.py scale_by_adam betas).
ROUND_ADAM_B1 = 0.9
ROUND_ADAM_B2 = 0.999

OPT_PLACEMENTS = ("replicated", "sharded")


def _bucket_name(i: int) -> str:
    return f"b{i:04d}"


def round_opt_init(per_worker_tree: PyTree, n: int, *, placement: str,
                   bucket_bytes: int = DEFAULT_BUCKET_BYTES) -> dict:
    """Zero-initialized round-optimizer moments for ``per_worker_tree``
    (leaves may be arrays or ShapeDtypeStructs — per-worker shapes, no
    worker axis), worker-STACKED for the engine state.

    Layout per bucket of the sync engine's plan: ``sharded`` stores each
    worker's OWN 1/N shard row — ``[n, padded // n]`` — so per-worker
    resident bytes are 1/N of the moment vector; ``replicated`` stores
    the full padded vector on every worker — ``[n, padded]`` — the
    N-copies baseline the ZeRO-1 scheme removes.  Both track the same
    worker-invariant quantity (Adam moments of the cross-worker mean of
    the aggregated tree), so rows of the replicated layout are
    identical and the sharded layout is its exact row-partition
    (bitwise-gated in tests/test_opt_placement.py)."""
    if placement not in OPT_PLACEMENTS:
        raise ValueError(
            f"placement must be one of {OPT_PLACEMENTS}, got {placement!r}")
    leaves = jax.tree_util.tree_leaves(per_worker_tree)
    out: dict = {}
    for i, b in enumerate(bucket_plan(leaves, n, bucket_bytes)):
        row = b.padded // n if placement == "sharded" else b.padded
        out[_bucket_name(i)] = {
            "mu": jnp.zeros((n, row), jnp.float32),
            "nu": jnp.zeros((n, row), jnp.float32)}
    return out


def round_opt_relayout(tracker: dict, per_worker_tree: PyTree, n_new: int,
                       *, placement: str,
                       bucket_bytes: int = DEFAULT_BUCKET_BYTES) -> dict:
    """Re-layout a HOST round-optimizer tracker for a new worker count
    (elastic membership change, ISSUE 9 satellite).

    The tracked quantity is worker-invariant, so a membership change
    never edits rows the way per-worker state does: the moment VECTOR
    is reconstructed (concatenate the shard rows / take the replicated
    row), re-padded for the new bucket tiling (padding positions carry
    exactly-zero moments — the padded mean is zero every round — so
    trimming or extending the pad is exact), and re-split.  ``tracker``
    layout must match ``placement``; returns numpy arrays."""
    import numpy as np

    leaves = jax.tree_util.tree_leaves(per_worker_tree)
    plan = bucket_plan(leaves, max(1, n_new), bucket_bytes)
    out: dict = {}
    for i, b in enumerate(plan):
        name = _bucket_name(i)
        if name not in tracker:
            raise ValueError(
                f"round-optimizer tracker has no bucket {name} "
                f"({len(tracker)} buckets vs plan {len(plan)})")
        filled = sum(size for (_i, _off, size) in b.items)
        row_new = b.padded // n_new if placement == "sharded" else b.padded
        out[name] = {}
        for m in ("mu", "nu"):
            arr = np.asarray(tracker[name][m])
            vec = (arr.reshape(-1) if placement == "sharded"
                   else arr[0])
            if vec.size < filled:
                raise ValueError(
                    f"round-optimizer bucket {name}/{m} carries "
                    f"{vec.size} elements but the plan needs {filled}")
            vec = vec[:filled]
            pad = (n_new * row_new if placement == "sharded"
                   else b.padded) - filled
            if pad:
                vec = np.concatenate([vec, np.zeros(pad, vec.dtype)])
            if placement == "sharded":
                out[name][m] = vec.reshape(n_new, row_new)
            else:
                out[name][m] = np.broadcast_to(
                    vec, (n_new, b.padded)).copy()
    return out


# ----------------------------------------------------------------------
# Scatter-resident consensus params (ISSUE 11): the between-round
# parameter layout of the round-loop FSDP scheme.  One bucket of the
# sync engine's plan maps to one [n, padded // n] array whose row w is
# worker w's contiguous 1/N shard of the packed consensus vector — the
# exact psum_scatter output layout, which is what lets the sync END at
# the scatter (apply on the shard, no trailing all_gather) and the NEXT
# round's entry gather reconstruct the full tree bit-for-bit.  Padding
# positions carry exactly-zero values (the padded mean is zero every
# round), so re-tiling for a new worker count is exact — the same
# invariant the round-optimizer tracker relies on.
# ----------------------------------------------------------------------

PARAM_RESIDENCIES = ("replicated", "resident")


def resident_from_tree(per_worker_tree: PyTree, n: int, *,
                       bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                       n_rows: int | None = None) -> dict:
    """HOST: pack one worker's CONSENSUS params into the resident layout.

    ``per_worker_tree`` holds the shared consensus values (equal-blend
    weights mode: every worker's post-sync params are identical, so any
    row is the consensus).  Returns ``{bucket: [n, padded // n]}`` numpy
    arrays — row w is worker w's shard.  Used at engine init (broadcast
    init IS a consensus) and by the cross-residency checkpoint/elastic
    re-layouts.

    ``n_rows`` (ISSUE 13): the hierarchical mesh stacks S slices of W
    workers, so the worker axis carries ``n_rows = S x n`` rows while
    the bucket tiling stays per-INNER-shard (``padded // n``); the one
    consensus is tiled across the slice groups (a broadcast init, or a
    global consensus restored from a flat checkpoint, IS every slice's
    consensus)."""
    import numpy as np

    rows = n_rows or n
    if rows % n:
        raise ValueError(
            f"resident layout rows ({rows}) must be a multiple of the "
            f"inner shard count ({n})")
    leaves = jax.tree_util.tree_leaves(per_worker_tree)
    out: dict = {}
    for i, b in enumerate(bucket_plan(leaves, n, bucket_bytes)):
        parts = [np.asarray(leaves[j]).reshape(-1).astype(b.dtype)
                 for (j, _off, _size) in b.items]
        vec = np.concatenate(parts) if len(parts) > 1 else parts[0]
        pad = b.padded - vec.size
        if pad:
            vec = np.concatenate([vec, np.zeros(pad, vec.dtype)])
        shards = vec.reshape(n, b.padded // n)
        out[_bucket_name(i)] = (shards if rows == n
                                else np.tile(shards, (rows // n, 1)))
    return out


def resident_to_tree(resident: dict, per_worker_template: PyTree, *,
                     bucket_bytes: int = DEFAULT_BUCKET_BYTES) -> PyTree:
    """HOST: unpack a resident layout back into the consensus tree.

    The host twin of the round-entry device gather — concatenating the
    shard rows IS the all_gather (pure data movement, bit-exact), so
    final-eval / checkpoint-relayout consumers reconstruct exactly the
    tree the round program would have gathered.  The worker count is
    read off the rows."""
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(per_worker_template)
    n = None
    for arr in resident.values():
        n = int(np.shape(arr)[0])
        break
    if not n:
        raise ValueError("resident params layout is empty")
    out: list = [None] * len(leaves)
    plan = bucket_plan(leaves, n, bucket_bytes)
    for i, b in enumerate(plan):
        name = _bucket_name(i)
        if name not in resident:
            raise ValueError(
                f"resident params layout has no bucket {name} "
                f"({len(resident)} buckets vs plan {len(plan)})")
        arr = np.asarray(resident[name])
        if arr.shape != (n, b.padded // n):
            raise ValueError(
                f"resident params bucket {name} has shape {arr.shape}, "
                f"expected {(n, b.padded // n)} (sync_bucket_mb or "
                "worker count changed since the state was built?)")
        vec = arr.reshape(-1)
        for (j, off, size) in b.items:
            out[j] = vec[off:off + size].reshape(
                np.shape(leaves[j])).astype(np.dtype(leaves[j].dtype))
    return jax.tree_util.tree_unflatten(treedef, out)


def resident_relayout(resident: dict, per_worker_template: PyTree,
                      n_new: int, *,
                      bucket_bytes: int = DEFAULT_BUCKET_BYTES) -> dict:
    """Re-tile a HOST resident params layout for a new worker count
    (elastic membership change, ISSUE 11).

    The consensus vector is worker-invariant, so the re-layout mirrors
    ``round_opt_relayout``: reconstruct the vector from the shard rows,
    re-pad for the new bucket tiling (pad positions carry exactly-zero
    values — the padded mean is zero every round — so trimming or
    extending the pad is exact), and re-split."""
    import numpy as np

    leaves = jax.tree_util.tree_leaves(per_worker_template)
    plan = bucket_plan(leaves, max(1, n_new), bucket_bytes)
    out: dict = {}
    for i, b in enumerate(plan):
        name = _bucket_name(i)
        if name not in resident:
            raise ValueError(
                f"resident params layout has no bucket {name} "
                f"({len(resident)} buckets vs plan {len(plan)})")
        vec = np.asarray(resident[name]).reshape(-1)
        filled = sum(size for (_j, _off, size) in b.items)
        if vec.size < filled:
            raise ValueError(
                f"resident params bucket {name} carries {vec.size} "
                f"elements but the plan needs {filled}")
        vec = vec[:filled]
        pad = b.padded - filled
        if pad:
            vec = np.concatenate([vec, np.zeros(pad, vec.dtype)])
        out[name] = vec.reshape(n_new, b.padded // n_new)
    return out


def buddy_wire_bytes(tree: PyTree, n: int, *, wire_dtype=None,
                     bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                     params: bool = True, tracker: bool = False,
                     ef: bool = False) -> int:
    """Per-worker bytes SENT by the ISSUE 12 buddy-redundancy hop —
    ONE extra ppermute per bucket at scatter exit, carrying exactly the
    shard-resident rows: the ``padded/N`` resident params row in the
    WIRE dtype (``params``), the two fp32 tracker rows (``tracker``),
    and the fp32 residual own-span (``ef``).  Zero when nothing is
    shard-resident (n <= 1 or an empty tree) — the accounting twin of
    ``sync_wire_bytes``, asserted in tests/test_sync.py."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves or n <= 1:
        return 0
    total = 0
    for b in bucket_plan(leaves, n, bucket_bytes):
        row = b.padded // n
        wire_item = (jnp.dtype(wire_dtype).itemsize
                     if wire_dtype is not None else b.dtype.itemsize)
        if params:
            total += row * wire_item
        if ef:
            total += row * 4
        if tracker:
            total += 2 * row * 4
    return total


def derive_buddy(per_worker_template: PyTree, n: int, *,
                 bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                 params_resident: dict | None = None,
                 round_opt: dict | None = None,
                 residual: PyTree | None = None,
                 opt_placement: str = "sharded") -> dict | None:
    """HOST: the buddy layout implied by a state's shard-resident rows
    (ISSUE 12) — ``buddy[bucket][comp][w]`` is worker ``(w-1) % n``'s
    component row, exactly what the device hop's ring ppermute delivers
    (``ring_neighbors(n, 1)``: every worker holds its PREDECESSOR's
    spans).

    Used wherever the state is (re)built on host and the device copy
    does not exist yet: engine init, checkpoint restore (buddy rows are
    STRIPPED from checkpoints — they are derivable, and saving them
    would couple the manifest layout to the redundancy flag), and the
    elastic re-tile.  ``residual`` contributes each worker's OWN-span
    slice of its packed fp32 residual (the span carrying the stage-2
    consensus correction).  Returns None when nothing is
    shard-resident."""
    import numpy as np

    if n < 2:
        return None
    leaves = jax.tree_util.tree_leaves(per_worker_template)
    if not leaves:
        return None
    res_rows = (None if residual is None
                else [np.asarray(x) for x in
                      jax.tree_util.tree_leaves(residual)])
    tracker_on = round_opt is not None and opt_placement == "sharded"
    if params_resident is None and res_rows is None and not tracker_on:
        return None
    out: dict = {}
    for i, b in enumerate(bucket_plan(leaves, n, bucket_bytes)):
        name = _bucket_name(i)
        row = b.padded // n
        bud: dict = {}
        if params_resident is not None:
            arr = np.asarray(params_resident[name])
            if arr.shape != (n, row):
                raise ValueError(
                    f"resident params bucket {name} has shape "
                    f"{arr.shape}, expected {(n, row)}")
            bud["params"] = np.roll(arr, 1, axis=0).copy()
        if res_rows is not None:
            mat = np.zeros((n, b.padded), np.float32)
            for (j, off, size) in b.items:
                mat[:, off:off + size] = res_rows[j].reshape(n, -1)
            spans = np.stack([mat[w, w * row:(w + 1) * row]
                              for w in range(n)])
            bud["res"] = np.roll(spans, 1, axis=0).copy()
        if tracker_on:
            for m in ("mu", "nu"):
                arr = np.asarray(round_opt[name][m])
                if arr.shape != (n, row):
                    raise ValueError(
                        f"round-opt bucket {name}/{m} has shape "
                        f"{arr.shape}, expected {(n, row)} (buddy "
                        "redundancy covers the SHARDED placement)")
                bud[m] = np.roll(arr, 1, axis=0).copy()
        out[name] = bud
    return out


def buddy_restore_rows(host_state_parts: dict, buddy: dict,
                       lost_positions: list[int],
                       per_worker_template: PyTree, *,
                       bucket_bytes: int = DEFAULT_BUCKET_BYTES) -> dict:
    """HOST: reconstruct CRASHED workers' shard-resident rows from their
    buddy copies (ISSUE 12 recovery).

    ``host_state_parts`` maps component name -> layout:
    ``{"params_resident": {bucket: [n, row]},
       "round_opt": {bucket: {"mu"/"nu": [n, row]}},
       "residual": params-shaped [n, ...] pytree}`` (absent components
    omitted).  For each lost position ``p`` the holder is ``(p+1) % n``
    — its buddy row IS the lost worker's span, by the ring hop's
    construction.  A holder that is itself lost is a DOUBLE FAULT and
    raises (the caller falls back to the newest committed checkpoint).
    The residual component is FOLDED into the holder's own residual at
    the lost span's positions (the pending stage-2 consensus correction
    survives the crash instead of vanishing with the row); resident
    params / tracker rows are patched in place.  Returns the patched
    ``host_state_parts`` (new arrays, inputs untouched)."""
    import numpy as np

    leaves = jax.tree_util.tree_leaves(per_worker_template)
    resident = host_state_parts.get("params_resident")
    round_opt = host_state_parts.get("round_opt")
    residual = host_state_parts.get("residual")
    n = None
    for comp in (resident, round_opt):
        if comp:
            first = next(iter(comp.values()))
            arr = first.get("mu") if isinstance(first, dict) else first
            n = int(np.shape(arr)[0])
            break
    if n is None and residual is not None:
        n = int(np.shape(jax.tree_util.tree_leaves(residual)[0])[0])
    if n is None:
        raise ValueError("nothing shard-resident to restore")
    lost = sorted(set(int(p) for p in lost_positions))
    for p in lost:
        if not 0 <= p < n:
            raise ValueError(f"lost position {p} outside worker axis {n}")
        holder = (p + 1) % n
        if holder in lost:
            raise ValueError(
                f"double fault: crashed worker at position {p} and its "
                f"buddy at position {holder} are both lost — the span "
                "exists nowhere in memory (fall back to the newest "
                "committed checkpoint)")
    plan = bucket_plan(leaves, n, bucket_bytes)
    out = dict(host_state_parts)
    if resident is not None:
        patched = {k: np.asarray(v).copy() for k, v in resident.items()}
        for i, b in enumerate(plan):
            name = _bucket_name(i)
            for p in lost:
                patched[name][p] = np.asarray(
                    buddy[name]["params"])[(p + 1) % n]
        out["params_resident"] = patched
    if round_opt is not None and any(
            "mu" in bud for bud in buddy.values()):
        patched = {k: {m: np.asarray(v).copy() for m, v in d.items()}
                   for k, d in round_opt.items()}
        for i, b in enumerate(plan):
            name = _bucket_name(i)
            for p in lost:
                for m in ("mu", "nu"):
                    patched[name][m][p] = np.asarray(
                        buddy[name][m])[(p + 1) % n]
        out["round_opt"] = patched
    if residual is not None and any(
            "res" in bud for bud in buddy.values()):
        res_leaves, res_def = jax.tree_util.tree_flatten(residual)
        res_leaves = [np.asarray(x).copy() for x in res_leaves]
        for i, b in enumerate(plan):
            name = _bucket_name(i)
            row = b.padded // n
            for p in lost:
                holder = (p + 1) % n
                span = np.asarray(buddy[name]["res"])[holder]
                lo, hi = p * row, (p + 1) * row
                for (j, off, size) in b.items:
                    a, z = max(off, lo), min(off + size, hi)
                    if a >= z:
                        continue
                    flat = res_leaves[j][holder].reshape(-1)
                    flat[a - off:z - off] += span[a - lo:z - lo]
        out["residual"] = jax.tree_util.tree_unflatten(res_def,
                                                       res_leaves)
    return out


def resident_gather(shards: dict, per_worker_template: PyTree, *,
                    axis_name: str = DATA_AXIS,
                    bucket_bytes: int = DEFAULT_BUCKET_BYTES) -> PyTree:
    """The round-entry gather (ISSUE 11 tentpole): inside ``shard_map``,
    all_gather each bucket's resident shard row over the worker axis and
    unpack the full consensus tree.

    ``shards`` holds this worker's squeezed per-worker rows
    (``[padded // n]`` per bucket); the gathered full buffers are
    transient compute-scope values — XLA frees them with the program, so
    the RESIDENT state never exceeds 1/N per worker.  Bit-exactness: the
    gather concatenates the same shard values the sync's trailing
    all_gather used to move, so entry-gather(exit-scatter) reproduces
    the replicated twin's tree bit-for-bit."""
    leaves, treedef = jax.tree_util.tree_flatten(per_worker_template)
    n = axis_size(axis_name)
    out: list = [None] * len(leaves)
    plan = bucket_plan(leaves, n, bucket_bytes)
    for i, b in enumerate(plan):
        name = _bucket_name(i)
        if name not in shards:
            raise ValueError(
                f"resident params layout has no bucket {name} "
                f"({len(shards)} buckets vs plan {len(plan)})")
        row = shards[name]
        if tuple(row.shape) != (b.padded // n,):
            raise ValueError(
                f"resident params bucket {name} row has shape "
                f"{tuple(row.shape)}, expected {(b.padded // n,)} "
                "(sync_bucket_mb or worker count changed?)")
        full = lax.all_gather(row, axis_name, tiled=True)
        for (j, off, size) in b.items:
            leaf = leaves[j]
            out[j] = full[off:off + size].reshape(leaf.shape).astype(
                leaf.dtype)
    return jax.tree_util.tree_unflatten(treedef, out)


def make_resident_gather(mesh, per_worker_template: PyTree, *,
                         bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                         donate: bool = False):
    """Jitted stand-alone round-entry gather over a worker-stacked
    resident layout (tests / bench A/Bs): takes ``{bucket:
    [n, padded // n]}`` and returns the worker-stacked full tree
    ([n, ...] leaves).  ``donate=True`` donates the resident input —
    the engine's enter program shape."""
    from jax.sharding import PartitionSpec as P

    from .mesh import stack_axes

    # slice-aware (ISSUE 13): on a hierarchical mesh the rows stack over
    # (slice, data) and the gather still runs over the inner ``data``
    # axis only — each slice reconstructs ITS OWN consensus
    spec = P(stack_axes(mesh))

    def _gather(shards):
        def inner(sh):
            sq = jax.tree_util.tree_map(lambda x: x[0], sh)
            tree = resident_gather(sq, per_worker_template,
                                   bucket_bytes=bucket_bytes)
            return jax.tree_util.tree_map(lambda x: x[None], tree)
        return shard_map(inner, mesh=mesh, in_specs=(spec,),
                         out_specs=spec)(shards)

    return jax.jit(_gather, donate_argnums=(0,) if donate else ())


def _contribution_ok(poison, leaves, res_leaves):
    """Per-worker validity of this worker's sync contribution (ISSUE 12
    integrity screen): not poisoned AND every leaf (plus the EF residual
    it folds in) entirely finite.  A scalar bool, computed inside
    shard_map."""
    ok = jnp.logical_not(jnp.asarray(poison, bool).reshape(()))
    for x in leaves:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(
            x.astype(jnp.float32))))
    if res_leaves is not None:
        for x in res_leaves:
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(
                x.astype(jnp.float32))))
    return ok


def sharded_opt_sync(tree: PyTree, *, how: str = "equal",
                     local_weight: float = 0.5, axis_name: str = DATA_AXIS,
                     wire_dtype=None, residual: PyTree | None = None,
                     bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                     opt_placement: str = "sharded",
                     tracker: dict | None = None,
                     residency: str = "replicated",
                     buddy: bool = False,
                     poison=None
                     ) -> tuple:
    """``sharded_sync`` with the full apply-stage surface (ISSUE 9):
    optimizer placement plus the round-level Adam moment tracker.

    ``residency`` (ISSUE 11) places the sync's OUTPUT: ``"replicated"``
    all_gathers the post-apply values home (the full synced tree on
    every worker, as always); ``"resident"`` ENDS the program at the
    scatter — the first return value is then the ``{bucket:
    [padded // n]}`` resident shard layout (this worker's decoded
    post-apply shard), the trailing all_gather is gone, and the next
    round's ``resident_gather`` reconstructs the full tree bit-for-bit
    at entry.  Resident output requires the equal blend on the sharded
    placement: the weighted blend's own-term is irreducibly per-worker
    and a replicated apply has no shard-side output (config.py resolves
    the combinations eagerly).

    ``tracker`` (per-worker slices of a ``round_opt_init`` tree, i.e.
    already squeezed inside shard_map) updates Adam moments of the
    CROSS-WORKER MEAN of ``tree`` — the worker-invariant aggregated
    quantity, which is what makes the moments shardable at all.  Under
    ``opt_placement="sharded"`` each worker updates only the moment
    slice of the bucket shard it owns (1/N state, 1/N FLOPs); under
    ``"replicated"`` every worker updates the full vector from the
    gathered sums — N identical copies of the same arithmetic, kept as
    the bitwise A/B twin.

    ``buddy`` (ISSUE 12) fuses ONE extra per-bucket ppermute hop at
    scatter exit: each worker also sends its post-apply resident shard
    row (the ``residency="resident"`` output — the WIRE-dtype payload
    plus its scale, decoded buddy-side, so the copy is bitwise the
    owner's row), the sharded tracker's new mu/nu rows, and (under EF)
    the owned span of its fp32 residual to its ring SUCCESSOR
    (``ring_neighbors(n, 1)``) — so every 1/N span of shard-resident
    state lives on exactly two workers and an abrupt worker loss is
    recoverable from the buddy copy.  Pure data movement: every other
    output is bitwise-unchanged.

    ``poison`` (ISSUE 12 integrity screen) is this worker's scalar
    poison flag: when not None, each worker's contribution is screened
    sender-side (poisoned or non-finite contributions enter the
    collectives as exact zeros) and the blend renormalizes over the
    count of valid workers — the quarantined worker receives the
    survivors' consensus.  When every worker is valid the outputs are
    bitwise-identical to the unscreened program (the screened branch is
    selected away by a ``where`` on the full-count predicate).

    Returns ``(synced, new_residual, new_tracker)``, with the buddy
    layout appended when ``buddy`` and this worker's validity flag (an
    fp32 0/1 scalar) appended when ``poison is not None`` — callers
    unpack exactly what they armed."""
    if how not in HOWS:
        raise ValueError(f"how must be one of {HOWS}, got {how!r}")
    if opt_placement not in OPT_PLACEMENTS:
        raise ValueError(
            f"opt_placement must be one of {OPT_PLACEMENTS}, got "
            f"{opt_placement!r}")
    if residency not in PARAM_RESIDENCIES:
        raise ValueError(
            f"residency must be one of {PARAM_RESIDENCIES}, got "
            f"{residency!r}")
    resident = residency == "resident"
    if resident and (how != "equal" or opt_placement != "sharded"):
        raise ValueError(
            "a scatter-resident output requires the equal blend on the "
            "sharded placement: the weighted own-term blend is "
            "irreducibly per-worker and a replicated apply produces no "
            f"shard-side output (got how={how!r}, "
            f"opt_placement={opt_placement!r}; config.py resolves these "
            "combinations to the replicated residency)")
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    n = axis_size(axis_name)
    if buddy and n < 2:
        raise ValueError(
            "buddy redundancy needs a worker axis of size >= 2 (a lone "
            "worker has no ring successor to back its shard up on)")
    if not leaves or n == 1:
        if resident:
            raise ValueError(
                "a scatter-resident output needs a worker axis of size "
                ">= 2 and a non-empty tree (nothing to shard)")
        if poison is not None:
            ok1 = _contribution_ok(poison, leaves, None)
            return tree, residual, tracker, ok1.astype(jnp.float32)
        return tree, residual, tracker
    res_leaves = None
    if residual is not None:
        res_leaves = jax.tree_util.tree_leaves(residual)
        if len(res_leaves) != len(leaves):
            raise ValueError(
                "residual must mirror the synced tree: "
                f"{len(res_leaves)} leaves vs {len(leaves)}")
    ok = okf = valid = None
    if poison is not None:
        ok = _contribution_ok(poison, leaves, res_leaves)
        okf = ok.astype(jnp.float32)
        valid = jnp.maximum(lax.psum(okf, axis_name), 1.0)
        all_ok = valid >= n   # every contribution finite -> the
        #                       unscreened arithmetic is selected below,
        #                       so clean rounds stay bitwise-identical
    compressed_wire = (wire_dtype is not None
                       and jnp.dtype(wire_dtype) != jnp.dtype(jnp.float32))
    if compressed_wire and opt_placement != "sharded":
        raise ValueError(
            "a compressed wire quantizes the gathered mean, which forces "
            "the scale-then-encode apply onto the shard: opt_placement "
            f"must be 'sharded', got {opt_placement!r}")
    new_tracker: dict | None = {} if tracker is not None else None
    resident_out: dict = {}
    buddy_out: dict = {}
    out: list = [None] * len(leaves)
    new_res: list | None = [None] * len(leaves) if res_leaves is not None \
        else None
    w = local_weight
    for bi, b in enumerate(bucket_plan(leaves, n, bucket_bytes)):
        parts, filled = [], 0
        for (i, _off, size) in b.items:
            x = leaves[i].astype(jnp.float32).reshape(-1)
            if res_leaves is not None:
                x = x + res_leaves[i].astype(jnp.float32).reshape(-1)
            parts.append(x)
            filled += size
        if b.padded > filled:
            parts.append(jnp.zeros((b.padded - filled,), jnp.float32))
        buf = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        if ok is not None:
            # sender-side quarantine: a poisoned/non-finite contribution
            # enters the collectives as exact zeros (where, not a
            # multiply — NaN payloads must not leak through 0 * NaN);
            # the worker's EF residual resets with it (err below is then
            # exactly zero, a fresh EF start after the bad round)
            buf = jnp.where(ok, buf, jnp.zeros_like(buf))
        wdt = jnp.dtype(wire_dtype) if wire_dtype is not None else b.dtype
        quantized, encode = _wire_codec(wdt)

        def gather_decoded(payload, scale):
            """all_gather the wire payload (+ its per-worker scale for
            int8) and decode each worker's segment with ITS scale."""
            full = lax.all_gather(payload, axis_name, tiled=True).astype(
                jnp.float32)
            if not quantized:
                return full
            scales = lax.all_gather(scale, axis_name)           # [n]
            return (full.reshape(n, -1) * scales[:, None]).reshape(-1)

        sent, sent32, sent_scale = encode(buf)
        if new_res is not None:
            # error feedback: what wire rounding dropped from THIS
            # worker's contribution rides into next round's
            # pre-compression sum
            err = buf - sent32
        compressed = wdt != jnp.dtype(jnp.float32)
        if compressed:
            # compressed reduce-scatter as all-to-all of wire-dtype shard
            # slices + LOCAL fp32 accumulation.  psum_scatter on bf16
            # would accumulate IN bf16, where one worker's grid-crossing
            # update can vanish into the sum's coarser grid (at sum ~ n|p|
            # the quantum is ~n x larger) — an error no residual can see,
            # because the fp32 truth never exists anywhere.  (int8 cannot
            # ride psum_scatter at all: integer accumulation would wrap
            # and each worker has its own scale.)  Wire traffic is
            # identical to reduce-scatter: each worker sends (n-1)/n of
            # the bucket.
            pieces = lax.all_to_all(sent.reshape(n, b.padded // n),
                                    axis_name, 0, 0)
            if quantized:
                scales = lax.all_gather(sent_scale, axis_name)   # [n]
                shard32 = jnp.sum(pieces.astype(jnp.float32)
                                  * scales[:, None], axis=0)
            else:
                shard32 = jnp.sum(pieces.astype(jnp.float32), axis=0)
        else:
            shard32 = psum_scatter(sent, axis_name, scatter_dimension=0,
                                   tiled=True).astype(jnp.float32)
        track32 = None   # fp32 mean the round-optimizer tracker consumes
        if how == "equal":
            if opt_placement == "replicated" and not compressed:
                # replicated apply (the ZeRO-1 paper's baseline, kept as
                # the A/B twin): gather the RAW shard sums and scale the
                # full buffer on EVERY worker — N copies of the same
                # arithmetic.  Elementwise scaling commutes with the
                # gather bit-for-bit, so the result is bitwise-identical
                # to the shard-resident apply below.
                gathered = lax.all_gather(shard32, axis_name,
                                          tiled=True).astype(jnp.float32)
                full = gathered / n
                if ok is not None:
                    # quarantine renormalization: the screened sum holds
                    # only the valid contributions, so the mean divides
                    # by their count; the full-count predicate keeps
                    # clean rounds on the literal-n division (bitwise)
                    full = jnp.where(all_ok, full, gathered / valid)
                track32 = full
            else:
                # shard-resident apply: the scale (and, compressed, the
                # mean's wire encode + stage-2 EF) runs on the 1/N shard;
                # only the post-update values ride the all_gather home
                mean32 = shard32 / n
                if ok is not None:
                    mean32 = jnp.where(all_ok, mean32, shard32 / valid)
                mean, mean32_dec, mean_scale = encode(mean32)
                if new_res is not None and compressed:
                    # second-stage error feedback: the gathered mean is
                    # ALSO wire-quantized, and that rounding recurs every
                    # round on the same grid (sub-quantum drift of the
                    # mean would stall without it).  The shard's owner
                    # folds n x the rounding error into its own residual
                    # at the shard's positions — next round's mean
                    # divides the n back out, delivering the correction
                    # one round delayed.
                    e2 = mean32 - mean32_dec
                    err = err + lax.dynamic_update_slice(
                        jnp.zeros((b.padded,), jnp.float32), n * e2,
                        (lax.axis_index(axis_name) * (b.padded // n),))
                if resident:
                    # ISSUE 11: the program ENDS at the scatter — the
                    # decoded post-apply shard IS the between-round
                    # state, and next round's entry gather concatenates
                    # exactly these values (what gather_decoded would
                    # have produced), so the handoff is bit-exact even
                    # on a compressed wire
                    resident_out[_bucket_name(bi)] = mean32_dec
                    full = None
                    if buddy:
                        # ISSUE 12 buddy hop, fused at scatter exit: the
                        # WIRE-dtype payload (+ its scale) rides one
                        # ppermute to the ring successor and decodes
                        # there — the buddy copy is bitwise the owner's
                        # resident row (decode is a pure function of the
                        # permuted payload), at wire-dtype hop cost
                        nb = ring_neighbors(n, 1)
                        brow = lax.ppermute(mean, axis_name, nb)
                        if quantized:
                            bsc = lax.ppermute(mean_scale, axis_name, nb)
                            b32 = brow.astype(jnp.float32) * bsc
                        else:
                            b32 = brow.astype(jnp.float32)
                        bud = {"params": b32}
                        if new_res is not None:
                            # the owned span of the fp32 residual carries
                            # the stage-2 consensus correction (n x e2 at
                            # this worker's scatter positions) — state no
                            # other worker holds; back it up alongside
                            row = b.padded // n
                            span = lax.dynamic_slice_in_dim(
                                err, lax.axis_index(axis_name) * row, row)
                            bud["res"] = lax.ppermute(span, axis_name, nb)
                        buddy_out[_bucket_name(bi)] = bud
                else:
                    full = gather_decoded(mean, mean_scale)
                track32 = mean32
        else:
            # weighted needs the per-worker OWN value elementwise, so the
            # gather redistributes the raw sum and the blend runs locally;
            # own is the compressed own contribution — the value the peers
            # actually received.  The own-blend is irreducibly per-worker
            # (each worker's output is a different function of its own
            # value) and stays replicated under BOTH placements — the
            # shardable part of the weighted apply is the reduction and
            # the tracker's mean scale (docs/ARCHITECTURE.md).
            tq, _tq32, tq_scale = encode(shard32)
            total = gather_decoded(tq, tq_scale)
            own = sent32
            full = w * own + (1.0 - w) * (total - own) / (n - 1)
            track32 = (shard32 / n if opt_placement == "sharded"
                       else total / n)
            if ok is not None:
                # quarantine under the weighted blend: a valid worker's
                # peer mean renormalizes over the valid peer count (its
                # own screened term is already in total); a quarantined
                # worker adopts the valid consensus mean — its own value
                # is the garbage being quarantined
                peers = jnp.maximum(valid - 1.0, 1.0)
                screened = jnp.where(
                    ok, w * own + (1.0 - w) * (total - own) / peers,
                    total / valid)
                full = jnp.where(all_ok, full, screened)
                track32 = jnp.where(
                    all_ok, track32,
                    (shard32 if opt_placement == "sharded" else total)
                    / valid)
        if new_tracker is not None:
            # round-level Adam moments of the cross-worker mean — the
            # worker-invariant quantity whose state the sharded placement
            # stores at 1/N per worker (the replicated layout updates the
            # identical full vector N times over)
            name = _bucket_name(bi)
            if name not in tracker:
                raise ValueError(
                    f"round-optimizer tracker has no bucket {name} "
                    f"(bucket plan / tracker layout mismatch)")
            mu, nu = tracker[name]["mu"], tracker[name]["nu"]
            expect = b.padded // n if opt_placement == "sharded" \
                else b.padded
            if mu.shape[-1] != expect:
                raise ValueError(
                    f"round-optimizer bucket {name} row has "
                    f"{mu.shape[-1]} elements, expected {expect} for "
                    f"opt_placement={opt_placement!r} (sync_bucket_mb "
                    "or placement changed since the state was built?)")
            g = track32
            new_tracker[name] = {
                "mu": ROUND_ADAM_B1 * mu + (1.0 - ROUND_ADAM_B1) * g,
                "nu": ROUND_ADAM_B2 * nu + (1.0 - ROUND_ADAM_B2) * (g * g)}
            if buddy and opt_placement == "sharded":
                # ISSUE 12: the sharded tracker rows are 1/N state no
                # other worker holds — one fp32 ppermute each backs the
                # fresh moments up on the ring successor
                nb = ring_neighbors(n, 1)
                buddy_out.setdefault(name, {}).update(
                    mu=lax.ppermute(new_tracker[name]["mu"], axis_name,
                                    nb),
                    nu=lax.ppermute(new_tracker[name]["nu"], axis_name,
                                    nb))
        for (i, off, size) in b.items:
            leaf = leaves[i]
            if full is not None:
                out[i] = full[off:off + size].reshape(leaf.shape).astype(
                    leaf.dtype)
            if new_res is not None:
                new_res[i] = err[off:off + size].reshape(leaf.shape)
    res_out = (residual if new_res is None
               else jax.tree_util.tree_unflatten(treedef, new_res))
    first = (resident_out if resident
             else jax.tree_util.tree_unflatten(treedef, out))
    ret: list = [first, res_out, new_tracker]
    if buddy:
        ret.append(buddy_out)
    if poison is not None:
        ret.append(okf)
    return tuple(ret)


# --------------------------------------------------------------------------
# Bucketed gossip round sync: flatten-and-bucket -> per-bucket ppermute
# shifts -> local fp32 blend (ISSUE 4 tentpole)
# --------------------------------------------------------------------------
# The legacy ``aggregate`` path runs ring/double-ring gossip leaf by leaf:
# every parameter tensor is its own ppermute (dozens of sub-MB collectives
# per round, each paying launch latency), always dense, always fp32.  The
# gossip engine reuses the sharded-sync bucketer: the pytree flattens into
# ~bucket_bytes fp32 segments, each HOP moves one contiguous buffer per
# bucket (collective count ~ buckets x hops, not leaves x hops), and the
# blend arithmetic runs once on the packed buffer.  Unlike the
# reduce-scatter engine the buckets need NO padding — ppermute moves the
# buffer wholesale, nothing tiles by worker count.
#
# In fp32 the bucketed round is BIT-IDENTICAL to the dense path: the blend
# evaluates the exact dense expressions ((x + r) / 2, (x + r1 + r2) / 3,
# and their local_weight forms) elementwise on the same values — packing
# and slicing move bytes, never round them.
#
# Compressed wire (bf16 / int8) casts only the PERMUTED payload: the own
# term of the blend stays full-precision fp32, so per-round error is one
# wire rounding of the neighbor term.  Error feedback carries the fp32
# rounding error of the worker's OWN transmission in its residual and
# re-injects it into the next round's payload (send = x + e), so repeated
# gossip rounds still contract to the dense consensus fixed point: what
# this round's quantization dropped, the neighbors receive next round.
# (Gossip needs only this single EF stage — there is no shared quantized
# mean whose rounding recurs on a fixed grid, unlike the sharded engine's
# second stage.)


def gossip_sync(tree: PyTree, *, topology: str, how: str = "equal",
                local_weight: float = 0.5, axis_name: str = DATA_AXIS,
                wire_dtype=None, residual: PyTree | None = None,
                bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                poison=None) -> tuple:
    """One bucketed ring/double-ring gossip round over the data axis.

    Must be called inside ``shard_map`` (``axis_name`` bound), like
    ``aggregate``.  Semantics match ``aggregate(topology=...)`` per
    element: ``ring`` blends with the shift-1 predecessor, ``double_ring``
    with the shift-1 and shift-2 predecessors; ``equal`` is the uniform
    blend, ``weighted`` the ``local_weight`` own/peer blend (the
    Disbalanced variants' straggler weighting).  In fp32 the result is
    bit-identical to the dense per-leaf path.

    ``wire_dtype`` compresses the permuted payload only (bf16 downcast or
    per-bucket-scale int8, the scale ppermuted alongside); the local term
    and the blend accumulate in fp32.  ``residual`` enables error
    feedback: each worker transmits ``encode(x + residual)`` and carries
    the fp32 rounding error of that transmission forward, so repeated
    rounds converge to the dense fixed point within EF tolerance instead
    of plateauing at the wire quantum.  Returns
    ``(blended_tree, new_residual)``; ``new_residual`` is ``residual``
    unchanged (possibly None) when no error feedback is active.

    ``poison`` (ISSUE 12 integrity screen): when not None, each
    worker's TRANSMISSION is screened sender-side (poisoned/non-finite
    payloads travel as exact zeros, the validity flag ppermutes
    alongside) and the blend renormalizes over the valid terms — a
    worker whose predecessor is quarantined keeps its own value, a
    quarantined worker adopts its valid neighbor terms.  Clean rounds
    select the unscreened arithmetic (bitwise-identical).  The return
    gains this worker's fp32 0/1 validity flag:
    ``(blended, new_residual, ok)``.

    Double-ring issues the shift-1 and shift-2 exchanges back to back and
    fences them with ``optimization_barrier`` before either blend term is
    consumed, so the shift-2 hop rides the wire while the shift-1 blend
    computes (the PR 2 two-rounds-in-flight trick, inside one program).
    """
    if topology not in GOSSIP_HOPS:
        raise ValueError(
            f"topology must be one of {tuple(GOSSIP_HOPS)}, got "
            f"{topology!r} (allreduce rides sharded_sync)")
    if how not in HOWS:
        raise ValueError(f"how must be one of {HOWS}, got {how!r}")
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    n = axis_size(axis_name)
    if not leaves or n == 1:
        if poison is not None:
            ok1 = _contribution_ok(poison, leaves, None)
            return tree, residual, ok1.astype(jnp.float32)
        return tree, residual
    res_leaves = None
    if residual is not None:
        res_leaves = jax.tree_util.tree_leaves(residual)
        if len(res_leaves) != len(leaves):
            raise ValueError(
                "residual must mirror the synced tree: "
                f"{len(res_leaves)} leaves vs {len(leaves)}")
    ok = okf = None
    if poison is not None:
        ok = _contribution_ok(poison, leaves, res_leaves)
        okf = ok.astype(jnp.float32)
    out: list = [None] * len(leaves)
    new_res: list | None = [None] * len(leaves) if res_leaves is not None \
        else None
    w = local_weight
    for b in bucket_plan(leaves, n, bucket_bytes):
        # pack the bucket; no zero padding — ppermute has no tiling
        # constraint, so the wire carries exactly the filled elements
        own_parts = [leaves[i].astype(jnp.float32).reshape(-1)
                     for (i, _off, _size) in b.items]
        buf = jnp.concatenate(own_parts) if len(own_parts) > 1 \
            else own_parts[0]
        send = buf
        if res_leaves is not None:
            res_parts = [res_leaves[i].astype(jnp.float32).reshape(-1)
                         for (i, _off, _size) in b.items]
            send = buf + (jnp.concatenate(res_parts) if len(res_parts) > 1
                          else res_parts[0])
        if ok is not None:
            # sender-side quarantine: a poisoned/non-finite transmission
            # travels as exact zeros, and the validity flag ppermutes
            # alongside so receivers renormalize their blend terms
            send = jnp.where(ok, send, jnp.zeros_like(send))
        wdt = jnp.dtype(wire_dtype) if wire_dtype is not None else b.dtype
        quantized, encode = _wire_codec(wdt)
        sent, sent32, sent_scale = encode(send)
        if new_res is not None:
            # error feedback: what wire rounding dropped from THIS
            # worker's transmission rides into next round's payload —
            # the neighbors receive the correction one round delayed
            err = send - sent32

        def hop(shift):
            """Permuted (payload, scale, validity) from the shift-th
            predecessor; int8 payloads travel with their sender's fp32
            scale."""
            r = _shift(sent, n, shift, axis_name)
            s = _shift(sent_scale, n, shift, axis_name) if quantized \
                else None
            o = _shift(okf, n, shift, axis_name) if okf is not None \
                else None
            return r, s, o

        def dec(trip):
            r, s, _o = trip
            r32 = r.astype(jnp.float32)
            return r32 * s if s is not None else r32

        if topology == "ring":
            h1 = hop(1)
            r1 = dec(h1)
            blended = (buf + r1) / 2.0 if how == "equal" \
                else w * buf + (1.0 - w) * r1
            if ok is not None:
                r1ok = h1[2] > 0
                safe_buf = jnp.where(ok, buf, jnp.zeros_like(buf))
                if how == "equal":
                    num = safe_buf + jnp.where(r1ok, r1,
                                               jnp.zeros_like(r1))
                    cnt = okf + h1[2]
                    screened = jnp.where(cnt > 0,
                                         num / jnp.maximum(cnt, 1.0), buf)
                else:
                    screened = jnp.where(
                        jnp.logical_and(ok, r1ok),
                        w * buf + (1.0 - w) * r1,
                        jnp.where(r1ok, r1, buf))
                blended = jnp.where(jnp.logical_and(ok, r1ok), blended,
                                    screened)
        else:
            # both shifts issued before either blend term is consumed:
            # the barrier keeps XLA from serializing the shift-2
            # collective behind the shift-1 blend, so the second hop's
            # wire time overlaps the first hop's arithmetic
            h1, h2 = optimization_barrier((hop(1), hop(2)))
            r1, r2 = dec(h1), dec(h2)
            # exact dense expressions (comms.aggregate per_leaf) for the
            # fp32 bit-identity guarantee
            blended = (buf + r1 + r2) / 3.0 if how == "equal" \
                else w * buf + ((1.0 - w) / 2.0) * (r1 + r2)
            if ok is not None:
                r1ok, r2ok = h1[2] > 0, h2[2] > 0
                every = jnp.logical_and(ok, jnp.logical_and(r1ok, r2ok))
                safe_buf = jnp.where(ok, buf, jnp.zeros_like(buf))
                num = (safe_buf
                       + jnp.where(r1ok, r1, jnp.zeros_like(r1))
                       + jnp.where(r2ok, r2, jnp.zeros_like(r2)))
                cnt = okf + h1[2] + h2[2]
                if how == "equal":
                    screened = jnp.where(cnt > 0,
                                         num / jnp.maximum(cnt, 1.0), buf)
                else:
                    pn = (jnp.where(r1ok, r1, jnp.zeros_like(r1))
                          + jnp.where(r2ok, r2, jnp.zeros_like(r2)))
                    pc = h1[2] + h2[2]
                    pmean = pn / jnp.maximum(pc, 1.0)
                    screened = jnp.where(
                        ok,
                        jnp.where(pc > 0, w * buf + (1.0 - w) * pmean,
                                  buf),
                        jnp.where(pc > 0, pmean, buf))
                blended = jnp.where(every, blended, screened)
        for (i, off, size) in b.items:
            leaf = leaves[i]
            out[i] = blended[off:off + size].reshape(leaf.shape).astype(
                leaf.dtype)
            if new_res is not None:
                new_res[i] = err[off:off + size].reshape(leaf.shape)
    synced = jax.tree_util.tree_unflatten(treedef, out)
    res_out = (residual if new_res is None
               else jax.tree_util.tree_unflatten(treedef, new_res))
    if poison is not None:
        return synced, res_out, okf
    return synced, res_out


# --------------------------------------------------------------------------
# Hierarchical two-level round sync: inner sharded allreduce over ICI x
# outer compressed gossip over DCN (ISSUE 13 tentpole)
# --------------------------------------------------------------------------
# The paper's topology matrix keeps its engines flat: ONE worker axis,
# either all-reduced (PR 2's psum_scatter/all_gather program) or gossiped
# (PR 4's per-bucket ppermute program).  A multi-pod deployment has two
# very different wires at once — ICI within a slice (fast, low-latency)
# and DCN between slices (slow, high-latency) — and the production shape
# (arXiv 2204.06514's multi-pod pjit recipe; arXiv 2412.14374's
# DCN-traffic hiding) is the COMPOSITION: every slice's W workers
# all-reduce over ICI, and only the S slice consensuses cross DCN, via
# gossip hops that can take the compressed int8+EF wire.
#
# The decisive layout property: the outer hop rides the 1/W SCATTER
# SHARD, never the full tree.  The inner psum_scatter already leaves each
# worker holding its span of the slice SUM; dividing by W makes it the
# slice mean — worker-invariant within the slice, so worker (s, i) and
# its counterpart (s', i) in every other slice hold the SAME span of
# their slices' means.  One ppermute over the ``slice`` axis per bucket
# therefore gossips the whole slice-mean tree at bucket_bytes / W wire
# cost per hop, and the trailing inner all_gather distributes the
# gossip-blended consensus back to every worker of the slice.  DCN bytes
# per round per worker: hops x padded/W x outer_wire_itemsize per bucket
# — exactly 1/N_inner of what a flat gossip over the full tree would
# move (asserted in tests/test_sync.py and bench --entry hier).
#
# Semantics ("gossip of means"): g_s = gossip_blend(m_s, m_{s-1}[, m_{s-2}])
# where m_s is slice s's equal mean.  ``equal`` output is g_s for every
# worker of slice s; ``weighted`` (the straggler blend, flowing through
# both levels) keeps the flat form with the gossiped mean standing in
# for the local one: out_i = w*own_i + (1-w)*(W*g_s - own_i)/(W-1) — the
# self-exclusive peer mean whose peer pool has been gossip-blended
# across slices (at S=1 this IS the flat weighted allreduce, the
# 1-slice-limit contract).  In fp32 the bucketed program is BIT-IDENTICAL
# to ``aggregate_hier`` below — the same expressions evaluated per leaf
# from the flat primitives (lax.pmean + the dense gossip blends), i.e.
# the flat S*W-worker gossip-of-means reference.
#
# EF is PER LEVEL: the inner residual keeps its two flat stages (own
# contribution rounding + W x the gather-payload rounding at the owner's
# span); a NEW outer residual carries the fp32 rounding of each worker's
# own outer-hop transmission — the single-stage gossip EF, per slice,
# on the shard span.  Stage-2 corrections now deliver THROUGH the gossip
# mixing (next round's mean carries them into the blend), gossip-weighted
# rather than exact — the usual EF contraction argument still holds, and
# the fp32 fast path stays bitwise (no EF active).


def aggregate_hier(tree: PyTree, *, topology: str, how: str = "equal",
                   local_weight: float = 0.5,
                   inner_axis: str = DATA_AXIS,
                   outer_axis: str = SLICE_AXIS) -> PyTree:
    """Dense per-leaf hierarchical twin — THE flat gossip-of-means
    reference the bucketed program is bitwise-gated against.

    Built from the flat engines' own primitives, per leaf, no bucketing
    or compression: ``lax.pmean`` over the inner (worker) axis is the
    flat dense slice mean, the ring/double-ring blend expressions over
    the outer (slice) axis are ``comms.aggregate``'s gossip forms, and
    the weighted own-term blend is the flat allreduce's.  Must be called
    inside ``shard_map`` with both axes bound."""
    if topology not in GOSSIP_HOPS:
        raise ValueError(
            f"hierarchical outer topology must be one of "
            f"{tuple(GOSSIP_HOPS)}, got {topology!r} (an allreduce outer "
            "level is the flat S*W engine)")
    if how not in HOWS:
        raise ValueError(f"how must be one of {HOWS}, got {how!r}")
    nw = axis_size(inner_axis)
    ns = axis_size(outer_axis)
    w = local_weight

    def per_leaf(x: jnp.ndarray) -> jnp.ndarray:
        m = lax.pmean(x, inner_axis)
        r1 = _shift(m, ns, 1, outer_axis)
        if topology == "ring":
            g = (m + r1) / 2.0 if how == "equal" \
                else w * m + (1.0 - w) * r1
        else:
            r2 = _shift(m, ns, 2, outer_axis)
            g = (m + r1 + r2) / 3.0 if how == "equal" \
                else w * m + ((1.0 - w) / 2.0) * (r1 + r2)
        if how == "equal":
            return g
        # the straggler-weighted blend through both levels: the flat
        # self-exclusive peer-mean form, with the peer pool's mean
        # gossip-blended across slices (W*g is the blended slice total)
        return w * x + (1.0 - w) * (nw * g - x) / (nw - 1)

    return jax.tree_util.tree_map(per_leaf, tree)


def hier_wire_bytes(tree: PyTree, n_inner: int, *, topology: str,
                    wire_dtype=None, outer_wire_dtype=None,
                    bucket_bytes: int = DEFAULT_BUCKET_BYTES) -> dict:
    """Per-worker bytes SENT by one hierarchical round sync, split by
    level: ``{"ici": inner_bytes, "dcn": outer_bytes}`` (shapes only —
    leaves may be arrays or ShapeDtypeStructs).

    - ``ici``: the inner sharded engine, unchanged from the flat
      accounting — 2(W-1)/W x padded x inner_wire_itemsize per bucket
      (reduce-scatter + all-gather each move (W-1)/W);
    - ``dcn``: hops x (padded // W) x outer_wire_itemsize per bucket —
      the gossip hop rides the 1/W scatter shard, so the outer payload
      is exactly 1/N_inner of what a flat gossip over the same tree
      would permute per hop (when the bucket needs no padding; padding
      rides the wire like everywhere else in the engine).  The int8
      per-bucket scale scalar is excluded, as in ``sync_wire_bytes``.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    hops = GOSSIP_HOPS.get(topology, 1)
    if not leaves or n_inner < 1:
        return {"ici": 0, "dcn": 0}
    ici = dcn = 0
    for b in bucket_plan(leaves, n_inner, bucket_bytes):
        inner_item = (jnp.dtype(wire_dtype).itemsize
                      if wire_dtype is not None else b.dtype.itemsize)
        outer_item = (jnp.dtype(outer_wire_dtype).itemsize
                      if outer_wire_dtype is not None else b.dtype.itemsize)
        row = b.padded // n_inner
        ici += 2 * (n_inner - 1) * row * inner_item
        dcn += hops * row * outer_item
    return {"ici": ici, "dcn": dcn}


def hier_outer_residual_init(per_worker_tree: PyTree, n_inner: int,
                             n_rows: int, *,
                             bucket_bytes: int = DEFAULT_BUCKET_BYTES
                             ) -> dict:
    """Zero-initialized OUTER-level EF residual, worker-stacked: one
    ``[n_rows, padded // n_inner]`` fp32 array per sync bucket — row
    (s*W + i) carries worker (s, i)'s fp32 rounding error of its own
    outer-hop transmission (its span of slice s's mean), re-injected
    into the next round's payload exactly like the flat gossip EF."""
    leaves = jax.tree_util.tree_leaves(per_worker_tree)
    return {_bucket_name(i): jnp.zeros((n_rows, b.padded // n_inner),
                                       jnp.float32)
            for i, b in enumerate(bucket_plan(leaves, n_inner,
                                              bucket_bytes))}


def hierarchical_sync(tree: PyTree, *, topology: str, how: str = "equal",
                      local_weight: float = 0.5,
                      inner_axis: str = DATA_AXIS,
                      outer_axis: str = SLICE_AXIS,
                      wire_dtype=None, outer_wire_dtype=None,
                      residual: PyTree | None = None,
                      outer_residual: dict | None = None,
                      bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                      residency: str = "replicated") -> tuple:
    """One hierarchical round sync (ISSUE 13): bucketed inner
    reduce-scatter over ``inner_axis`` -> per-bucket outer gossip hop(s)
    on the 1/W shard over ``outer_axis`` -> apply -> inner all_gather,
    as one program.  Must be called inside ``shard_map`` with BOTH axes
    bound.

    ``wire_dtype`` compresses the inner (ICI) collectives exactly like
    ``sharded_opt_sync``; ``outer_wire_dtype`` independently compresses
    the outer (DCN) gossip payload (the per-bucket int8 scale ppermutes
    alongside, decoded with the sender's scale).  ``residual`` is the
    flat inner EF state (params-shaped, stage 1 + stage 2);
    ``outer_residual`` the per-level outer EF state ({bucket:
    [padded // W]} rows, already squeezed inside shard_map) — each
    enables its level's error feedback independently.

    ``residency="resident"`` (ISSUE 11 composed): the program ENDS at
    the inner scatter — the first return value is the ``{bucket:
    [padded // W]}`` decoded post-apply shard of THIS SLICE's consensus
    (worker-invariant within the slice under the equal blend), and the
    next round's ``resident_gather`` over the inner axis reconstructs
    it bit-for-bit.  Scatter-resident state is exactly 1/N_inner per
    worker between rounds.

    Returns ``(out_or_resident, new_residual, new_outer_residual)``.
    """
    if topology not in GOSSIP_HOPS:
        raise ValueError(
            f"hierarchical outer topology must be one of "
            f"{tuple(GOSSIP_HOPS)}, got {topology!r} (an allreduce outer "
            "level is the flat S*W engine)")
    if how not in HOWS:
        raise ValueError(f"how must be one of {HOWS}, got {how!r}")
    if residency not in PARAM_RESIDENCIES:
        raise ValueError(
            f"residency must be one of {PARAM_RESIDENCIES}, got "
            f"{residency!r}")
    resident = residency == "resident"
    if resident and how != "equal":
        raise ValueError(
            "a scatter-resident hierarchical output requires the equal "
            "blend: the weighted own-term makes every worker's output "
            "per-worker state (config.py resolves weighted to the "
            "replicated residency)")
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    nw = axis_size(inner_axis)
    ns = axis_size(outer_axis)
    if nw < 2:
        raise ValueError(
            "the hierarchical sync needs an inner worker axis of size "
            ">= 2 (the outer gossip rides the 1/W scatter shard; with "
            "W = 1 there is no inner level — run the flat gossip engine)")
    if not leaves:
        return tree, residual, outer_residual
    res_leaves = None
    if residual is not None:
        res_leaves = jax.tree_util.tree_leaves(residual)
        if len(res_leaves) != len(leaves):
            raise ValueError(
                "residual must mirror the synced tree: "
                f"{len(res_leaves)} leaves vs {len(leaves)}")
    out: list = [None] * len(leaves)
    new_res: list | None = [None] * len(leaves) if res_leaves is not None \
        else None
    new_outer: dict | None = {} if outer_residual is not None else None
    resident_out: dict = {}
    w = local_weight
    for bi, b in enumerate(bucket_plan(leaves, nw, bucket_bytes)):
        name = _bucket_name(bi)
        row = b.padded // nw
        # ---- pack + inner encode (the flat sharded engine's stage) ----
        parts, filled = [], 0
        for (i, _off, size) in b.items:
            x = leaves[i].astype(jnp.float32).reshape(-1)
            if res_leaves is not None:
                x = x + res_leaves[i].astype(jnp.float32).reshape(-1)
            parts.append(x)
            filled += size
        if b.padded > filled:
            parts.append(jnp.zeros((b.padded - filled,), jnp.float32))
        buf = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        wdt_in = jnp.dtype(wire_dtype) if wire_dtype is not None \
            else b.dtype
        quantized_in, encode_in = _wire_codec(wdt_in)
        sent, sent32, sent_scale = encode_in(buf)
        if new_res is not None:
            err = buf - sent32
        compressed_in = wdt_in != jnp.dtype(jnp.float32)
        if compressed_in:
            # compressed reduce-scatter as all-to-all + LOCAL fp32
            # accumulation (the sharded_opt_sync recipe and rationale)
            pieces = lax.all_to_all(sent.reshape(nw, row),
                                    inner_axis, 0, 0)
            if quantized_in:
                scales = lax.all_gather(sent_scale, inner_axis)   # [W]
                shard32 = jnp.sum(pieces.astype(jnp.float32)
                                  * scales[:, None], axis=0)
            else:
                shard32 = jnp.sum(pieces.astype(jnp.float32), axis=0)
        else:
            shard32 = psum_scatter(sent, inner_axis, scatter_dimension=0,
                                   tiled=True).astype(jnp.float32)
        # ---- the slice mean on the shard: worker-invariant WITHIN the
        # slice, which is what lets the outer hop ride the shard ----
        m32 = shard32 / nw
        # ---- outer gossip hop(s) over the slice axis ----
        o_send = m32
        if new_outer is not None:
            if name not in outer_residual:
                raise ValueError(
                    f"outer residual has no bucket {name} (bucket plan "
                    "/ outer-residual layout mismatch)")
            o_res = outer_residual[name]
            if tuple(o_res.shape) != (row,):
                raise ValueError(
                    f"outer residual bucket {name} row has shape "
                    f"{tuple(o_res.shape)}, expected {(row,)} "
                    "(sync_bucket_mb or worker count changed?)")
            o_send = m32 + o_res.astype(jnp.float32)
        wdt_out = jnp.dtype(outer_wire_dtype) \
            if outer_wire_dtype is not None else b.dtype
        quantized_out, encode_out = _wire_codec(wdt_out)
        osent, osent32, osent_scale = encode_out(o_send)
        if new_outer is not None:
            # outer-level EF: the fp32 rounding this hop's wire dropped
            # from THIS worker's transmission rides into next round's
            # payload (the flat gossip engine's single stage, per level)
            new_outer[name] = o_send - osent32

        def hop(shift):
            r = _shift(osent, ns, shift, outer_axis)
            s = (_shift(osent_scale, ns, shift, outer_axis)
                 if quantized_out else None)
            return r, s

        def dec(trip):
            r, s = trip
            r32 = r.astype(jnp.float32)
            return r32 * s if s is not None else r32

        if topology == "ring":
            r1 = dec(hop(1))
            g32 = (m32 + r1) / 2.0 if how == "equal" \
                else w * m32 + (1.0 - w) * r1
        else:
            # both shifts issued before either blend term is consumed
            # (the PR 4 double-ring overlap fence): the shift-2 hop's
            # DCN time rides under the shift-1 blend
            h1, h2 = optimization_barrier((hop(1), hop(2)))
            r1, r2 = dec(h1), dec(h2)
            g32 = (m32 + r1 + r2) / 3.0 if how == "equal" \
                else w * m32 + ((1.0 - w) / 2.0) * (r1 + r2)

        # ---- apply on the shard + home gather (inner wire) ----
        gq, gq_dec, gq_scale = encode_in(g32)
        if new_res is not None and compressed_in and how == "equal":
            # stage-2 inner EF (the flat engine's): the gather payload
            # is wire-quantized every round on the same grid; the span
            # owner folds W x the rounding error into its residual —
            # delivery now flows THROUGH next round's mean + gossip
            # blend (gossip-weighted, one round delayed)
            e2 = g32 - gq_dec
            err = err + lax.dynamic_update_slice(
                jnp.zeros((b.padded,), jnp.float32), nw * e2,
                (lax.axis_index(inner_axis) * row,))

        def gather_decoded(payload, scale):
            full = lax.all_gather(payload, inner_axis,
                                  tiled=True).astype(jnp.float32)
            if not quantized_in:
                return full
            scales = lax.all_gather(scale, inner_axis)           # [W]
            return (full.reshape(nw, -1) * scales[:, None]).reshape(-1)

        if how == "equal":
            if resident:
                # ISSUE 11 composed: the program ends at the scatter —
                # the decoded shard IS the between-round state, and the
                # next round's entry gather (over the inner axis)
                # concatenates exactly these values
                resident_out[name] = gq_dec
                full = None
            else:
                full = gather_decoded(gq, gq_scale)
        else:
            gfull = gather_decoded(gq, gq_scale)
            own = sent32
            # the flat weighted form with the gossip-blended peer pool:
            # W*g is the blended slice total, own excluded as ever
            full = w * own + (1.0 - w) * (nw * gfull - own) / (nw - 1)
        for (i, off, size) in b.items:
            leaf = leaves[i]
            if full is not None:
                out[i] = full[off:off + size].reshape(leaf.shape).astype(
                    leaf.dtype)
            if new_res is not None:
                new_res[i] = err[off:off + size].reshape(leaf.shape)
    res_out = (residual if new_res is None
               else jax.tree_util.tree_unflatten(treedef, new_res))
    outer_out = outer_residual if new_outer is None else new_outer
    first = (resident_out if resident
             else jax.tree_util.tree_unflatten(treedef, out))
    return first, res_out, outer_out


def make_hier_host_sync(mesh, *, topology: str, how: str = "equal",
                        local_weight: float = 0.5, wire_dtype=None,
                        outer_wire_dtype=None,
                        bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                        residency: str = "replicated"):
    """Jitted stand-alone hierarchical round sync over worker-stacked
    pytrees (tests / bench A/Bs) — the two-level twin of
    ``make_host_sync``.  Leaves carry a leading worker axis of size
    S x W sharded over ``(slice, data)`` (slice-major rows).  Returns
    ``run(tree, residual=None, outer_residual=None)`` ->
    ``(out_or_resident, new_residual, new_outer_residual)``."""
    from jax.sharding import PartitionSpec as P

    spec = P((SLICE_AXIS, DATA_AXIS))

    def _sync(tree, residual, outer_res):
        def inner(shard, res, ores):
            sq = lambda t: jax.tree_util.tree_map(lambda x: x[0], t)
            ex = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
            outs = hierarchical_sync(
                sq(shard), topology=topology, how=how,
                local_weight=local_weight, wire_dtype=wire_dtype,
                outer_wire_dtype=outer_wire_dtype, residual=sq(res),
                outer_residual=sq(ores), bucket_bytes=bucket_bytes,
                residency=residency)
            return tuple(ex(o) for o in outs)
        return shard_map(inner, mesh=mesh, in_specs=(spec,) * 3,
                         out_specs=(spec,) * 3)(tree, residual, outer_res)

    jitted = jax.jit(_sync)

    def run(tree, residual=None, outer_residual=None):
        return jitted(tree, residual, outer_residual)

    return run


def make_hier_host_aggregator(mesh, *, topology: str, how: str = "equal",
                              local_weight: float = 0.5):
    """Jitted stand-alone DENSE hierarchical aggregator — the flat
    gossip-of-means reference program (``aggregate_hier`` per leaf) the
    bucketed engine is bitwise-gated against in fp32."""
    from jax.sharding import PartitionSpec as P

    spec = P((SLICE_AXIS, DATA_AXIS))

    def _agg(tree):
        def inner(shard):
            squeezed = jax.tree_util.tree_map(lambda x: x[0], shard)
            out = aggregate_hier(squeezed, topology=topology, how=how,
                                 local_weight=local_weight)
            return jax.tree_util.tree_map(lambda x: x[None], out)
        return shard_map(
            inner, mesh=mesh, in_specs=(spec,), out_specs=spec)(tree)

    return jax.jit(_agg)


def make_host_sync(mesh, *, mode: str = "sharded", how: str = "equal",
                   local_weight: float = 0.5, wire_dtype=None,
                   bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                   topology: str = "allreduce",
                   opt_placement: str = "sharded",
                   track_opt: bool = False,
                   param_residency: str = "replicated",
                   redundancy: str = "off",
                   screen: bool = False):
    """Jitted stand-alone round sync over worker-stacked pytrees.

    The sync-engine twin of ``make_host_aggregator`` (tests, bench A/Bs,
    federated checkpoint averaging): takes worker-stacked pytrees
    ([N, ...] leaves over the mesh's data axis) plus an optional residual
    pytree of the same structure, and returns ``(synced, new_residual)``.
    ``mode="dense"`` routes through ``aggregate`` (per-leaf, any
    topology) so the engines can be compared under identical harnesses;
    ``mode="gossip"`` runs the bucketed gossip engine for ring /
    double_ring; ``mode="sharded"`` the reduce-scatter engine
    (allreduce).

    ``opt_placement`` places the sharded engine's apply stage (ISSUE 9,
    ``sharded_sync``); ``track_opt=True`` additionally threads a
    round-optimizer tracker (``round_opt_init`` layout, worker-stacked)
    through the program — the returned callable then takes
    ``(tree, residual, tracker)`` and returns
    ``(synced, new_residual, new_tracker)``.

    ``param_residency="resident"`` (ISSUE 11, sharded mode only) ends
    the program at the scatter: the first return value is the
    worker-stacked resident layout (``{bucket: [n, padded // n]}``)
    instead of the synced tree — feed it to ``make_resident_gather`` to
    reconstruct the full tree bit-for-bit.

    ``redundancy="buddy"`` / ``screen=True`` (ISSUE 12) arm the buddy
    hop and the NaN/Inf integrity screen; the returned callable then
    takes ``(tree, residual=None, tracker=None, poison=None)`` and
    returns a DICT ``{"out", "residual", "tracker", "buddy", "ok"}``
    (keys present per arming) — the unit-test surface for the
    failure-domain program shapes.
    """
    from jax.sharding import PartitionSpec as P

    if param_residency == "resident" and mode != "sharded":
        raise ValueError(
            "param_residency 'resident' is a sharded-engine output "
            f"layout; mode {mode!r} has no scatter to end at")
    buddy_on = redundancy == "buddy"
    if buddy_on and mode != "sharded":
        raise ValueError(
            "buddy redundancy backs up shard-resident rows, which only "
            f"the sharded engine produces; mode {mode!r} has none")
    if buddy_on and param_residency != "resident" and not (
            track_opt and opt_placement == "sharded"):
        raise ValueError(
            "buddy redundancy needs something shard-resident: "
            "param_residency 'resident' and/or a sharded-placement "
            "tracker (track_opt=True)")
    spec = P(DATA_AXIS)

    def _sync(tree, residual, tracker, poison):
        def inner(shard, res, trk, poi):
            sq = lambda t: jax.tree_util.tree_map(lambda x: x[0], t)
            ex = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
            # squeeze the tracker too: the dense/gossip branches pass it
            # through untouched, and ``ex`` below must restore exactly
            # the worker-stacked layout it arrived in
            t, r, new_t = sq(shard), sq(res), sq(trk)
            # when the screen is armed the wrapper below guarantees a
            # poison vector (all-clear default), so poi is never None
            p = sq(poi) if screen else None
            extra: dict = {}
            if mode == "dense":
                if screen:
                    out, okf = aggregate(
                        t, how=how, topology=topology,
                        local_weight=local_weight, poison=p)
                    extra["ok"] = okf
                else:
                    out = aggregate(t, how=how, topology=topology,
                                    local_weight=local_weight)
                new_r = r
            elif mode == "gossip":
                rets = gossip_sync(
                    t, topology=topology, how=how,
                    local_weight=local_weight, wire_dtype=wire_dtype,
                    residual=r, bucket_bytes=bucket_bytes,
                    poison=p if screen else None)
                out, new_r = rets[0], rets[1]
                if screen:
                    extra["ok"] = rets[2]
            else:
                rets = sharded_opt_sync(
                    t, how=how, local_weight=local_weight,
                    wire_dtype=wire_dtype, residual=r,
                    bucket_bytes=bucket_bytes,
                    opt_placement=opt_placement, tracker=new_t,
                    residency=param_residency, buddy=buddy_on,
                    poison=p if screen else None)
                out, new_r, new_t = rets[0], rets[1], rets[2]
                idx = 3
                if buddy_on:
                    extra["buddy"] = rets[idx]
                    idx += 1
                if screen:
                    extra["ok"] = rets[idx]
            if not buddy_on and not screen:
                return ex(out), ex(new_r), ex(new_t)
            return {"out": ex(out), "residual": ex(new_r),
                    "tracker": ex(new_t),
                    **{k: ex(v) for k, v in extra.items()}}
        n_in = 4 if (buddy_on or screen) else 3
        args = (tree, residual, tracker, poison)[:n_in]
        return shard_map(inner if n_in == 4 else
                         (lambda a, b, c: inner(a, b, c, None)),
                         mesh=mesh, in_specs=(spec,) * n_in,
                         out_specs=spec if (buddy_on or screen)
                         else (spec, spec, spec))(*args)

    jitted = jax.jit(_sync)

    if buddy_on or screen:
        n_workers = int(mesh.shape[DATA_AXIS])

        def run_full(tree, residual=None, tracker=None, poison=None):
            import numpy as np
            if screen and poison is None:
                poison = np.zeros(n_workers, np.bool_)
            return jitted(tree, residual, tracker, poison)
        return run_full

    if track_opt:
        def run_tracked(tree, residual=None, tracker=None):
            return jitted(tree, residual, tracker, None)
        return run_tracked

    def run(tree, residual=None):
        out, new_r, _ = jitted(tree, residual, None, None)
        return out, new_r

    return run


def make_host_aggregator(mesh, *, how: str, topology: str,
                         local_weight: float = 0.5):
    """Jitted stand-alone aggregator over worker-stacked pytrees.

    Takes pytrees whose leaves carry a leading worker axis of size
    ``mesh.shape['data']`` (the framework's representation of N independent
    local-SGD replicas) and returns the synchronized pytree.  The train loop
    fuses aggregation into its round program; this wrapper exists for tests
    and for ad-hoc use (e.g. federated averaging of checkpoints).
    """
    from jax.sharding import PartitionSpec as P

    spec = P(DATA_AXIS)

    def _agg(tree):
        def inner(shard):
            squeezed = jax.tree_util.tree_map(lambda x: x[0], shard)
            out = aggregate(squeezed, how=how, topology=topology,
                            local_weight=local_weight)
            return jax.tree_util.tree_map(lambda x: x[None], out)
        return shard_map(
            inner, mesh=mesh, in_specs=(spec,), out_specs=spec)(tree)

    return jax.jit(_agg)
