"""The synchronization matrix: one pytree-level ``aggregate`` for all 12 DP
sync modes.

The reference splits this across three ``communication.py`` flavors with
asymmetric interfaces (model-level for all-reduce,
``Balanced All-Reduce/communication.py:4-31``; tensor-level with the trainer
iterating parameters for ring/double-ring,
``Balanced Ring/communication.py:5-62``, ``Balanced Double-Ring/
communication.py:5-77``) over two backends (torch.distributed, mpi4py).
Here it is a single pure function on pytrees, executed *inside*
``shard_map``/``jit`` with XLA collectives over the mesh's data axis:

- ``allreduce`` -> ``lax.pmean`` / ``lax.psum`` (NCCL/gloo all_reduce
  equivalent, rides ICI);
- ``ring``      -> ``lax.ppermute`` shift-by-1 (the reference's 1-neighbor
  Isend/Irecv gossip, ``Balanced Ring/communication.py:19-25``);
- ``double_ring`` -> two ``ppermute`` shifts (1 and 2) (2-neighbor gossip,
  ``Balanced Double-Ring/communication.py:5-40``).

Semantics notes (SURVEY.md 2.5):

- "Ring" is one gossip exchange per sync — NOT a reduce-scatter/all-gather
  ring all-reduce; consensus emerges over repeated global epochs.  That is
  the observable behavior being reproduced.
- The reference's ring gossip silently no-ops on GPU (2.5.2); the behavior
  matched here is the correct CPU path.
- ``weighted`` all-reduce (2.5.10): ``new = w*own + (1-w)*(sum-own)/(N-1)``
  — the self-exclusive peer mean blended with the own value.  The reference
  divides by zero when N == 1; here N == 1 returns the own value unchanged
  (every topology is the identity on a single worker).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from .compat import axis_size, shard_map
from .mesh import DATA_AXIS

PyTree = Any

TOPOLOGIES = ("allreduce", "ring", "double_ring")
HOWS = ("equal", "weighted")
BYS = ("gradients", "weights")


def _shift(x: jnp.ndarray, n: int, shift: int, axis_name: str) -> jnp.ndarray:
    """Receive the value of ``rank - shift`` (mod n): each rank i sends to
    ``i + shift``, matching the reference's Isend(to rank+1)/Irecv(from
    rank-1) gossip pattern."""
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def aggregate(tree: PyTree, *, how: str = "equal",
              topology: str = "allreduce", local_weight: float = 0.5,
              axis_name: str = DATA_AXIS) -> PyTree:
    """Aggregate a per-worker pytree across the data axis.

    Must be called inside ``shard_map`` (or any context where ``axis_name``
    is bound).  Works on parameter or gradient pytrees alike — the
    gradients/weights choice ("aggregation_by") is the caller's, matching
    the reference's dispatch (``Balanced All-Reduce/trainer.py:141-150``).
    """
    if how not in HOWS:
        raise ValueError(f"how must be one of {HOWS}, got {how!r}")
    if topology not in TOPOLOGIES:
        raise ValueError(f"topology must be one of {TOPOLOGIES}, got {topology!r}")
    n = axis_size(axis_name)
    if n == 1:
        return tree
    w = local_weight

    def per_leaf(x: jnp.ndarray) -> jnp.ndarray:
        if topology == "allreduce":
            if how == "equal":
                return lax.pmean(x, axis_name)
            total = lax.psum(x, axis_name)
            peers_mean = (total - x) / (n - 1)
            return w * x + (1.0 - w) * peers_mean
        if topology == "ring":
            r = _shift(x, n, 1, axis_name)
            if how == "equal":
                return (x + r) / 2.0
            return w * x + (1.0 - w) * r
        # double_ring: blend with the two predecessors
        r1 = _shift(x, n, 1, axis_name)
        r2 = _shift(x, n, 2, axis_name)
        if how == "equal":
            return (x + r1 + r2) / 3.0
        return w * x + ((1.0 - w) / 2.0) * (r1 + r2)

    return jax.tree_util.tree_map(per_leaf, tree)


def make_host_aggregator(mesh, *, how: str, topology: str,
                         local_weight: float = 0.5):
    """Jitted stand-alone aggregator over worker-stacked pytrees.

    Takes pytrees whose leaves carry a leading worker axis of size
    ``mesh.shape['data']`` (the framework's representation of N independent
    local-SGD replicas) and returns the synchronized pytree.  The train loop
    fuses aggregation into its round program; this wrapper exists for tests
    and for ad-hoc use (e.g. federated averaging of checkpoints).
    """
    from jax.sharding import PartitionSpec as P

    spec = P(DATA_AXIS)

    def _agg(tree):
        def inner(shard):
            squeezed = jax.tree_util.tree_map(lambda x: x[0], shard)
            out = aggregate(squeezed, how=how, topology=topology,
                            local_weight=local_weight)
            return jax.tree_util.tree_map(lambda x: x[None], out)
        return shard_map(
            inner, mesh=mesh, in_specs=(spec,), out_specs=spec)(tree)

    return jax.jit(_agg)
