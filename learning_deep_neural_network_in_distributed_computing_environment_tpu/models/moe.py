"""Mixture-of-Experts FFN with expert parallelism over the ``expert``
mesh axis.

Beyond-reference capability (the reference is data-parallel only,
SURVEY.md 2.3).  Switch-Transformer-style top-1 token routing with a
capacity limit, formulated TPU-first as dispatch/combine einsums (dense
one-hot dispatch tensors -> MXU work, no gather/scatter):

- the gate (replicated) scores every token against all ``num_experts``
  experts; each token goes to its top-1 expert, capped at
  ``capacity = ceil(capacity_factor * tokens / num_experts)`` tokens per
  expert (overflow tokens are dropped — the residual connection in the
  caller carries them through, standard Switch behavior);
- expert weights are STACKED with a leading [num_experts] axis; under
  ``shard_map`` that axis is sharded over ``expert`` and each device
  dispatches only to its local slice, contributing its experts' outputs
  to a cross-shard ``psum``;
- the load-balance auxiliary loss (Switch: E * sum(f_e * P_e)) is sown
  into the ``aux`` variable collection; the training engine adds it to
  the objective with ``moe_aux_weight``.

The dense twin (``expert_axis=None``, ``ep_size=1``) computes the exact
same function with the full expert stack — one parameter structure for
both worlds, as with tensor parallelism.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from ..compat import checkpoint_name

_init = nn.initializers.normal(stddev=0.02)


class MoEFFN(nn.Module):
    num_experts: int               # GLOBAL expert count
    ffn_dim: int                   # GLOBAL per-expert FFN width
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32
    expert_axis: Optional[str] = None  # mesh axis experts shard over
    ep_size: int = 1               # expert-axis size (local = E / ep_size)
    tp_size: int = 1               # tensor-parallel size (F local = F / tp)
    model_axis: Optional[str] = None   # mesh axis the F dim shards over

    @nn.compact
    def __call__(self, x, *, train: bool = False, aux_scale=1.0):
        """``aux_scale`` multiplies the sown load-balance loss: the GPipe
        schedule passes validity/(num_microbatches) so bubble steps sow
        exactly zero and valid microbatch contributions average to the
        full-batch scale (parallel/pp.py).

        Tensor parallelism (MoE x TP, VERDICT r3 'next' #4): each expert's
        FFN is Megatron-sharded over ``model_axis`` — w1/b1 column-parallel
        on the F dim, w2 row-parallel — while the gate and the routing stay
        replicated (every shard routes the identical full token set), so
        the capacity and aux-loss semantics are EXACTLY those of the
        unsharded MoE and the composition is golden-testable against it.
        The per-shard partial outputs and the expert shards reduce in one
        ``psum`` over both axes; b2 (post-reduction bias) is scaled by
        1/tp so the psum restores it exactly once."""
        b, t, h = x.shape
        e, ep = self.num_experts, self.ep_size
        if e % ep:
            raise ValueError(f"num_experts {e} not divisible by "
                             f"expert-parallel size {ep}")
        e_local = e // ep
        if self.ffn_dim % self.tp_size:
            raise ValueError(f"ffn_dim {self.ffn_dim} not divisible by "
                             f"tp_size {self.tp_size} (column-parallel "
                             "expert FFN)")
        f_local = self.ffn_dim // self.tp_size
        toks = x.reshape(b * t, h)
        n_tok = b * t
        cap = max(int(math.ceil(self.capacity_factor * n_tok / e)), 1)

        # --- top-1 routing (computed identically on every expert shard) --
        gate_logits = nn.Dense(e, use_bias=False, dtype=jnp.float32,
                               kernel_init=_init, name="gate")(
                                   toks.astype(jnp.float32))
        probs = jax.nn.softmax(gate_logits, axis=-1)         # [N, E]
        expert_idx = jnp.argmax(probs, axis=-1)              # [N]
        gate = jnp.max(probs, axis=-1)                       # [N]
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)
        # Switch load-balance loss: E * sum_e f_e * P_e
        self.sow("aux", "load_balance",
                 jnp.asarray(aux_scale, jnp.float32)
                 * e * jnp.sum(onehot.mean(0) * probs.mean(0)))
        # position of each token within its expert's queue; drop overflow
        pos = jnp.einsum("ne,ne->n", jnp.cumsum(onehot, axis=0) - 1.0,
                         onehot).astype(jnp.int32)
        keep = (pos < cap).astype(jnp.float32)
        dispatch = (onehot * keep[:, None])[..., None] * jax.nn.one_hot(
            jnp.clip(pos, 0, cap - 1), cap,
            dtype=jnp.float32)[:, None, :]                      # [N, E, C]

        # --- local expert slice ------------------------------------------
        if self.expert_axis is not None:
            off = lax.axis_index(self.expert_axis) * e_local
            dispatch_local = lax.dynamic_slice_in_dim(dispatch, off, e_local,
                                                      axis=1)
        else:
            dispatch_local = dispatch

        w1 = self.param("w1", _init, (e_local, h, f_local))
        b1 = self.param("b1", nn.initializers.zeros, (e_local, f_local))
        w2 = self.param("w2", _init, (e_local, f_local, h))
        b2 = self.param("b2", nn.initializers.zeros, (e_local, h))

        dl = dispatch_local.astype(self.dtype)
        # named activation "moe_dispatch" (ISSUE 15): the expert-batched
        # dispatched tokens [E, C, H] — the MoE-specific residual a
        # save_names:/offload_names: policy may pin (recomputing it
        # re-pays the dense one-hot dispatch einsum)
        xe = checkpoint_name(
            jnp.einsum("nec,nh->ech", dl, toks.astype(self.dtype)),
            "moe_dispatch")
        h1 = nn.gelu(jnp.einsum("ech,ehf->ecf", xe, w1.astype(self.dtype))
                     + b1[:, None, :].astype(self.dtype), approximate=False)
        # row-parallel w2: per-shard partial sums over the local F slice;
        # b2 is scaled so the cross-shard psum below adds it exactly once
        b2_scale = 1.0 / self.tp_size if self.model_axis is not None else 1.0
        ye = jnp.einsum("ecf,efh->ech", h1, w2.astype(self.dtype)) \
            + b2_scale * b2[:, None, :].astype(self.dtype)
        combine = dl * gate[:, None, None].astype(self.dtype)
        out = jnp.einsum("nec,ech->nh", combine, ye)
        reduce_axes = tuple(a for a in (self.expert_axis, self.model_axis)
                            if a is not None)
        if reduce_axes:
            out = lax.psum(out, reduce_axes)
        return out.reshape(b, t, h)


def with_expert_overlay(specs_fn, *, axis: str = "expert"):
    """Wrap a PartitionSpec-tree builder (e.g. ``bert.tp_param_specs`` /
    ``bert.pp_tp_param_specs``) so MoE expert-stack leaves additionally
    shard their EXPERT dim over ``axis`` — the EP x TP (and PP x EP x TP)
    composition: inner F dims come from the wrapped Megatron pattern, the
    expert dim (leading, or right behind the stacked-layer dim) from the
    overlay."""
    from jax.sharding import PartitionSpec as P

    def fn(params):
        specs = specs_fn(params)

        def fix(path, leaf_spec):
            names = [getattr(p_, "key", str(p_)) for p_ in path]
            if "moe" not in names or "gate" in names:
                return leaf_spec
            i = 1 if "layers" in names else 0
            parts = list(leaf_spec)
            while len(parts) <= i:
                parts.append(None)
            if parts[i] is not None:
                raise ValueError(
                    f"expert dim {i} of {'/'.join(names)} already sharded "
                    f"over {parts[i]!r}")
            parts[i] = axis
            return P(*parts)

        return jax.tree_util.tree_map_with_path(
            fix, specs, is_leaf=lambda x: isinstance(x, P))
    return fn


def ep_param_specs(params, axis: str = "expert"):
    """PartitionSpec tree sharding MoE expert stacks over ``axis`` (no
    worker axis — the engine prepends it): w1/b1/w2/b2 leaves under any
    ``moe`` submodule get their EXPERT dim sharded — the leading dim, or
    dim 1 under a ``layer_scan`` stacked ``layers`` collection (the layer
    dim stays unsharded; ``pp_ep_param_specs`` is the twin that puts it
    on ``pipe``); the gate and everything else replicated."""
    from jax.sharding import PartitionSpec as P

    def spec(path, leaf):
        names = [getattr(p_, "key", str(p_)) for p_ in path]
        if "moe" in names and "gate" not in names:
            if "layers" in names:
                return P(None, axis, *([None] * (leaf.ndim - 2)))
            return P(axis, *([None] * (leaf.ndim - 1)))
        return P()
    return jax.tree_util.tree_map_with_path(spec, params)


def pp_ep_param_specs(params, *, pipe_axis: str = "pipe",
                      axis: str = "expert"):
    """PartitionSpec tree for a ``scan_layers`` MoE model under BOTH
    pipeline and expert parallelism: leaves under the stacked ``layers``
    collection shard their leading (layer) dim over ``pipe_axis``, and the
    expert stacks (now at dim 1, behind the layer dim) additionally shard
    over ``axis``; everything outside the stack replicated."""
    from jax.sharding import PartitionSpec as P

    def spec(path, leaf):
        names = [getattr(p_, "key", str(p_)) for p_ in path]
        expert = "moe" in names and "gate" not in names
        if "layers" in names:
            if expert:
                return P(pipe_axis, axis, *([None] * (leaf.ndim - 2)))
            return P(pipe_axis, *([None] * (leaf.ndim - 1)))
        if expert:
            return P(axis, *([None] * (leaf.ndim - 1)))
        return P()
    return jax.tree_util.tree_map_with_path(spec, params)
