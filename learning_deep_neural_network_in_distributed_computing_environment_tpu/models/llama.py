"""Llama-style causal language model (beyond-reference model family).

The modern decoder recipe, from scratch in flax (no ``transformers``
dependency): pre-norm **RMSNorm**, **rotary position embeddings** (RoPE,
rotate-half convention — no learned position table, so sequence length is
unbounded by parameters), **SwiGLU** FFN, no biases anywhere, and an
UNTIED vocab-parallel-capable LM head.  The reference has no sequence
models at all (its model is a CNN, SURVEY.md 2.3).

All parallelism plumbing is shared with the BERT/GPT stack:

- attention IS ``bert.SelfAttention(causal=True, rope_theta=..., use_bias=
  False)`` — one shared module for dense, Pallas flash, and causal ring /
  Ulysses sequence-parallel attention; it applies RoPE (``ops.attention.
  rope``) to q/k before ``attend`` with absolute positions (offset by
  ``lax.axis_index`` under sequence parallelism), so rotated keys travel
  the ring already position-encoded;
- tensor parallelism uses the Megatron construction with the shared
  param-name patterns (``qkv``/``out`` sharded by head, ``ffn_in``/
  ``ffn_up`` column-parallel, ``ffn_out`` row-parallel, ``lm_head``
  vocab-parallel — ``bert._tp_parts``), so ``bert.tp_param_specs`` and
  ``bert.pp_tp_param_specs`` apply unchanged;
- ``scan_layers=True`` stacks the blocks for the GPipe schedule
  (``bert.apply_scanned_stack``);
- ``num_experts > 0`` swaps SwiGLU for the Switch-MoE FFN.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..compat import checkpoint_name
from ..ops.attention import rope  # noqa: F401  (re-export; tests use it)
from ..parallel.tp import copy_to_tp_region, reduce_from_tp_region
from .bert import SelfAttention

_init = nn.initializers.normal(stddev=0.02)


class LlamaBlock(nn.Module):
    """Pre-norm decoder block: x + attn(rms1(x)); x + swiglu(rms2(x))."""

    num_heads: int
    ffn_dim: int                   # GLOBAL SwiGLU hidden width
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    axis_name: Optional[str] = None
    tp_size: int = 1
    model_axis: Optional[str] = None
    rope_theta: float = 10000.0
    num_kv_heads: Optional[int] = None   # < num_heads => GQA
    num_experts: int = 0
    expert_axis: Optional[str] = None
    ep_size: int = 1
    capacity_factor: float = 1.25

    @nn.compact
    def __call__(self, x, *, train: bool = False, aux_scale=1.0):
        norm = lambda name: nn.RMSNorm(epsilon=1e-5, dtype=self.dtype,
                                       name=name)
        # named activations (ISSUE 15, models.REMAT_NAMES): inert
        # identity labels a save_names:/offload_names: policy selects
        a = checkpoint_name(
            SelfAttention(self.num_heads, dtype=self.dtype,
                          attention_impl=self.attention_impl,
                          axis_name=self.axis_name, tp_size=self.tp_size,
                          model_axis=self.model_axis, causal=True,
                          rope_theta=self.rope_theta, use_bias=False,
                          num_kv_heads=self.num_kv_heads,
                          name="attn")(norm("rms1")(x)), "attn_out")
        x = x + a
        f = norm("rms2")(x)
        if self.num_experts:
            from .moe import MoEFFN
            f = MoEFFN(self.num_experts, self.ffn_dim,
                       capacity_factor=self.capacity_factor,
                       dtype=self.dtype, expert_axis=self.expert_axis,
                       ep_size=self.ep_size, tp_size=self.tp_size,
                       model_axis=self.model_axis, name="moe")(
                           f, train=train, aux_scale=aux_scale)
        else:
            if self.ffn_dim % self.tp_size:
                raise ValueError(
                    f"ffn_dim {self.ffn_dim} not divisible by tp_size "
                    f"{self.tp_size} (column-parallel SwiGLU)")
            f_in = copy_to_tp_region(f, self.model_axis)
            gate = nn.Dense(self.ffn_dim // self.tp_size, use_bias=False,
                            kernel_init=_init, dtype=self.dtype,
                            name="ffn_in")(f_in)
            up = nn.Dense(self.ffn_dim // self.tp_size, use_bias=False,
                          kernel_init=_init, dtype=self.dtype,
                          name="ffn_up")(f_in)
            f = nn.Dense(x.shape[-1], use_bias=False, kernel_init=_init,
                         dtype=self.dtype,
                         name="ffn_out")(nn.silu(gate) * up)
            f = reduce_from_tp_region(f, self.model_axis)
        f = checkpoint_name(f, "mlp_out")
        return checkpoint_name(x + f, "block_out")


class _ScanLlamaBlock(nn.Module):
    """carry-API adapter so ``nn.scan`` can stack LlamaBlocks.  Second
    (broadcast) arg: MoE aux-loss scale (None => 1.0; the GPipe schedule
    passes its bubble mask — parallel/pp.py)."""

    num_heads: int
    ffn_dim: int
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    axis_name: Optional[str] = None
    tp_size: int = 1
    model_axis: Optional[str] = None
    rope_theta: float = 10000.0
    num_kv_heads: Optional[int] = None
    num_experts: int = 0
    expert_axis: Optional[str] = None
    ep_size: int = 1
    capacity_factor: float = 1.25
    train: bool = False

    @nn.compact
    def __call__(self, x, aux_scale):
        y = LlamaBlock(self.num_heads, self.ffn_dim, dtype=self.dtype,
                       attention_impl=self.attention_impl,
                       axis_name=self.axis_name, tp_size=self.tp_size,
                       model_axis=self.model_axis,
                       rope_theta=self.rope_theta,
                       num_kv_heads=self.num_kv_heads,
                       num_experts=self.num_experts,
                       expert_axis=self.expert_axis, ep_size=self.ep_size,
                       capacity_factor=self.capacity_factor, name="layer")(
                           x, train=self.train,
                           aux_scale=1.0 if aux_scale is None
                           else aux_scale)
        return y, None


class LlamaForCausalLM(nn.Module):
    """Token ids [B, L] -> next-token logits [B, L, vocab] (or the LOCAL
    vocab slice under tensor parallelism — vocab-parallel LM head)."""

    num_classes: int = 32000       # vocab size (engine passes num_classes)
    num_layers: int = 16
    hidden: int = 1024
    num_heads: int = 16
    ffn_dim: int = 2816            # SwiGLU hidden (~2.75x hidden)
    rope_theta: float = 10000.0
    num_kv_heads: Optional[int] = None   # < num_heads => GQA (Llama-2/3)
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    axis_name: Optional[str] = None
    tp_size: int = 1
    model_axis: Optional[str] = None
    scan_layers: bool = False
    pipeline_axis: Optional[str] = None
    pp_size: int = 1
    num_microbatches: int = 0      # 0 => pp_size
    remat: bool = False            # [compat alias] remat_policy="everything"
    remat_policy: Optional[str] = None  # none | dots_saveable | everything
    num_experts: int = 0           # >0 => Switch-MoE FFN in every block
    expert_axis: Optional[str] = None
    ep_size: int = 1
    capacity_factor: float = 1.25

    # class marker: with tp_size > 1 the untied lm_head outputs its LOCAL
    # vocab slice and the engine's loss goes vocab-parallel
    vocab_parallel_head = True

    @nn.compact
    def __call__(self, input_ids, *, train: bool = False,
                 mode: str = "full"):
        """``mode`` partitions the forward for the 1F1B engine path
        (parallel/pp.py): 'embed' / 'stage' / 'head' — see
        ``bert.BertForMLM.__call__``."""
        if self.tp_size > 1 and self.num_classes % self.tp_size:
            raise ValueError(
                f"vocab size {self.num_classes} not divisible by tp_size "
                f"{self.tp_size} (vocab-parallel LM head)")
        if mode == "head":
            return self._lm_head(input_ids)
        if mode != "stage":
            x = nn.Embed(self.num_classes, self.hidden,
                         embedding_init=_init, dtype=self.dtype,
                         name="tok_emb")(input_ids)
            if mode == "embed":
                return x
        else:
            if not self.scan_layers:
                raise ValueError("mode='stage' requires scan_layers=True")
            x = input_ids  # activations: apply the local stage layers only
        # no position table: RoPE inside attention carries all position info
        if self.scan_layers:
            from .bert import apply_scanned_stack, resolve_remat_policy
            x = apply_scanned_stack(
                _ScanLlamaBlock, x, num_layers=self.num_layers,
                pp_size=self.pp_size,
                pipeline_axis=None if mode == "stage"
                else self.pipeline_axis,
                remat_policy=resolve_remat_policy(self.remat,
                                                  self.remat_policy),
                num_microbatches=self.num_microbatches, train=train,
                num_heads=self.num_heads, ffn_dim=self.ffn_dim,
                dtype=self.dtype, attention_impl=self.attention_impl,
                axis_name=self.axis_name, tp_size=self.tp_size,
                model_axis=self.model_axis, rope_theta=self.rope_theta,
                num_kv_heads=self.num_kv_heads,
                num_experts=self.num_experts,
                expert_axis=self.expert_axis, ep_size=self.ep_size,
                capacity_factor=self.capacity_factor)
        else:
            for i in range(self.num_layers):
                x = LlamaBlock(self.num_heads, self.ffn_dim,
                               dtype=self.dtype,
                               attention_impl=self.attention_impl,
                               axis_name=self.axis_name,
                               tp_size=self.tp_size,
                               model_axis=self.model_axis,
                               rope_theta=self.rope_theta,
                               num_kv_heads=self.num_kv_heads,
                               num_experts=self.num_experts,
                               expert_axis=self.expert_axis,
                               ep_size=self.ep_size,
                               capacity_factor=self.capacity_factor,
                               name=f"layer{i}")(x, train=train)
        if mode == "stage":
            return x
        return self._lm_head(x)

    def _lm_head(self, x):
        x = nn.RMSNorm(epsilon=1e-5, dtype=self.dtype, name="rms_f")(x)
        if self.tp_size > 1:
            x = copy_to_tp_region(x, self.model_axis)
        return nn.Dense(self.num_classes // self.tp_size, use_bias=False,
                        kernel_init=_init, dtype=self.dtype,
                        name="lm_head")(x)
