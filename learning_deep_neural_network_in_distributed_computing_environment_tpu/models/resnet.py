"""ResNet-18/50 (BASELINE.md config ladder entries 3 and 4).

Standard He-initialised ResNet v1 in NHWC with a selectable stem:
``cifar`` (3x3 conv, no max-pool — the right stem for 32x32 inputs, and the
shape the reference's own model family occupies) or ``imagenet`` (7x7/2 +
3x3/2 max-pool, for 224x224).  bfloat16 compute, fp32 BN + head.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

_he = nn.initializers.he_normal()


class BasicBlock(nn.Module):
    features: int
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool):
        # BN outputs follow the compute dtype: flax computes the statistics
        # in float32 internally either way, but a float32 BN output forces
        # every activation through HBM at twice the width; params/stats
        # stay fp32
        norm = lambda name: nn.BatchNorm(use_running_average=not train,
                                         momentum=0.9, epsilon=1e-5,
                                         dtype=self.dtype, name=name)
        conv = lambda f, k, s, name: nn.Conv(
            f, (k, k), strides=(s, s), padding=[(k // 2, k // 2)] * 2,
            use_bias=False, kernel_init=_he, dtype=self.dtype, name=name)
        out = nn.relu(norm("bn1")(conv(self.features, 3, self.stride,
                                       "conv1")(x)))
        out = norm("bn2")(conv(self.features, 3, 1, "conv2")(out))
        if self.stride != 1 or x.shape[-1] != self.features:
            x = norm("bn_sc")(conv(self.features, 1, self.stride, "conv_sc")(x))
        return nn.relu(out + jnp.asarray(x, out.dtype))


class Bottleneck(nn.Module):
    features: int  # bottleneck width; output is 4x
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool):
        # bf16 BN output (f32 stats internally) — see BasicBlock
        norm = lambda name: nn.BatchNorm(use_running_average=not train,
                                         momentum=0.9, epsilon=1e-5,
                                         dtype=self.dtype, name=name)
        conv = lambda f, k, s, name: nn.Conv(
            f, (k, k), strides=(s, s), padding=[(k // 2, k // 2)] * 2,
            use_bias=False, kernel_init=_he, dtype=self.dtype, name=name)
        out = nn.relu(norm("bn1")(conv(self.features, 1, 1, "conv1")(x)))
        out = nn.relu(norm("bn2")(conv(self.features, 3, self.stride,
                                       "conv2")(out)))
        out = norm("bn3")(conv(4 * self.features, 1, 1, "conv3")(out))
        if self.stride != 1 or x.shape[-1] != 4 * self.features:
            x = norm("bn_sc")(conv(4 * self.features, 1, self.stride,
                                   "conv_sc")(x))
        return nn.relu(out + jnp.asarray(x, out.dtype))


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block: type = BasicBlock
    num_classes: int = 1000
    stem: str = "imagenet"  # imagenet | cifar
    width: int = 64
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = jnp.asarray(x, self.dtype)
        if self.stem == "imagenet":
            x = nn.Conv(self.width, (7, 7), strides=(2, 2),
                        padding=[(3, 3)] * 2, use_bias=False, kernel_init=_he,
                        dtype=self.dtype, name="stem_conv")(x)
        else:
            x = nn.Conv(self.width, (3, 3), padding=[(1, 1)] * 2,
                        use_bias=False, kernel_init=_he, dtype=self.dtype,
                        name="stem_conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype, name="stem_bn")(x)
        x = nn.relu(x)
        if self.stem == "imagenet":
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1)] * 2)
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                stride = 2 if i > 0 and j == 0 else 1
                x = self.block(self.width * 2 ** i, stride=stride,
                               dtype=self.dtype,
                               name=f"stage{i + 1}_block{j}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, kernel_init=_he, dtype=jnp.float32,
                     name="fc")(jnp.asarray(x, jnp.float32))
        return x


def ResNet18(num_classes: int = 10, stem: str = "cifar",
             dtype: Any = jnp.float32, **kw):
    return ResNet(stage_sizes=(2, 2, 2, 2), block=BasicBlock,
                  num_classes=num_classes, stem=stem, dtype=dtype, **kw)


def ResNet50(num_classes: int = 1000, stem: str = "imagenet",
             dtype: Any = jnp.float32, **kw):
    return ResNet(stage_sizes=(3, 4, 6, 3), block=Bottleneck,
                  num_classes=num_classes, stem=stem, dtype=dtype, **kw)
