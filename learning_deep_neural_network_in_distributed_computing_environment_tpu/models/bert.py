"""BERT-base masked-LM (BASELINE.md config ladder entry 5).

A from-scratch flax implementation (no ``transformers`` dependency):
post-LN encoder, learned position embeddings, GELU FFN, untied MLM head.
Attention is factored through ``ops.attention.dot_product_attention`` so
the same model runs dense, flash (Pallas), or ring/sequence-parallel
attention (``parallel/sp.py``) without touching the module.

Defaults are BERT-base: 12 layers, hidden 768, 12 heads, FFN 3072,
vocab 30522, max position 512.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

_init = nn.initializers.normal(stddev=0.02)


class SelfAttention(nn.Module):
    num_heads: int
    dtype: Any = jnp.float32
    attention_impl: str = "dense"  # dense | flash | ring (set by parallel/sp)
    axis_name: Optional[str] = None  # mesh axis for ring attention

    @nn.compact
    def __call__(self, x, mask=None):
        from ..ops.attention import attend
        d = x.shape[-1]
        h = self.num_heads
        qkv = nn.DenseGeneral((3, h, d // h), kernel_init=_init,
                              dtype=self.dtype, name="qkv")(x)
        q, k, v = (qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :])
        out = attend(q, k, v, mask=mask, impl=self.attention_impl,
                     axis_name=self.axis_name)
        return nn.DenseGeneral(d, axis=(-2, -1), kernel_init=_init,
                               dtype=self.dtype, name="out")(out)


class EncoderLayer(nn.Module):
    num_heads: int
    ffn_dim: int
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, mask=None, *, train: bool = False):
        # post-LN (original BERT): sublayer -> residual -> LayerNorm
        a = SelfAttention(self.num_heads, dtype=self.dtype,
                          attention_impl=self.attention_impl,
                          axis_name=self.axis_name, name="attn")(x, mask)
        x = nn.LayerNorm(epsilon=1e-12, name="ln_attn")(x + a)
        f = nn.Dense(self.ffn_dim, kernel_init=_init, dtype=self.dtype,
                     name="ffn_in")(x)
        f = nn.gelu(f, approximate=False)
        f = nn.Dense(x.shape[-1], kernel_init=_init, dtype=self.dtype,
                     name="ffn_out")(f)
        return nn.LayerNorm(epsilon=1e-12, name="ln_ffn")(x + f)


class BertForMLM(nn.Module):
    """Token ids [B, L] -> MLM logits [B, L, vocab]."""

    num_classes: int = 30522       # vocab size (engine passes num_classes)
    num_layers: int = 12
    hidden: int = 768
    num_heads: int = 12
    ffn_dim: int = 3072
    max_len: int = 512
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, input_ids, *, train: bool = False):
        b, l = input_ids.shape
        tok = nn.Embed(self.num_classes, self.hidden, embedding_init=_init,
                       name="tok_emb")(input_ids)
        pos_ids = jnp.arange(l)
        if self.axis_name is not None:
            # sequence-parallel: this device holds chunk axis_index of the
            # sequence, so absolute positions are offset by index * chunk
            from jax import lax
            pos_ids = pos_ids + lax.axis_index(self.axis_name) * l
        pos = nn.Embed(self.max_len, self.hidden, embedding_init=_init,
                       name="pos_emb")(pos_ids[None, :])
        x = nn.LayerNorm(epsilon=1e-12, name="ln_emb")(tok + pos)
        x = jnp.asarray(x, self.dtype)
        for i in range(self.num_layers):
            x = EncoderLayer(self.num_heads, self.ffn_dim, dtype=self.dtype,
                             attention_impl=self.attention_impl,
                             axis_name=self.axis_name,
                             name=f"layer{i}")(x, train=train)
        # untied MLM head: transform + LayerNorm + decode
        x = jnp.asarray(x, jnp.float32)
        x = nn.Dense(self.hidden, kernel_init=_init, name="mlm_dense")(x)
        x = nn.gelu(x, approximate=False)
        x = nn.LayerNorm(epsilon=1e-12, name="mlm_ln")(x)
        return nn.Dense(self.num_classes, kernel_init=_init,
                        name="mlm_decoder")(x)
