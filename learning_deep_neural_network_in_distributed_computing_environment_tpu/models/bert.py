"""BERT-base masked-LM (BASELINE.md config ladder entry 5).

A from-scratch flax implementation (no ``transformers`` dependency):
post-LN encoder, learned position embeddings, GELU FFN, untied MLM head.
Attention is factored through ``ops.attention.attend`` so the same model
runs dense, flash (Pallas), or ring/all-to-all sequence-parallel attention
(``parallel/sp.py``) without touching the module.

Tensor parallelism (``parallel/tp.py``, Megatron construction): with
``tp_size > 1`` the module computes its LOCAL shard — ``num_heads/tp``
attention heads and ``ffn_dim/tp`` hidden units — and the row-parallel
output projections carry explicit biases added AFTER the cross-shard
reduction.  The dense module (``tp_size=1``) has the identical parameter
STRUCTURE, so a TP mesh run and a dense run share checkpoints: the global
parameter arrays are simply sharded over the ``model`` axis
(``tp_param_specs``).

Defaults are BERT-base: 12 layers, hidden 768, 12 heads, FFN 3072,
vocab 30522, max position 512.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..compat import checkpoint_name
from ..parallel.tp import copy_to_tp_region, reduce_from_tp_region

_init = nn.initializers.normal(stddev=0.02)


class SelfAttention(nn.Module):
    num_heads: int                 # GLOBAL head count
    dtype: Any = jnp.float32
    attention_impl: str = "dense"  # dense | flash | ring | all_to_all
    axis_name: Optional[str] = None   # mesh axis for seq-parallel attention
    tp_size: int = 1
    model_axis: Optional[str] = None  # mesh axis for tensor parallelism
    causal: bool = False           # autoregressive masking (decoder models)
    rope_theta: Optional[float] = None  # apply RoPE to q/k (Llama recipe)
    use_bias: bool = True          # False => no qkv / output biases (Llama)
    num_kv_heads: Optional[int] = None  # < num_heads => grouped-query
    #                                     attention (separate q / kv
    #                                     projections, kv heads shared by
    #                                     num_heads // num_kv_heads queries)

    @nn.compact
    def __call__(self, x, mask=None):
        from ..ops.attention import attend
        d = x.shape[-1]
        head_dim = d // self.num_heads
        if self.num_heads % self.tp_size:
            raise ValueError(
                f"num_heads {self.num_heads} not divisible by tp_size "
                f"{self.tp_size} (head-sharded tensor parallelism)")
        h_local = self.num_heads // self.tp_size
        x_in = copy_to_tp_region(x, self.model_axis)
        # falsy num_kv_heads (None or the config's 0 sentinel) means MHA
        gqa = bool(self.num_kv_heads) and self.num_kv_heads != self.num_heads
        if gqa:
            if self.num_heads % self.num_kv_heads:
                raise ValueError(
                    f"num_heads {self.num_heads} not divisible by "
                    f"num_kv_heads {self.num_kv_heads}")
            if self.num_kv_heads % self.tp_size:
                raise ValueError(
                    f"num_kv_heads {self.num_kv_heads} not divisible by "
                    f"tp_size {self.tp_size}")
            kv_local = self.num_kv_heads // self.tp_size
            q = nn.DenseGeneral((h_local, head_dim), kernel_init=_init,
                                use_bias=self.use_bias, dtype=self.dtype,
                                name="q")(x_in)
            kv = nn.DenseGeneral((2, kv_local, head_dim), kernel_init=_init,
                                 use_bias=self.use_bias, dtype=self.dtype,
                                 name="kv")(x_in)
            k, v = kv[..., 0, :, :], kv[..., 1, :, :]
        else:
            qkv = nn.DenseGeneral((3, h_local, head_dim), kernel_init=_init,
                                  use_bias=self.use_bias, dtype=self.dtype,
                                  name="qkv")(x_in)
            q, k, v = (qkv[..., 0, :, :], qkv[..., 1, :, :],
                       qkv[..., 2, :, :])
        if self.rope_theta is not None:
            from jax import lax
            from ..ops.attention import rope
            pos = jnp.arange(x.shape[1])
            if self.axis_name is not None:
                # sequence-parallel: this device holds chunk axis_index, so
                # absolute positions are offset by index * chunk length —
                # rotated keys travel the ring already position-encoded
                pos = pos + lax.axis_index(self.axis_name) * x.shape[1]
            q = rope(q, pos, self.rope_theta)
            k = rope(k, pos, self.rope_theta)
        # GQA K/V are passed GROUPED ([B, L, kv_local, D]) straight into
        # attend: every impl — dense (grouped einsum), flash kernel
        # (grouped block specs), ring (rep-x smaller rotating blocks),
        # Ulysses — consumes them without a repeat-to-full-heads expansion,
        # so the K/V bandwidth saving GQA exists for actually materializes
        out = attend(q, k, v, mask=mask, impl=self.attention_impl,
                     axis_name=self.axis_name, causal=self.causal)
        y = nn.DenseGeneral(d, axis=(-2, -1), kernel_init=_init,
                            use_bias=False, dtype=self.dtype,
                            name="out")(out)
        y = reduce_from_tp_region(y, self.model_axis)
        if not self.use_bias:
            return y
        return y + self.param("out_bias", nn.initializers.zeros,
                              (d,)).astype(y.dtype)


class EncoderLayer(nn.Module):
    num_heads: int
    ffn_dim: int                   # GLOBAL FFN width
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    axis_name: Optional[str] = None
    tp_size: int = 1
    model_axis: Optional[str] = None
    num_experts: int = 0           # >0 => MoE FFN (models/moe.py)
    expert_axis: Optional[str] = None
    ep_size: int = 1
    capacity_factor: float = 1.25

    @nn.compact
    def __call__(self, x, mask=None, *, train: bool = False, aux_scale=1.0):
        # post-LN (original BERT): sublayer -> residual -> LayerNorm.
        # checkpoint_name labels (ISSUE 15: attn_out / mlp_out /
        # block_out, models.REMAT_NAMES) mark the activations a
        # --remat_policy save_names:/offload_names: set may pin on
        # device / offload to host — inert identities otherwise
        a = checkpoint_name(
            SelfAttention(self.num_heads, dtype=self.dtype,
                          attention_impl=self.attention_impl,
                          axis_name=self.axis_name, tp_size=self.tp_size,
                          model_axis=self.model_axis, name="attn")(x, mask),
            "attn_out")
        # LN output follows the compute dtype (flax does the mean/var math
        # in f32 internally); an f32 LN output would round-trip every
        # activation through HBM at twice the width
        x = nn.LayerNorm(epsilon=1e-12, dtype=self.dtype, name="ln_attn")(x + a)
        if self.num_experts:
            from .moe import MoEFFN
            f = MoEFFN(self.num_experts, self.ffn_dim,
                       capacity_factor=self.capacity_factor,
                       dtype=self.dtype, expert_axis=self.expert_axis,
                       ep_size=self.ep_size, tp_size=self.tp_size,
                       model_axis=self.model_axis, name="moe")(
                           x, train=train, aux_scale=aux_scale)
        else:
            if self.ffn_dim % self.tp_size:
                raise ValueError(
                    f"ffn_dim {self.ffn_dim} not divisible by tp_size "
                    f"{self.tp_size} (column-parallel FFN)")
            f_in = copy_to_tp_region(x, self.model_axis)
            f = nn.Dense(self.ffn_dim // self.tp_size, kernel_init=_init,
                         dtype=self.dtype, name="ffn_in")(f_in)
            f = nn.gelu(f, approximate=False)
            f = nn.Dense(x.shape[-1], kernel_init=_init, use_bias=False,
                         dtype=self.dtype, name="ffn_out")(f)
            f = reduce_from_tp_region(f, self.model_axis)
            f = f + self.param("ffn_bias", nn.initializers.zeros,
                               (x.shape[-1],)).astype(f.dtype)
        f = checkpoint_name(f, "mlp_out")
        return checkpoint_name(
            nn.LayerNorm(epsilon=1e-12, dtype=self.dtype,
                         name="ln_ffn")(x + f), "block_out")


class _ScanLayer(nn.Module):
    """carry-API adapter so ``nn.scan`` can stack EncoderLayers.  The
    second (broadcast) argument is the MoE aux-loss scale — None outside
    the GPipe schedule, bubble-masked ``valid / num_microbatches`` inside
    it (parallel/pp.py)."""

    num_heads: int
    ffn_dim: int
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    axis_name: Optional[str] = None
    tp_size: int = 1
    model_axis: Optional[str] = None
    num_experts: int = 0
    expert_axis: Optional[str] = None
    ep_size: int = 1
    capacity_factor: float = 1.25
    train: bool = False

    @nn.compact
    def __call__(self, x, aux_scale):
        y = EncoderLayer(self.num_heads, self.ffn_dim, dtype=self.dtype,
                         attention_impl=self.attention_impl,
                         axis_name=self.axis_name, tp_size=self.tp_size,
                         model_axis=self.model_axis,
                         num_experts=self.num_experts,
                         expert_axis=self.expert_axis,
                         ep_size=self.ep_size,
                         capacity_factor=self.capacity_factor,
                         name="layer")(
                             x, train=self.train,
                             aux_scale=1.0 if aux_scale is None
                             else aux_scale)
        return y, None


def resolve_remat_policy(remat: bool, remat_policy):
    """Effective named policy from the legacy bool + the named flag:
    ``remat_policy`` wins when set; ``remat=True`` is the "everything"
    alias; falsy/"none" means no rematerialization."""
    if remat_policy and remat_policy != "none":
        return remat_policy
    return "everything" if remat else None


def apply_scanned_stack(scan_layer_cls, x, *, num_layers: int, pp_size: int,
                        pipeline_axis, num_microbatches: int, train: bool,
                        remat_policy=None, **layer_kw):
    """``nn.scan`` the stacked ``layers`` collection and run it plain or as
    a GPipe schedule — shared by BERT/GPT/ViT/Llama.  The stacked
    collection's leading [num_layers] axis is what ``pp_param_specs``
    shards over ``pipe``; with a ``pipeline_axis`` this device applies its
    ``num_layers // pp_size`` local layers per schedule step.

    MoE composes: ``variable_axes['aux'] = 0`` stacks each layer's sown
    load-balance loss along the scan axis (the engine sums leaves fully),
    and the broadcast second argument carries the GPipe bubble mask down
    to ``MoEFFN.aux_scale``."""
    if num_layers % pp_size:
        raise ValueError(f"num_layers {num_layers} not divisible "
                         f"by pp_size {pp_size}")
    n_local = num_layers // pp_size
    cls = scan_layer_cls
    if remat_policy and remat_policy != "none":
        # rematerialize each layer on the backward pass under a named
        # jax.checkpoint policy: "everything" saves only the layer-
        # boundary activations (the GPipe paper's own memory recipe,
        # ~1/3 extra forward compute); "dots_saveable" keeps matmul
        # outputs and recomputes only the cheap elementwise chains
        # between them (the pjit/TPUv4 selective-remat default);
        # "save_names:<set>" / "offload_names:<set>" (ISSUE 15) keep
        # exactly the checkpoint_name-annotated activations in the set
        # on device / offloaded to pinned host memory (compat.py
        # demotes offload to same-set save on backends without a host
        # memory space)
        from ..compat import checkpoint_policy
        policy = checkpoint_policy(remat_policy)
        remat_kw = {} if policy is None else {"policy": policy}
        cls = nn.remat(scan_layer_cls, prevent_cse=False, **remat_kw)
    scanned = nn.scan(
        cls, variable_axes={"params": 0, "aux": 0},
        split_rngs={"params": True}, in_axes=nn.broadcast,
        length=n_local)(
            train=train, name="layers", **layer_kw)
    if pipeline_axis is None:
        return scanned(x, None)[0]
    from ..parallel.pp import gpipe_apply_scanned
    return gpipe_apply_scanned(scanned, x, pipeline_axis, pp_size,
                               num_microbatches)


class BertForMLM(nn.Module):
    """Token ids [B, L] -> MLM logits [B, L, vocab].

    ``scan_layers=True`` stores the encoder stack STACKED (one ``layers``
    collection with a leading [num_layers] axis, applied via ``nn.scan``)
    instead of ``layer{i}`` loop unrolling — required for pipeline
    parallelism (the layer axis is what shards over ``pipe``) and much
    faster to compile at depth.  ``pipeline_axis``/``pp_size`` run the
    stack as a GPipe schedule (``parallel/pp.py``): this device applies
    its ``num_layers/pp_size`` local layers per schedule step.
    """

    num_classes: int = 30522       # vocab size (engine passes num_classes)
    num_layers: int = 12
    hidden: int = 768
    num_heads: int = 12
    ffn_dim: int = 3072
    max_len: int = 512
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    axis_name: Optional[str] = None
    tp_size: int = 1
    model_axis: Optional[str] = None
    scan_layers: bool = False
    pipeline_axis: Optional[str] = None
    pp_size: int = 1               # pipe-axis size (static; local layer
    #                                count = num_layers // pp_size)
    num_microbatches: int = 0      # 0 => pp_size
    remat: bool = False            # [compat alias] remat_policy="everything"
    remat_policy: Optional[str] = None  # none | dots_saveable | everything
    num_experts: int = 0           # >0 => MoE FFN in every layer
    expert_axis: Optional[str] = None
    ep_size: int = 1
    capacity_factor: float = 1.25

    # class marker (not a field): with tp_size > 1 this model's output is
    # its LOCAL vocab slice and the loss must be vocab-parallel
    vocab_parallel_head = True

    @nn.compact
    def __call__(self, input_ids, *, train: bool = False,
                 mode: str = "full"):
        """``mode`` partitions the forward for the 1F1B engine path
        (parallel/pp.py): 'embed' -> embedded activations, 'stage' ->
        apply this device's local scanned layers to activations (no
        pipeline schedule), 'head' -> MLM transform + decode on
        activations.  'full' (default) is the ordinary forward; init
        always uses it so every mode shares one parameter structure."""
        if self.tp_size > 1 and self.num_classes % self.tp_size:
            raise ValueError(
                f"vocab size {self.num_classes} not divisible by tp_size "
                f"{self.tp_size} (vocab-parallel MLM head)")
        if mode == "stage":
            return self._encode_scanned(input_ids, train, as_stage=True)
        if mode == "head":
            return self._mlm_head(input_ids)
        b, l = input_ids.shape
        tok = nn.Embed(self.num_classes, self.hidden, embedding_init=_init,
                       name="tok_emb")(input_ids)
        pos_ids = jnp.arange(l)
        if self.axis_name is not None:
            # sequence-parallel: this device holds chunk axis_index of the
            # sequence, so absolute positions are offset by index * chunk
            from jax import lax
            pos_ids = pos_ids + lax.axis_index(self.axis_name) * l
        pos = nn.Embed(self.max_len, self.hidden, embedding_init=_init,
                       name="pos_emb")(pos_ids[None, :])
        x = nn.LayerNorm(epsilon=1e-12, name="ln_emb")(tok + pos)
        x = jnp.asarray(x, self.dtype)
        if mode == "embed":
            return x
        if self.scan_layers:
            x = self._encode_scanned(x, train)
        else:
            for i in range(self.num_layers):
                x = EncoderLayer(self.num_heads, self.ffn_dim,
                                 dtype=self.dtype,
                                 attention_impl=self.attention_impl,
                                 axis_name=self.axis_name,
                                 tp_size=self.tp_size,
                                 model_axis=self.model_axis,
                                 num_experts=self.num_experts,
                                 expert_axis=self.expert_axis,
                                 ep_size=self.ep_size,
                                 capacity_factor=self.capacity_factor,
                                 name=f"layer{i}")(x, train=train)
        return self._mlm_head(x)

    def _mlm_head(self, x):
        # untied MLM head: transform + LayerNorm + decode.  The head runs
        # in the compute dtype: at bf16 the [*, hidden, vocab] decode
        # matmul hits the MXU's full bf16 rate and the [B, L, vocab]
        # logits cost half the HBM; the loss upcasts to f32 for the
        # log-softmax either way (train.softmax_cross_entropy).
        # Under tensor parallelism the decode is VOCAB-PARALLEL (Megatron):
        # each shard computes logits for its vocab slice and the engine's
        # loss uses parallel.tp.vocab_parallel_token_stats — the full
        # [B, L, V] logits never materialize on one device.
        x = nn.Dense(self.hidden, kernel_init=_init, dtype=self.dtype,
                     name="mlm_dense")(x)
        x = nn.gelu(x, approximate=False)
        x = nn.LayerNorm(epsilon=1e-12, dtype=self.dtype, name="mlm_ln")(x)
        if self.tp_size > 1:
            x = copy_to_tp_region(x, self.model_axis)
        return nn.Dense(self.num_classes // self.tp_size, kernel_init=_init,
                        dtype=self.dtype, name="mlm_decoder")(x)

    def _encode_scanned(self, x, train: bool, as_stage: bool = False):
        return apply_scanned_stack(
            _ScanLayer, x, num_layers=self.num_layers, pp_size=self.pp_size,
            pipeline_axis=None if as_stage else self.pipeline_axis,
            remat_policy=resolve_remat_policy(self.remat, self.remat_policy),
            num_microbatches=self.num_microbatches, train=train,
            num_heads=self.num_heads, ffn_dim=self.ffn_dim,
            dtype=self.dtype, attention_impl=self.attention_impl,
            axis_name=self.axis_name, tp_size=self.tp_size,
            model_axis=self.model_axis, num_experts=self.num_experts,
            expert_axis=self.expert_axis, ep_size=self.ep_size,
            capacity_factor=self.capacity_factor)


def _tp_parts(names: list, ndim: int, axis: str,
              shard_tok_emb: bool = False):
    """Megatron sharding pattern for one leaf, as a parts list of length
    ``ndim`` (the UNSTACKED leaf rank — callers with a leading layer dim
    pass ``leaf.ndim - 1``).

    ``shard_tok_emb``: shard the token-embedding table's VOCAB dim — the
    vocab-parallel TIED head (GPT: the same table is the decode matrix,
    so sharding it shards both the lookup and the logits; models/gpt.py
    ``_embed``).  BERT/Llama keep their lookup tables replicated (their
    decodes are separate vocab-parallel Dense kernels).

    qkv kernel [H, 3, heads, hd] / bias [3, heads, hd]: heads dim sharded;
    attn out kernel [heads, hd, H] and ffn_out kernel [F, H]: dim 0 sharded
    (row-parallel); ffn_in kernel [H, F] / bias [F]: F sharded (column-
    parallel); the MLM decode is vocab-parallel (kernel [H, V]: V sharded
    — column-parallel over the vocabulary); everything else (embeddings,
    LNs, post-reduce biases, the MLM transform) replicated.
    """
    parts = [None] * ndim
    if "moe" in names:
        # MoE x TP (models/moe.py): per-expert Megatron sharding on the F
        # dim — w1 [E, H, F] / b1 [E, F] column-parallel, w2 [E, F, H]
        # row-parallel; gate and b2 (post-psum bias) replicated.  The
        # leading E dim is the EXPERT dim (overlaid with the 'expert' axis
        # by moe.with_expert_overlay when EP is also on).
        if "w1" in names and ndim == 3:
            parts[2] = axis
        elif "b1" in names and ndim == 2:
            parts[1] = axis
        elif "w2" in names and ndim == 3:
            parts[1] = axis
        return parts
    if "qkv" in names:
        parts[2 if ndim == 4 else 1] = axis
    elif "q" in names:
        # GQA query projection: kernel [H, heads, hd] / bias [heads, hd]
        parts[1 if ndim == 3 else 0] = axis
    elif "kv" in names:
        # GQA kv projection: kernel [H, 2, kv_heads, hd] / bias [2, kv, hd]
        parts[2 if ndim == 4 else 1] = axis
    elif "out" in names and ndim == 3:   # kernel [heads, hd, H]
        parts[0] = axis
    elif "ffn_in" in names or "ffn_up" in names:
        # column-parallel: ffn_in kernel [H, F] / bias [F]; ffn_up is the
        # SwiGLU second input projection (models/llama.py), same pattern
        parts[1 if ndim == 2 else 0] = axis
    elif "ffn_out" in names and ndim == 2:   # kernel [F, H]
        parts[0] = axis
    elif "mlm_decoder" in names or "lm_head" in names:
        # vocab-parallel decode: kernel [H, V] / bias [V]
        parts[1 if ndim == 2 else 0] = axis
    elif shard_tok_emb and "tok_emb" in names and ndim == 2:
        parts[0] = axis              # embedding table [V, H]: V sharded
    return parts


def tp_param_specs(params, axis: str = "model", *,
                   shard_tok_emb: bool = False):
    """PartitionSpec tree sharding BERT parameters over the TP ``axis``
    (no worker axis — the engine prepends it); pattern in ``_tp_parts``.

    Handles BOTH parameter layouts: unrolled ``layer{i}`` trees and the
    ``layer_scan`` stacked ``layers`` collection, whose leaves carry a
    leading [num_layers] dim (unsharded here — ``pp_tp_param_specs`` is
    the twin that puts it on ``pipe``) with the Megatron pattern applied
    to the inner dims."""
    from jax.sharding import PartitionSpec as P

    def spec(path, leaf):
        names = [getattr(p, "key", str(p)) for p in path]
        if "layers" in names:
            return P(None, *_tp_parts(names, leaf.ndim - 1, axis))
        return P(*_tp_parts(names, leaf.ndim, axis,
                            shard_tok_emb=shard_tok_emb))
    return jax.tree_util.tree_map_with_path(spec, params)


def pp_tp_param_specs(params, *, pipe_axis: str = "pipe",
                      axis: str = "model", shard_tok_emb: bool = False):
    """PartitionSpec tree for a ``scan_layers`` model under BOTH pipeline
    and tensor parallelism: leaves under the stacked ``layers`` collection
    shard their leading (layer) dim over ``pipe_axis`` AND their inner dims
    per the Megatron pattern; everything outside the stack (embeddings,
    the vocab-parallel MLM decode) gets the plain TP pattern."""
    from jax.sharding import PartitionSpec as P

    def spec(path, leaf):
        names = [getattr(p, "key", str(p)) for p in path]
        if "layers" in names:
            return P(pipe_axis, *_tp_parts(names, leaf.ndim - 1, axis))
        return P(*_tp_parts(names, leaf.ndim, axis,
                            shard_tok_emb=shard_tok_emb))
    return jax.tree_util.tree_map_with_path(spec, params)
