"""Vision Transformer (beyond-reference model family).

The reference's only model is a CNN (``Balanced All-Reduce/model.py:74-111``);
this adds the transformer vision family on top of the SAME encoder stack as
BERT/GPT (``models/bert.py:EncoderLayer``), so every encoder capability —
flash attention, Megatron tensor parallelism, GPipe pipeline parallelism
(``scan_layers``), Switch-MoE FFNs — composes with image classification for
free.  Sequence parallelism is the one exclusion: the engine's seq-sharded
input packs are token ids, not images.

TPU-first patchify: a reshape + one Dense (``[B, N, p*p*c] @ [p*p*c, H]``)
instead of the usual stride-p conv — identical math for non-overlapping
patches, and it lowers to a single MXU matmul with no small-channel conv
edge cases.

Defaults are ViT-S/16 (12 layers, hidden 384, 6 heads, FFN 1536) — the
matmul-dominated geometry that actually exercises the MXU at high
utilization, unlike the HBM-roofline-bound ResNets.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from .bert import EncoderLayer, _ScanLayer, _init


class _PatchEmbed(nn.Module):
    """Patch embedding as a single einsum over the 6-D patch view.

    Parameter-compatible with ``nn.Dense(hidden, name="patch_embed")``
    (same ``kernel`` [p*p*c, H] / ``bias`` [H] leaves): the kernel is
    viewed as [p, p, c, H] at apply time and contracted directly against
    ``x.reshape(b, h/p, p, w/p, p, c)`` — no explicit 6-D transpose for
    XLA to materialize in either the forward or its backward."""

    features: int
    patch: int
    channels: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x6):
        kernel = self.param("kernel", _init,
                            (self.patch * self.patch * self.channels,
                             self.features))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        k4 = kernel.reshape(self.patch, self.patch, self.channels,
                            self.features).astype(self.dtype)
        y = jnp.einsum("bipjqc,pqch->bijh", x6, k4)
        return y + bias.astype(self.dtype)


class ViT(nn.Module):
    """Images [B, H, W, C] -> class logits [B, num_classes]."""

    num_classes: int = 1000
    patch: int = 16
    num_layers: int = 12
    hidden: int = 384
    num_heads: int = 6
    ffn_dim: int = 1536
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    tp_size: int = 1
    model_axis: Optional[str] = None
    scan_layers: bool = False
    pipeline_axis: Optional[str] = None
    pp_size: int = 1
    num_microbatches: int = 0
    remat: bool = False            # [compat alias] remat_policy="everything"
    remat_policy: Optional[str] = None  # none | dots_saveable | everything
    num_experts: int = 0
    expert_axis: Optional[str] = None
    ep_size: int = 1
    capacity_factor: float = 1.25
    # patchify lowering (r5, VERDICT r4 'next' #3 — the trace's 22%
    # "output/data-fmt" category): 'einsum' contracts the 6-D patch view
    # against the [p, p, c, H] view of the SAME [p*p*c, H] kernel, letting
    # XLA fold the patch relayout into the matmul's operand load instead
    # of being handed an explicit 6-D transpose whose backward is another
    # full relayout.  'reshape' keeps the r4 lowering (A/B twin).  The
    # parameter structure is identical either way.
    patchify: str = "einsum"

    @nn.compact
    def __call__(self, x, *, train: bool = False, mode: str = "full"):
        """``mode`` partitions the forward for the 1F1B engine path
        (parallel/pp.py): 'embed' -> patchified + position-embedded
        activations, 'stage' -> apply this device's local scanned
        layers, 'head' -> mean-pool + classifier on activations.
        'full' (default) is the ordinary forward; init always uses it
        so every mode shares one parameter structure."""
        if mode == "stage":
            return self._encode_scanned(x, train, as_stage=True)
        if mode == "head":
            return self._head(x)
        b, h, w, c = x.shape
        p = self.patch
        if h % p or w % p:
            raise ValueError(f"input {h}x{w} not divisible by patch {p}")
        x = jnp.asarray(x, self.dtype)
        n = (h // p) * (w // p)
        if self.patchify == "einsum":
            x6 = x.reshape(b, h // p, p, w // p, p, c)
            x = _PatchEmbed(self.hidden, p, c, dtype=self.dtype,
                            name="patch_embed")(x6).reshape(b, n, self.hidden)
        else:
            # non-overlapping patchify as reshape + matmul (module docstring)
            x = x.reshape(b, h // p, p, w // p, p, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, n, p * p * c)
            x = nn.Dense(self.hidden, kernel_init=_init, dtype=self.dtype,
                         name="patch_embed")(x)
        pos = self.param("pos_emb", _init, (1, x.shape[1], self.hidden))
        x = x + pos.astype(x.dtype)
        if mode == "embed":
            return x
        if self.scan_layers:
            x = self._encode_scanned(x, train)
        else:
            for i in range(self.num_layers):
                x = EncoderLayer(self.num_heads, self.ffn_dim,
                                 dtype=self.dtype,
                                 attention_impl=self.attention_impl,
                                 tp_size=self.tp_size,
                                 model_axis=self.model_axis,
                                 num_experts=self.num_experts,
                                 expert_axis=self.expert_axis,
                                 ep_size=self.ep_size,
                                 capacity_factor=self.capacity_factor,
                                 name=f"layer{i}")(x, train=train)
        return self._head(x)

    def _head(self, x):
        x = x.mean(axis=1)  # global average pool over patches
        return nn.Dense(self.num_classes, kernel_init=_init,
                        dtype=jnp.float32, name="head")(
                            jnp.asarray(x, jnp.float32))

    def _encode_scanned(self, x, train: bool, as_stage: bool = False):
        from .bert import apply_scanned_stack, resolve_remat_policy
        return apply_scanned_stack(
            _ScanLayer, x, num_layers=self.num_layers, pp_size=self.pp_size,
            pipeline_axis=None if as_stage else self.pipeline_axis,
            remat_policy=resolve_remat_policy(self.remat, self.remat_policy),
            num_microbatches=self.num_microbatches, train=train,
            num_heads=self.num_heads, ffn_dim=self.ffn_dim,
            dtype=self.dtype, attention_impl=self.attention_impl,
            tp_size=self.tp_size, model_axis=self.model_axis,
            num_experts=self.num_experts, expert_axis=self.expert_axis,
            ep_size=self.ep_size, capacity_factor=self.capacity_factor)
