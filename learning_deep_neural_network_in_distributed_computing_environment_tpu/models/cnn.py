"""The reference's flagship model, rebuilt TPU-first in flax.linen.

Capability parity with ``Balanced All-Reduce/model.py:52-111``
(``EnhancedCNNModel``): a ResNet-style CNN for 32x32x3 -> 10 classes —
prep conv 3->64 + BN + ReLU; four stages of two residual blocks each
(64->128->256->512->1024, first block of each stage stride 2, 1x1-conv
shortcut on shape change); global average pool; FC 1024->10.
Trainable parameter count matches torch exactly: 44,595,786.

TPU-first choices (deliberately not a translation):
- NHWC layout (TPU conv layout; torch uses NCHW),
- parameterized compute dtype (bfloat16 on the MXU by default, params fp32),
- BatchNorm statistics kept per data-parallel worker, never synced during
  training — matching the reference's local-SGD semantics where only
  ``model.parameters()`` are averaged (``communication.py:5,22``) while the
  initial broadcast covers buffers too (``main.py:40-46``).

Weight init parity: Xavier-uniform for conv/linear kernels, zero biases
(``Balanced All-Reduce/main.py:33-37``).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

Dtype = Any

_xavier = nn.initializers.xavier_uniform()


class ResBlock(nn.Module):
    """Residual block: conv3x3(s)-BN-ReLU-conv3x3-BN + shortcut, ReLU.

    Shortcut is a 1x1 conv + BN when stride != 1 or channels change
    (ref model.py:52-72).
    """

    features: int
    stride: int = 1
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool) -> jnp.ndarray:
        in_features = x.shape[-1]
        # BN outputs follow the compute dtype (flax keeps the mean/var math
        # in float32 regardless); an fp32 BN output would force every
        # activation through HBM at twice the width
        norm = lambda name: nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5,
            dtype=self.dtype, name=name)
        conv = lambda feats, k, s, name: nn.Conv(
            feats, (k, k), strides=(s, s), padding=[(k // 2, k // 2)] * 2,
            use_bias=False, kernel_init=_xavier, dtype=self.dtype, name=name)

        out = conv(self.features, 3, self.stride, "conv1")(x)
        out = nn.relu(norm("bn1")(out))
        out = conv(self.features, 3, 1, "conv2")(out)
        out = norm("bn2")(out)

        if self.stride != 1 or in_features != self.features:
            sc = conv(self.features, 1, self.stride, "shortcut_conv")(x)
            sc = norm("shortcut_bn")(sc)
        else:
            sc = x
        return nn.relu(out + jnp.asarray(sc, out.dtype))


class EnhancedCNNModel(nn.Module):
    """ResNet-18-style CNN for CIFAR-10 (ref model.py:74-111).

    Stages: prep(3->64), [64->128, 128], [->256, 256], [->512, 512],
    [->1024, 1024] with stride-2 first blocks; GAP; Dense(10).
    """

    num_classes: int = 10
    width: int = 64  # channel multiplier base; 64 == reference
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        w = self.width
        x = jnp.asarray(x, self.dtype)
        x = nn.Conv(w, (3, 3), padding=[(1, 1), (1, 1)], use_bias=False,
                    kernel_init=_xavier, dtype=self.dtype, name="prep_conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype, name="prep_bn")(x)
        x = nn.relu(x)
        for i, feats in enumerate((2 * w, 4 * w, 8 * w, 16 * w)):
            x = ResBlock(feats, stride=2, dtype=self.dtype,
                         name=f"layer{i + 1}_block0")(x, train=train)
            x = ResBlock(feats, stride=1, dtype=self.dtype,
                         name=f"layer{i + 1}_block1")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool (AdaptiveAvgPool(1,1))
        x = nn.Dense(self.num_classes, kernel_init=_xavier,
                     bias_init=nn.initializers.zeros, dtype=jnp.float32,
                     name="fc")(jnp.asarray(x, jnp.float32))
        return x
