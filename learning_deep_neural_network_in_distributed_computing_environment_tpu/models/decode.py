"""Cache-aware autoregressive decode over the scanned-layer stack.

The serving twin of the training forward (ISSUE 7): the same stacked
parameters the layer-scan compile engine stores (``layers/layer`` with a
leading [num_layers] axis) applied token-incrementally against a **paged
KV cache** instead of recomputing the whole sequence per token.

Layout (vLLM-style paged attention, formulated as dense XLA gathers — no
custom kernel, so it runs on every backend the repo tests on):

- the cache is one pool of ``num_pages`` fixed-size pages per layer:
  ``k/v [num_layers, num_pages, page_size, kv_heads, head_dim]``;
- each sequence owns a **page table** row ``[pages_per_seq]`` of page ids
  mapping global position ``p`` to ``(table[p // page_size],
  p % page_size)``;
- page id 0 is the **trash page**: the allocator never hands it out, and
  every masked write (prefill padding beyond the prompt, inactive decode
  slots) is routed there, so the compiled programs stay fixed-shape with
  no conditionals;
- attention gathers a slot's pages back into a ``[pages_per_seq *
  page_size]`` key/value run and applies the **cache-offset causal
  mask** ``kpos <= q_position`` — stale data on recycled pages sits at
  positions the mask excludes, so pages never need zeroing between
  sequences.

``forward_paged`` is ONE function covering both serving programs: prefill
calls it with ``[1, bucket]`` tokens at ``lengths == 0``, the decode step
with ``[max_batch, 1]`` tokens at the current lengths.  The layer stack
runs under ``lax.scan`` (carry = activations, per-layer cache slices as
scanned inputs/outputs), so the block traces once at any depth — the
PR 3 compile story carried over to inference.

Numerics: the block math here mirrors ``models/gpt.py`` /
``models/llama.py`` / ``models/moe.py`` operation-for-operation (same
einsum formulations, fp32 softmax/normalizer, same dtype casts).
``tests/test_serve.py`` gates paged logits against the full-sequence
``model.apply`` forward at fp32 tolerance with argmax equality.  MoE
decode routes each token to its top-1 expert WITHOUT a capacity limit
(a decode step has no token queue to overflow); it matches the training
forward whenever the forward's capacity dropped nothing.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import dot_product_attention, rope

TRASH_PAGE = 0   # reserved page id for masked writes (never allocated)


@dataclasses.dataclass(frozen=True)
class DecodeSpec:
    """Static architecture facts the decode program needs — derived from
    a model instance (``spec_from_model``), never restated by the user."""

    family: str                  # "gpt" | "llama"
    num_layers: int
    hidden: int
    num_heads: int
    num_kv_heads: int            # == num_heads for MHA
    head_dim: int
    vocab: int
    max_len: int                 # gpt position-table bound (0 = unbounded)
    rope_theta: float            # llama
    num_experts: int             # > 0 => MoE FFN blocks
    dtype: Any = jnp.float32


def spec_from_model(model) -> DecodeSpec:
    """Build the decode spec for a supported autoregressive model."""
    fam = {"GPTForCausalLM": "gpt", "LlamaForCausalLM": "llama"}.get(
        type(model).__name__)
    if fam is None:
        raise ValueError(
            f"serving supports the autoregressive families (gpt_*/llama_*, "
            f"optionally MoE); got model class {type(model).__name__} — "
            "bert/vit/cnn models have no decode path")
    if not getattr(model, "scan_layers", False):
        raise ValueError(
            "serving decodes over the STACKED layer collection "
            "(layer_scan); rebuild the model with scan_layers=True — "
            "training checkpoints of the autoregressive families use the "
            "stacked layout by default (--layer_scan auto)")
    if getattr(model, "tp_size", 1) > 1 or model.axis_name is not None:
        raise ValueError("serving runs the single-replica dense twin; "
                         "TP/SP train-model variants are not servable")
    kv = getattr(model, "num_kv_heads", None) or model.num_heads
    return DecodeSpec(
        family=fam, num_layers=model.num_layers, hidden=model.hidden,
        num_heads=model.num_heads, num_kv_heads=kv,
        head_dim=model.hidden // model.num_heads,
        vocab=model.num_classes,
        max_len=getattr(model, "max_len", 0) or 0,
        rope_theta=getattr(model, "rope_theta", 10000.0),
        num_experts=getattr(model, "num_experts", 0),
        dtype=model.dtype)


def init_paged_cache(spec: DecodeSpec, num_pages: int, page_size: int):
    """Zeroed (k, v) page pools [L, P, page_size, KV, head_dim]."""
    shape = (spec.num_layers, num_pages, page_size, spec.num_kv_heads,
             spec.head_dim)
    return (jnp.zeros(shape, spec.dtype), jnp.zeros(shape, spec.dtype))


# ----------------------------------------------------------------------
# Shared numerics (mirrors of the flax modules' math)
# ----------------------------------------------------------------------

def _layernorm(x, scale, bias, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True) - mu * mu
    y = (xf - mu) * lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def _rmsnorm(x, scale, eps=1e-5):
    xf = x.astype(jnp.float32)
    y = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (y * scale.astype(jnp.float32)).astype(x.dtype)


def paged_attend(q, k_new, v_new, *, positions, num_valid, page_table,
                 k_pages, v_pages):
    """The cache-aware attention core shared by prefill and decode.

    ``q/k_new/v_new`` [B, T, H|KV, D] are this call's projections at
    global ``positions`` [B, T]; the new K/V are scattered into the page
    pool first (rows ``i >= num_valid[b]`` — prefill padding, inactive
    slots — go to the trash page), then each slot's table is gathered
    back to a [S = pages_per_seq * page_size] run and attended under the
    cache-offset causal mask ``kpos <= position``.  Returns
    ``(out [B, T, H, D], k_pages', v_pages')``.
    """
    b, t = q.shape[:2]
    page_size = k_pages.shape[1]
    pages_per_seq = page_table.shape[1]
    flat_pos = positions.reshape(b, t)
    page_idx = jnp.clip(flat_pos // page_size, 0, pages_per_seq - 1)
    dest_page = jnp.take_along_axis(page_table, page_idx, axis=1)  # [B, T]
    valid = jnp.arange(t, dtype=jnp.int32)[None, :] < num_valid[:, None]
    dest_page = jnp.where(valid, dest_page, TRASH_PAGE).reshape(-1)
    dest_row = (flat_pos % page_size).reshape(-1)
    kv_shape = (b * t, *k_new.shape[2:])
    k_pages = k_pages.at[dest_page, dest_row].set(k_new.reshape(kv_shape))
    v_pages = v_pages.at[dest_page, dest_row].set(v_new.reshape(kv_shape))
    # gather each slot's pages into a contiguous [S] key/value run
    s = pages_per_seq * page_size
    k_all = k_pages[page_table].reshape(b, s, *k_pages.shape[2:])
    v_all = v_pages[page_table].reshape(b, s, *v_pages.shape[2:])
    kpos = jnp.arange(s, dtype=jnp.int32)
    mask = kpos[None, None, None, :] <= positions[:, None, :, None]
    out = dot_product_attention(q, k_all, v_all, mask=mask)
    return out, k_pages, v_pages


# ----------------------------------------------------------------------
# Per-family block decode (one scanned layer)
# ----------------------------------------------------------------------

def _dense_general(x, kernel, bias=None):
    """flax DenseGeneral over the trailing feature dim: contract x's last
    axis with kernel dim 0, appending the kernel's remaining dims."""
    y = lax.dot_general(x, kernel,
                        (((x.ndim - 1,), (0,)), ((), ())))
    return y if bias is None else y + bias


def _moe_ffn(mp, x, dtype):
    """Top-1 expert FFN, capacity-free (decode twin of models/moe.py:
    identical gate/expert math, no token queue to cap — see module doc)."""
    b, t, h = x.shape
    toks = x.reshape(b * t, h)
    gate_logits = toks.astype(jnp.float32) @ mp["gate"]["kernel"].astype(
        jnp.float32)
    probs = jax.nn.softmax(gate_logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)
    gate = jnp.max(probs, axis=-1)
    onehot = jax.nn.one_hot(expert_idx, probs.shape[-1], dtype=jnp.float32)
    w1, b1 = mp["w1"].astype(dtype), mp["b1"].astype(dtype)
    w2, b2 = mp["w2"].astype(dtype), mp["b2"].astype(dtype)
    h1 = jax.nn.gelu(jnp.einsum("nh,ehf->nef", toks.astype(dtype), w1)
                     + b1[None], approximate=False)
    ye = jnp.einsum("nef,efh->neh", h1, w2) + b2[None]
    combine = (onehot * gate[:, None]).astype(dtype)
    return jnp.einsum("ne,neh->nh", combine, ye).reshape(b, t, h)


def _attn_proj(lp, x, spec: DecodeSpec, positions):
    """q/k/v projections of one block's attention at ``positions``
    (RoPE-rotated for llama so cached keys carry their encoding)."""
    ap = lp["attn"]
    if "qkv" in ap:
        qkv = _dense_general(x, ap["qkv"]["kernel"],
                             ap["qkv"].get("bias"))
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
    else:  # grouped-query attention: separate q / kv projections
        q = _dense_general(x, ap["q"]["kernel"])
        kv = _dense_general(x, ap["kv"]["kernel"])
        k, v = kv[..., 0, :, :], kv[..., 1, :, :]
    if spec.family == "llama":
        # rope() takes [L]-shaped positions; rows differ per slot, so
        # vmap the rotation over the batch
        rot = jax.vmap(lambda xb, pb: rope(xb[None], pb,
                                           spec.rope_theta)[0])
        q, k = rot(q, positions), rot(k, positions)
    return q, k, v


def _block(spec: DecodeSpec, lp, x, positions, num_valid, page_table,
           kc, vc):
    """One decoder block against the paged cache; ``lp`` is this layer's
    slice of the stacked params, ``kc/vc`` its [P, ps, KV, D] pool."""
    if spec.family == "gpt":
        h = _layernorm(x, lp["ln1"]["scale"], lp["ln1"]["bias"])
    else:
        h = _rmsnorm(x, lp["rms1"]["scale"])
    q, k, v = _attn_proj(lp, h, spec, positions)
    out, kc, vc = paged_attend(q, k, v, positions=positions,
                               num_valid=num_valid, page_table=page_table,
                               k_pages=kc, v_pages=vc)
    a = _dense_general(out.reshape(*out.shape[:2], -1),
                       lp["attn"]["out"]["kernel"].reshape(
                           -1, spec.hidden))
    if "out_bias" in lp["attn"]:
        a = a + lp["attn"]["out_bias"].astype(a.dtype)
    x = x + a
    if spec.family == "gpt":
        f = _layernorm(x, lp["ln2"]["scale"], lp["ln2"]["bias"])
        if spec.num_experts:
            f = _moe_ffn(lp["moe"], f, spec.dtype)
        else:
            f = _dense_general(f, lp["ffn_in"]["kernel"],
                               lp["ffn_in"]["bias"])
            f = jax.nn.gelu(f, approximate=True)
            f = _dense_general(f, lp["ffn_out"]["kernel"])
            f = f + lp["ffn_bias"].astype(f.dtype)
    else:
        f = _rmsnorm(x, lp["rms2"]["scale"])
        if spec.num_experts:
            f = _moe_ffn(lp["moe"], f, spec.dtype)
        else:
            gate = _dense_general(f, lp["ffn_in"]["kernel"])
            up = _dense_general(f, lp["ffn_up"]["kernel"])
            f = _dense_general(jax.nn.silu(gate) * up,
                               lp["ffn_out"]["kernel"])
    return x + f, kc, vc


# ----------------------------------------------------------------------
# The full paged forward (prefill AND decode are this one function)
# ----------------------------------------------------------------------

def forward_paged(spec: DecodeSpec, params, tokens, lengths, num_valid,
                  page_table, k_pages, v_pages,
                  positions: Optional[jnp.ndarray] = None):
    """Apply the model to ``tokens [B, T]`` whose rows sit at cache
    offsets ``lengths [B]`` (tokens already cached per slot).

    ``num_valid [B]`` counts the REAL new tokens per row (prefill
    padding and inactive decode slots write to the trash page);
    ``page_table [B, pages_per_seq]``.  Returns ``(logits [B, T, vocab],
    k_pages', v_pages')``.  The layer stack runs under ``lax.scan`` over
    the stacked ``layers/layer`` collection — one traced block at any
    depth, the serving twin of the layer-scan compile engine.
    """
    if positions is None:
        positions = lengths[:, None] + jnp.arange(
            tokens.shape[1], dtype=jnp.int32)[None, :]
    emb = params["tok_emb"]["embedding"]
    x = emb.astype(spec.dtype)[tokens]
    if spec.family == "gpt":
        pos_tab = params["pos_emb"]["embedding"].astype(spec.dtype)
        x = x + pos_tab[jnp.clip(positions, 0, pos_tab.shape[0] - 1)]
    x = x.astype(spec.dtype)
    stacked = params["layers"]["layer"]

    def body(carry, layer_in):
        lp, kc, vc = layer_in
        y, kc, vc = _block(spec, lp, carry, positions, num_valid,
                           page_table, kc, vc)
        return y, (kc, vc)

    x, (k_pages, v_pages) = lax.scan(body, x, (stacked, k_pages, v_pages))
    if spec.family == "gpt":
        x = _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
        logits = jnp.einsum("bth,vh->btv", x, emb.astype(spec.dtype))
    else:
        x = _rmsnorm(x, params["rms_f"]["scale"])
        logits = _dense_general(x, params["lm_head"]["kernel"])
    return logits, k_pages, v_pages


def speculative_accept(logits, draft):
    """Fused greedy accept/reject of one speculation burst (ISSUE 18).

    ``logits [B, k+1, vocab]`` are the TARGET model's verify logits at
    positions ``C .. C+k`` (the pending token plus the k drafted
    tokens); ``draft [B, k]`` the draft model's proposals.  Greedy-only:
    the target's token at position ``C+j`` is ``t_j = argmax`` — the
    bitwise-identical twin of the non-speculative decode step's
    ``sample_tokens`` at temperature 0.

    Acceptance is CAPPED at ``k - 1`` drafted tokens, with the bonus
    token always emitted: ``acc = min(longest matching prefix, k-1)``,
    ``emitted = d_1 .. d_acc, t_acc``.  The cap costs nothing (when all
    k drafts match, the bonus ``t_{k-1}`` IS ``d_k``, so the emitted
    stream is identical) and buys the cache invariant the schedule
    rides on: after committing ``acc + 1`` tokens both KV pools are
    filled exactly to the new length — the draft pool wrote positions
    ``C .. C+k-1`` and ``acc + 1 <= k`` always, so no catch-up program
    of a second shape ever exists.  Rejected positions hold garbage at
    ``>= new length``; the next burst overwrites them before the causal
    mask can see them, so rollback is pure page-table arithmetic (no
    zeroing).

    Returns ``(emitted [B, k] int32, acc [B] int32)``: row i's burst is
    ``emitted[i, :acc[i] + 1]``; tail entries are -1.
    """
    k = draft.shape[1]
    tgt = jnp.argmax(logits, axis=-1).astype(jnp.int32)        # [B, k+1]
    match = (draft == tgt[:, :-1]).astype(jnp.int32)           # [B, k]
    n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)        # [B]
    acc = jnp.minimum(n_acc, k - 1).astype(jnp.int32)
    bonus = jnp.take_along_axis(tgt, acc[:, None], axis=1)     # [B, 1]
    idx = jnp.arange(k, dtype=jnp.int32)[None, :]
    emitted = jnp.where(idx < acc[:, None], draft,
                        jnp.where(idx == acc[:, None], bonus, -1))
    return emitted.astype(jnp.int32), acc


def sample_tokens(logits, temps, rids, gen_pos, seed: int):
    """Greedy (temp <= 0) or temperature sampling of one token per row.

    The PRNG key is derived ONLY from (seed, request id, absolute
    position of the token being generated) — independent of decode-slot
    index and batch composition, so batched continuous decoding samples
    the identical token stream a single-sequence decode would."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def one(rid, pos, lg, t):
        key = jax.random.fold_in(
            jax.random.fold_in(jax.random.key(seed), rid), pos)
        return jax.random.categorical(
            key, lg.astype(jnp.float32) / jnp.maximum(t, 1e-6))

    sampled = jax.vmap(one)(rids, gen_pos, logits, temps).astype(jnp.int32)
    return jnp.where(temps > 0.0, sampled, greedy)
