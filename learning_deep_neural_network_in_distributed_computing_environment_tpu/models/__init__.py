"""Model zoo.

``enhanced_cnn`` is the reference's flagship (``Balanced All-Reduce/
model.py:52-111``).  The rest form the BASELINE.md config ladder:
mlp -> lenet5 -> resnet18 -> resnet50 -> bert_base.
"""

from __future__ import annotations

from typing import Any


def get_model(name: str, **kw: Any):
    """Build a flax module by registry name (lazy imports keep startup cheap)."""
    name = name.lower()
    if name not in MODEL_INPUT_SPECS:
        raise ValueError(
            f"unknown model {name!r}; available: {sorted(MODEL_INPUT_SPECS)}")
    if name == "enhanced_cnn":
        from .cnn import EnhancedCNNModel
        return EnhancedCNNModel(**kw)
    if name == "mlp":
        from .mlp import MLP
        return MLP(**kw)
    if name == "lenet5":
        from .lenet import LeNet5
        return LeNet5(**kw)
    if name == "resnet18":
        from .resnet import ResNet18
        return ResNet18(**kw)
    if name == "resnet50":
        from .resnet import ResNet50
        return ResNet50(**kw)
    if name == "bert_base":
        from .bert import BertForMLM
        return BertForMLM(**kw)
    if name == "bert_tiny":
        # CPU-testable MLM model (same code path as bert_base, 2 layers)
        from .bert import BertForMLM
        kw.setdefault("num_layers", 2)
        kw.setdefault("hidden", 64)
        kw.setdefault("num_heads", 4)
        kw.setdefault("ffn_dim", 128)
        return BertForMLM(**kw)
    if name == "gpt2_small":
        from .gpt import GPTForCausalLM
        return GPTForCausalLM(**kw)
    if name == "gpt_tiny":
        # CPU-testable causal LM (same code path as gpt2_small, 2 layers)
        from .gpt import GPTForCausalLM
        kw.setdefault("num_layers", 2)
        kw.setdefault("hidden", 64)
        kw.setdefault("num_heads", 4)
        kw.setdefault("ffn_dim", 128)
        return GPTForCausalLM(**kw)
    if name == "gpt_small":
        # CPU-trainable middle size between gpt_tiny and gpt2_small —
        # the speculative-decoding TARGET of the draft/target smoke
        # (gpt_tiny drafts for it: same vocab, ~4x the per-step work)
        from .gpt import GPTForCausalLM
        kw.setdefault("num_layers", 4)
        kw.setdefault("hidden", 128)
        kw.setdefault("num_heads", 4)
        kw.setdefault("ffn_dim", 256)
        return GPTForCausalLM(**kw)
    if name == "llama_medium":
        from .llama import LlamaForCausalLM
        return LlamaForCausalLM(**kw)
    if name == "llama_tiny":
        # CPU-testable Llama (same code path as llama_medium, 2 layers)
        from .llama import LlamaForCausalLM
        kw.setdefault("num_layers", 2)
        kw.setdefault("hidden", 64)
        kw.setdefault("num_heads", 4)
        kw.setdefault("ffn_dim", 176)
        return LlamaForCausalLM(**kw)
    if name == "vit_s16":
        from .vit import ViT
        return ViT(**kw)
    if name == "vit_b16":
        from .vit import ViT
        kw.setdefault("hidden", 768)
        kw.setdefault("num_heads", 12)
        kw.setdefault("ffn_dim", 3072)
        return ViT(**kw)
    if name == "vit_tiny":
        # CPU-testable ViT for 32x32 inputs (same code path as vit_s16)
        from .vit import ViT
        kw.setdefault("patch", 8)
        kw.setdefault("num_layers", 2)
        kw.setdefault("hidden", 64)
        kw.setdefault("num_heads", 4)
        kw.setdefault("ffn_dim", 128)
        return ViT(**kw)
    raise ValueError(f"unknown model {name!r}")


def is_attention_model(name: str) -> bool:
    """True for transformer families (bert_*/gpt_*/vit_*/llama_*) — the
    models that accept attention/parallelism kwargs (TP, PP, MoE,
    attention_impl)."""
    return name.lower().startswith(("bert", "gpt", "vit", "llama"))


def supports_layer_scan(name: str) -> bool:
    """True for the homogeneous-block families whose repeated blocks can
    be stacked along a layer axis and run under ``lax.scan`` (the
    layer-scan compile engine): every transformer family.  CNN/MLP models
    have heterogeneous layers (changing widths/strides) that cannot
    share one stacked parameter block."""
    return is_attention_model(name)


def is_token_model(name: str) -> bool:
    """True for models whose input is a token-id sequence [B, L] — the
    shape sequence parallelism shards.  ViT is attention-based but takes
    images, so SP does not apply."""
    return name.lower().startswith(("bert", "gpt", "llama"))


# The named-activation vocabulary of the shared scanned-block path
# (ISSUE 15).  Every transformer family's block annotates EXACTLY these
# ``checkpoint_name`` labels — the stable contract the ``--remat_policy
# save_names:<set>`` / ``offload_names:<set>`` tiers select from, the
# eager config validation checks against, and graftlint's R6 rule
# discovers (a typo'd label silently degrades a named policy to
# save-NOTHING, which is why the vocabulary is closed):
#
# - ``attn_out``  — the attention sublayer's output projection
#   ([B, L, H] per block; the pjit/TPUv4 report's canonical save point);
# - ``mlp_out``   — the FFN / MoE sublayer output ([B, L, H]);
# - ``block_out`` — the block's residual-stream output (the layer
#   boundary — saving only these IS the GPipe-paper recipe, spelled as
#   a named set);
# - ``moe_dispatch`` — the MoE dispatch einsum's expert-batched tokens
#   ([E, C, H]; emitted only when the family runs with num_experts > 0).
REMAT_NAMES = ("attn_out", "mlp_out", "block_out", "moe_dispatch")


def remat_name_vocab(name: str, num_experts: int = 0) -> tuple[str, ...]:
    """The ``checkpoint_name`` labels the ``name`` family's blocks emit
    — what a named remat policy may select from.  CNN/MLP families emit
    none (they have no scanned block path); ``moe_dispatch`` exists only
    when the run actually builds MoE FFNs."""
    if not is_attention_model(name):
        return ()
    base = ("attn_out", "mlp_out", "block_out")
    return base + ("moe_dispatch",) if num_experts > 0 else base


MODEL_INPUT_SPECS = {
    # name -> (example input shape without batch, num_classes or vocab)
    "enhanced_cnn": ((32, 32, 3), 10),
    "mlp": ((28, 28, 1), 10),
    "lenet5": ((28, 28, 1), 10),
    "resnet18": ((32, 32, 3), 10),
    "resnet50": ((224, 224, 3), 1000),
    "bert_base": ((128,), 30522),
    "bert_tiny": ((128,), 30522),
    "gpt2_small": ((128,), 50257),
    "gpt_small": ((128,), 50257),
    "gpt_tiny": ((128,), 50257),
    "llama_medium": ((1024,), 32000),
    "llama_tiny": ((128,), 32000),
    "vit_s16": ((224, 224, 3), 1000),
    "vit_b16": ((224, 224, 3), 1000),
    "vit_tiny": ((32, 32, 3), 10),
}
