"""2-layer MLP (BASELINE.md config ladder entry 1: MNIST, single-process).

Accepts either flat [B, D] or image [B, H, W, C] inputs (flattened).  Shares
the engine's (train, mutable batch_stats) calling convention; has no
BatchNorm so ``batch_stats`` is simply absent.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

_xavier = nn.initializers.xavier_uniform()


class MLP(nn.Module):
    num_classes: int = 10
    hidden: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        x = x.reshape(x.shape[0], -1)
        x = jnp.asarray(x, self.dtype)
        x = nn.Dense(self.hidden, kernel_init=_xavier, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, kernel_init=_xavier,
                     dtype=jnp.float32)(jnp.asarray(x, jnp.float32))
        return x
