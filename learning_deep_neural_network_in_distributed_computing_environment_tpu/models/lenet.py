"""LeNet-5 (BASELINE.md config ladder entry 2: MNIST, 4-way DP).

Classic LeCun architecture adapted to NHWC/TPU: conv 6@5x5 -> avgpool ->
conv 16@5x5 -> avgpool -> dense 120 -> 84 -> classes, tanh activations.

TPU-first formulation: the two tiny-channel convolutions (1->6, 6->16) are
expressed as im2col patch-matmuls and the 2x2 average pools as reshape-means
instead of ``lax.conv`` / ``reduce_window``.  Two reasons:

1. this backend's compiler takes unbounded time on the gradient of a
   small-channel conv at batch >= ~192 (empirically bisected: the bare
   1->6 5x5 conv grad compiles in 4s at B=32, 54s at B=128, and never
   finishes at B=256, where the im2col form compiles in 11s);
2. a conv with 1-6 input channels occupies 1-6 of the MXU's 128 lanes,
   while the im2col matmul has K = kh*kw*cin (25 / 150) — an order of
   magnitude better systolic-array utilization for the same math.

Per-conv parameter shapes/count are identical to the ``nn.Conv`` version
(kernel ``[kh, kw, cin, cout]`` + bias); note the module path names in the
param tree change (``Conv_i`` -> ``ConvIm2Col_i``), so checkpoints saved
before this rewrite do not restore into it.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

_xavier = nn.initializers.xavier_uniform()


def _avg_pool_2x2(x: jnp.ndarray) -> jnp.ndarray:
    """2x2/stride-2 average pooling as a reshape-mean (exact for even H, W);
    equivalent to ``nn.avg_pool(x, (2, 2), strides=(2, 2))``."""
    b, h, w, c = x.shape
    return x.reshape(b, h // 2, 2, w // 2, 2, c).mean(axis=(2, 4))


class ConvIm2Col(nn.Module):
    """5x5-style conv as patch-extraction + one matmul.

    Numerically identical to ``nn.Conv(features, (kh, kw), padding=...)``
    with the same (kernel, bias) parameters (parity pinned by
    tests/test_models_extra.py::TestLeNet).
    """

    features: int
    kernel_size: tuple[int, int]
    padding: str = "SAME"  # SAME | VALID
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        kh, kw = self.kernel_size
        if self.padding not in ("SAME", "VALID"):
            raise ValueError(f"padding must be 'SAME' or 'VALID', "
                             f"got {self.padding!r}")
        if self.padding == "SAME" and (kh % 2 == 0 or kw % 2 == 0):
            raise ValueError(
                "SAME padding here is symmetric k//2 (exact only for odd "
                f"kernels); nn.Conv pads (k-1)//2 low for even kernels — "
                f"got kernel_size {self.kernel_size}")
        cin = x.shape[-1]
        kernel = self.param("kernel", _xavier, (kh, kw, cin, self.features))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        x = jnp.asarray(x, self.dtype)
        kernel = jnp.asarray(kernel, self.dtype)
        bias = jnp.asarray(bias, self.dtype)
        if self.padding == "SAME":
            x = jnp.pad(x, ((0, 0), (kh // 2, kh // 2),
                            (kw // 2, kw // 2), (0, 0)))
        b, h, w, _ = x.shape
        oh, ow = h - kh + 1, w - kw + 1
        # kh*kw static shifted views; stacking order (di, dj, cin) matches
        # the [kh, kw, cin, features] kernel reshape below
        cols = jnp.stack([x[:, di:di + oh, dj:dj + ow, :]
                          for di in range(kh) for dj in range(kw)], axis=3)
        cols = cols.reshape(b, oh, ow, kh * kw * cin)
        return cols @ kernel.reshape(kh * kw * cin, self.features) + bias


class LeNet5(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        x = jnp.asarray(x, self.dtype)
        x = ConvIm2Col(6, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.tanh(x)
        x = _avg_pool_2x2(x)
        x = ConvIm2Col(16, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.tanh(x)
        x = _avg_pool_2x2(x)
        x = x.reshape(x.shape[0], -1)
        x = nn.tanh(nn.Dense(120, kernel_init=_xavier, dtype=self.dtype)(x))
        x = nn.tanh(nn.Dense(84, kernel_init=_xavier, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, kernel_init=_xavier,
                     dtype=jnp.float32)(jnp.asarray(x, jnp.float32))
        return x
