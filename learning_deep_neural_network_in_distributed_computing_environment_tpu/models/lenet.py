"""LeNet-5 (BASELINE.md config ladder entry 2: MNIST, 4-way DP).

Classic LeCun architecture adapted to NHWC/TPU: conv 6@5x5 -> avgpool ->
conv 16@5x5 -> avgpool -> dense 120 -> 84 -> classes, tanh activations.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

_xavier = nn.initializers.xavier_uniform()


def _avg_pool_2x2(x: jnp.ndarray) -> jnp.ndarray:
    """2x2/stride-2 average pooling as a reshape-mean (exact for even H, W).

    Equivalent to ``nn.avg_pool(x, (2, 2), strides=(2, 2))`` but avoids
    ``reduce_window``, whose gradient composed with a small-channel conv
    gradient hangs this TPU backend's compiler (empirically bisected: conv
    1->6 grad alone compiles, + reduce_window-backward never finishes)."""
    b, h, w, c = x.shape
    return x.reshape(b, h // 2, 2, w // 2, 2, c).mean(axis=(2, 4))


class LeNet5(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        x = jnp.asarray(x, self.dtype)
        x = nn.Conv(6, (5, 5), padding="SAME", kernel_init=_xavier,
                    dtype=self.dtype)(x)
        x = nn.tanh(x)
        x = _avg_pool_2x2(x)
        x = nn.Conv(16, (5, 5), padding="VALID", kernel_init=_xavier,
                    dtype=self.dtype)(x)
        x = nn.tanh(x)
        x = _avg_pool_2x2(x)
        x = x.reshape(x.shape[0], -1)
        x = nn.tanh(nn.Dense(120, kernel_init=_xavier, dtype=self.dtype)(x))
        x = nn.tanh(nn.Dense(84, kernel_init=_xavier, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, kernel_init=_xavier,
                     dtype=jnp.float32)(jnp.asarray(x, jnp.float32))
        return x
