"""GPT-2-style causal language model (beyond-reference model family).

A from-scratch flax decoder (no ``transformers`` dependency): pre-LN
blocks, learned position embeddings, GELU FFN, and a TIED LM head (logits
= hidden @ token_embedding^T, the GPT-2 construction — ``gpt2_small``
matches the canonical 124,439,808-parameter count).  The reference has no
sequence models at all (its model is a CNN, SURVEY.md 2.3); this family
extends the framework's BASELINE ladder beyond BERT to autoregressive
training.

All the parallelism plumbing is shared with BERT (``models/bert.py``):

- attention is ``ops.attention.attend(..., causal=True)`` so the same
  module runs dense, flash (Pallas causal kernel), or causal ring /
  Ulysses sequence-parallel attention;
- tensor parallelism uses the identical Megatron construction and param
  names (``qkv``/``out``/``ffn_in``/``ffn_out``), so ``bert.tp_param_specs``
  applies unchanged;
- ``scan_layers=True`` stacks the blocks for pipeline parallelism
  (``parallel/pp.py`` GPipe schedule).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..compat import checkpoint_name
from ..parallel.tp import copy_to_tp_region, reduce_from_tp_region
from .bert import SelfAttention

_init = nn.initializers.normal(stddev=0.02)


class GPTBlock(nn.Module):
    """Pre-LN decoder block: x + attn(ln1(x)); x + ffn(ln2(x)).

    ``num_experts > 0`` swaps the dense FFN for the Switch-MoE FFN
    (``models/moe.py``), shardable over an ``expert`` mesh axis."""

    num_heads: int
    ffn_dim: int                   # GLOBAL FFN width
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    axis_name: Optional[str] = None
    tp_size: int = 1
    model_axis: Optional[str] = None
    num_experts: int = 0
    expert_axis: Optional[str] = None
    ep_size: int = 1
    capacity_factor: float = 1.25

    @nn.compact
    def __call__(self, x, *, train: bool = False, aux_scale=1.0):
        h = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="ln1")(x)
        # named activations (ISSUE 15, models.REMAT_NAMES): inert
        # identity labels a save_names:/offload_names: policy selects
        a = checkpoint_name(
            SelfAttention(self.num_heads, dtype=self.dtype,
                          attention_impl=self.attention_impl,
                          axis_name=self.axis_name, tp_size=self.tp_size,
                          model_axis=self.model_axis, causal=True,
                          name="attn")(h), "attn_out")
        x = x + a
        f = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="ln2")(x)
        if self.num_experts:
            from .moe import MoEFFN
            f = MoEFFN(self.num_experts, self.ffn_dim,
                       capacity_factor=self.capacity_factor,
                       dtype=self.dtype, expert_axis=self.expert_axis,
                       ep_size=self.ep_size, tp_size=self.tp_size,
                       model_axis=self.model_axis, name="moe")(
                           f, train=train, aux_scale=aux_scale)
        else:
            if self.ffn_dim % self.tp_size:
                raise ValueError(
                    f"ffn_dim {self.ffn_dim} not divisible by tp_size "
                    f"{self.tp_size} (column-parallel FFN)")
            f = copy_to_tp_region(f, self.model_axis)
            f = nn.Dense(self.ffn_dim // self.tp_size, kernel_init=_init,
                         dtype=self.dtype, name="ffn_in")(f)
            f = nn.gelu(f, approximate=True)
            f = nn.Dense(x.shape[-1], kernel_init=_init, use_bias=False,
                         dtype=self.dtype, name="ffn_out")(f)
            f = reduce_from_tp_region(f, self.model_axis)
            f = f + self.param("ffn_bias", nn.initializers.zeros,
                               (x.shape[-1],)).astype(f.dtype)
        f = checkpoint_name(f, "mlp_out")
        return checkpoint_name(x + f, "block_out")


class _ScanBlock(nn.Module):
    """carry-API adapter so ``nn.scan`` can stack GPTBlocks.  Second
    (broadcast) arg: MoE aux-loss scale (None => 1.0; the GPipe schedule
    passes its bubble mask — parallel/pp.py)."""

    num_heads: int
    ffn_dim: int
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    axis_name: Optional[str] = None
    tp_size: int = 1
    model_axis: Optional[str] = None
    num_experts: int = 0
    expert_axis: Optional[str] = None
    ep_size: int = 1
    capacity_factor: float = 1.25
    train: bool = False

    @nn.compact
    def __call__(self, x, aux_scale):
        y = GPTBlock(self.num_heads, self.ffn_dim, dtype=self.dtype,
                     attention_impl=self.attention_impl,
                     axis_name=self.axis_name, tp_size=self.tp_size,
                     model_axis=self.model_axis,
                     num_experts=self.num_experts,
                     expert_axis=self.expert_axis, ep_size=self.ep_size,
                     capacity_factor=self.capacity_factor, name="layer")(
                         x, train=self.train,
                         aux_scale=1.0 if aux_scale is None else aux_scale)
        return y, None


class GPTForCausalLM(nn.Module):
    """Token ids [B, L] -> next-token logits [B, L, vocab].

    The data pipeline provides shifted labels (``labels[t] = input[t+1]``,
    final position -1/ignore — ``data/sources.py synthetic_lm``), so the
    model itself is a pure sequence-to-logits map like BERT.
    """

    num_classes: int = 50257       # vocab size (engine passes num_classes)
    num_layers: int = 12
    hidden: int = 768
    num_heads: int = 12
    ffn_dim: int = 3072
    max_len: int = 1024
    dtype: Any = jnp.float32
    attention_impl: str = "dense"
    axis_name: Optional[str] = None
    tp_size: int = 1
    model_axis: Optional[str] = None
    scan_layers: bool = False
    pipeline_axis: Optional[str] = None
    pp_size: int = 1
    num_microbatches: int = 0      # 0 => pp_size
    remat: bool = False            # [compat alias] remat_policy="everything"
    remat_policy: Optional[str] = None  # none | dots_saveable | everything
    num_experts: int = 0           # >0 => Switch-MoE FFN in every block
    expert_axis: Optional[str] = None
    ep_size: int = 1
    capacity_factor: float = 1.25

    # tied head, vocab-parallel under TP (r4): the embedding table shards
    # over 'model' on the VOCAB dim, the lookup masks+psums the local
    # rows, and attend() emits the LOCAL vocab slice of the logits — the
    # Megatron vocab-parallel construction applied to a TIED head, so the
    # full [B, L, V] logits never materialize on one device and the
    # engine's loss goes through vocab_parallel_token_stats
    vocab_parallel_head = True

    @nn.compact
    def __call__(self, input_ids, *, train: bool = False,
                 mode: str = "full"):
        """``mode`` partitions the forward for the 1F1B engine path
        (parallel/pp.py): 'embed' -> embedded activations, 'stage' ->
        apply this device's local scanned layers to activations (no
        pipeline schedule), 'head' -> final LN + tied decode on
        activations.  'full' (default) is the ordinary forward; init
        always uses it so every mode shares one parameter structure."""
        if self.tp_size > 1 and self.num_classes % self.tp_size:
            raise ValueError(
                f"vocab size {self.num_classes} not divisible by tp_size "
                f"{self.tp_size} (vocab-parallel tied head)")
        tok_emb = nn.Embed(self.num_classes // self.tp_size, self.hidden,
                           embedding_init=_init, dtype=self.dtype,
                           name="tok_emb")
        if mode == "stage":
            return self._decode_scanned(input_ids, train, as_stage=True)
        if mode == "head":
            x = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype,
                             name="ln_f")(input_ids)
            return tok_emb.attend(x)
        b, l = input_ids.shape
        tok = self._embed(tok_emb, input_ids)
        pos_ids = jnp.arange(l)
        if self.axis_name is not None:
            # sequence-parallel: this device holds chunk axis_index of the
            # sequence, so absolute positions are offset by index * chunk
            from jax import lax
            pos_ids = pos_ids + lax.axis_index(self.axis_name) * l
        pos = nn.Embed(self.max_len, self.hidden, embedding_init=_init,
                       dtype=self.dtype, name="pos_emb")(pos_ids[None, :])
        x = jnp.asarray(tok + pos, self.dtype)
        if mode == "embed":
            return x
        if self.scan_layers:
            x = self._decode_scanned(x, train)
        else:
            for i in range(self.num_layers):
                x = GPTBlock(self.num_heads, self.ffn_dim, dtype=self.dtype,
                             attention_impl=self.attention_impl,
                             axis_name=self.axis_name, tp_size=self.tp_size,
                             model_axis=self.model_axis,
                             num_experts=self.num_experts,
                             expert_axis=self.expert_axis,
                             ep_size=self.ep_size,
                             capacity_factor=self.capacity_factor,
                             name=f"layer{i}")(x, train=train)
        x = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="ln_f")(x)
        # tied LM head: logits = x @ tok_emb^T (shares the embedding
        # table; the LOCAL vocab slice under tensor parallelism)
        return tok_emb.attend(x)

    def _embed(self, tok_emb, input_ids):
        """Token lookup; under TP each shard holds vocab rows
        [idx*V/tp, (idx+1)*V/tp) and the masked local lookups psum to the
        full embedding (transpose: each shard's table gradient is its
        local scatter-add — stays sharded)."""
        if self.tp_size <= 1:
            return tok_emb(input_ids)
        from jax import lax
        v_local = self.num_classes // self.tp_size
        off = lax.axis_index(self.model_axis) * v_local
        loc = input_ids - off
        hit = (loc >= 0) & (loc < v_local)
        tok = tok_emb(jnp.clip(loc, 0, v_local - 1))
        tok = jnp.where(hit[..., None], tok, jnp.zeros_like(tok))
        return lax.psum(tok, self.model_axis)

    def _decode_scanned(self, x, train: bool, as_stage: bool = False):
        from .bert import apply_scanned_stack, resolve_remat_policy
        return apply_scanned_stack(
            _ScanBlock, x, num_layers=self.num_layers, pp_size=self.pp_size,
            pipeline_axis=None if as_stage else self.pipeline_axis,
            remat_policy=resolve_remat_policy(self.remat, self.remat_policy),
            num_microbatches=self.num_microbatches, train=train,
            num_heads=self.num_heads, ffn_dim=self.ffn_dim,
            dtype=self.dtype, attention_impl=self.attention_impl,
            axis_name=self.axis_name, tp_size=self.tp_size,
            model_axis=self.model_axis, num_experts=self.num_experts,
            expert_axis=self.expert_axis, ep_size=self.ep_size,
            capacity_factor=self.capacity_factor)
