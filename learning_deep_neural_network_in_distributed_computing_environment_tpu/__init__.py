"""TPU-native distributed deep-learning framework.

A brand-new JAX/XLA/pjit/shard_map framework with the capabilities of the
reference repo ``Sanasar1/Learning-Deep-Neural-Network-In-Distributed-
Computing-Environment`` (six copied PyTorch variant directories), rebuilt as
ONE configurable framework:

- local-SGD / FedAvg-style data parallelism (sync once per global epoch),
- a 12-mode sync matrix: aggregate {gradients, weights} x {equal, weighted}
  x topology {allreduce, ring, double_ring}  (reference:
  ``Balanced All-Reduce/trainer.py:141-150``, ``.../communication.py``),
- heterogeneity-aware adaptive data partitioning driven by a timing probe
  (reference: ``Balanced All-Reduce/dataloader.py:119-153``),
- straggler time-limit protocol (reference: ``Balanced All-Reduce/
  trainer.py:42-44,112-139``) re-designed as a masked fixed step budget,
- non-IID fixed-class shard injection (reference: ``Disbalanced All-Reduce/
  dataloader.py:56-155``),
- distributed metric collection + the reference's six plots.

The compute path is jit/shard_map over a ``jax.sharding.Mesh`` with XLA
collectives (psum/pmean/ppermute/all_gather) over ICI/DCN — no NCCL/MPI.

The canonical import alias is::

    import learning_deep_neural_network_in_distributed_computing_environment_tpu as ldnde_tpu
"""

__version__ = "0.1.0"

# Subpackages (models, ops, parallel, data, utils) and modules (config, mesh,
# comms, train, eval, viz, probe, checkpoint, main) are imported explicitly by
# users; keep the package root import cheap (no jax import at package import
# time so that tests can set XLA_FLAGS first).

__all__ = [
    "__version__",
    "shard_map",
]


def __getattr__(name):
    # lazy: ``ldnde_tpu.shard_map`` resolves the JAX-version compat shim
    # (jax.shard_map, or the experimental one on legacy JAX) without making
    # the package root import jax eagerly
    if name == "shard_map":
        from .compat import shard_map
        return shard_map
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
