"""Evaluation: the reference's ``validator.py`` + ``evaluator.py``
capabilities.

- per-local-epoch validation runs *inside* the compiled round program
  (train.py ``eval_step``), matching ``validator.py:3-23``;
- ``evaluate`` here is the rank-0 final test-set pass
  (``evaluator.py:6-61``): loss, accuracy, and precision/recall/F1 in
  macro, weighted, and micro averages, with the reference's printed lines
  (including its 'Micro recision'/'Micro ecall' typos normalized — noted).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .train import masked_token_stats


def _prf(labels: np.ndarray, preds: np.ndarray, num_classes: int,
         average: str):
    """precision/recall/F1 without a sklearn dependency (numerically
    validated against sklearn in tests; sklearn semantics: undefined -> 0)."""
    tp = np.zeros(num_classes)
    fp = np.zeros(num_classes)
    fn = np.zeros(num_classes)
    for c in range(num_classes):
        tp[c] = np.sum((preds == c) & (labels == c))
        fp[c] = np.sum((preds == c) & (labels != c))
        fn[c] = np.sum((preds != c) & (labels == c))
    with np.errstate(divide="ignore", invalid="ignore"):
        prec = np.where(tp + fp > 0, tp / (tp + fp), 0.0)
        rec = np.where(tp + fn > 0, tp / (tp + fn), 0.0)
        f1 = np.where(prec + rec > 0, 2 * prec * rec / (prec + rec), 0.0)
    if average == "macro":
        return prec.mean(), rec.mean(), f1.mean()
    if average == "weighted":
        support = np.bincount(labels, minlength=num_classes).astype(np.float64)
        w = support / support.sum()
        return (prec * w).sum(), (rec * w).sum(), (f1 * w).sum()
    if average == "micro":
        p = tp.sum() / max(tp.sum() + fp.sum(), 1)
        r = tp.sum() / max(tp.sum() + fn.sum(), 1)
        f = 2 * p * r / max(p + r, 1e-12) if (p + r) > 0 else 0.0
        return p, r, f
    raise ValueError(f"unknown average {average!r}")


def evaluate(model, variables, images: np.ndarray, labels: np.ndarray,
             batch_size: int, *, rank: int = 0, verbose: bool = True):
    """Full test-set evaluation (ref evaluator.py:6-61).

    Returns (loss, accuracy, all_preds, all_labels, metrics_dict).
    Batching pads the tail batch and masks it out (static shapes for jit).
    """
    from .utils.batching import pad_to_batches
    n = len(labels)
    x, y, m = pad_to_batches(images, labels, batch_size)
    steps = len(m)

    # one-shot per evaluation: the whole test pass is ONE compiled scan
    # closing over this call's (model, variables) — a shared cache entry
    # could not hit across calls anyway
    @jax.jit  # graftlint: disable=R2 -- single final-eval compile
    def run(x, y, m):
        def step(_, inp):
            xb, yb, mb = inp
            out = model.apply(variables, xb, train=False)
            # reference loss is the mean of per-batch means
            # (evaluator.py:22,33); batches are equal-size here so the
            # example mean is identical up to tail masking
            ce, w, correct = masked_token_stats(out, yb, mb)
            return _, (out.argmax(-1), (ce * w).sum(), correct, w.sum())
        _, (preds, lsums, csums, wsums) = jax.lax.scan(step, 0, (x, y, m))
        return preds, lsums.sum(), csums.sum(), wsums.sum()

    bar = None
    if verbose:
        try:  # the reference's "Testing" bar (evaluator.py:15,30-31); the
            # whole pass is ONE compiled scan here, so it completes at once
            from tqdm import tqdm
            bar = tqdm(total=steps, desc="Testing")
        except ImportError:
            pass
    preds, loss_sum, correct, weight = jax.device_get(run(
        jnp.asarray(x), jnp.asarray(y), jnp.asarray(m)))
    if bar is not None:
        bar.update(steps)
        bar.close()
    preds = preds.reshape(-1, *labels.shape[1:])[:n]
    weight = max(float(weight), 1.0)
    loss = float(loss_sum) / weight
    accuracy = 100.0 * float(correct) / weight

    if labels.ndim > 1:  # token task (MLM): score the masked positions
        valid = labels >= 0
        labels_flat, preds_flat = labels[valid], preds[valid]
    else:
        labels_flat, preds_flat = labels, preds
    ncls = int(max(labels_flat.max(), preds_flat.max())) + 1
    pm, rm, fm = _prf(labels_flat, preds_flat, ncls, "macro")
    pw, rw, fw = _prf(labels_flat, preds_flat, ncls, "weighted")
    pi, ri, fi = _prf(labels_flat, preds_flat, ncls, "micro")
    metrics = dict(precision_macro=pm, recall_macro=rm, f1_macro=fm,
                   precision_weighted=pw, recall_weighted=rw, f1_weighted=fw,
                   precision_micro=pi, recall_micro=ri, f1_micro=fi)
    if verbose:
        # same report lines as evaluator.py:55-59
        print(f"Worker {rank}, Test Loss: {loss:.4f}, Test Accuracy: "
              f"{accuracy:.2f}%, Weighted Precision: {pw:.2f}, Weighted "
              f"Recall: {rw:.2f}, Weighted F1 Score: {fw:.2f}")
        print(f"Precision: {pm:.2f}, Recall: {rm:.2f}, F1 Score: {fm:.2f}")
        print(f"Micro Precision: {pi:.2f}, Micro Recall: {ri:.2f}, "
              f"Micro F1 Score: {fi:.2f}")
    return loss, accuracy, preds, labels, metrics
