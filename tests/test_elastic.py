"""Elastic worker membership + chaos-injection harness (ISSUE 8).

The tentpole gate is the ROADMAP's: kill/add a worker mid-run in the
simulated N-worker CPU driver and BITWISE-match (fp32) the post-event
loss trajectory of a fresh run started from the same membership
snapshot — under ``--sanitize``, with zero post-warmup retraces outside
the sanctioned reshard recompile.  Around it: the chaos grammar, the
straggler retry/timeout/backoff protocol, quorum/capacity graceful
degradation, the ring-neighbor rebuild across all three topologies, and
crash-during-reshard -> checkpoint-resume replay.

Walls are pinned via ``simulated_round_durations`` (membership-aware
vectors): the only nondeterminism left would be the elastic transition
itself, which must introduce none.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

from learning_deep_neural_network_in_distributed_computing_environment_tpu import (  # noqa: E402
    chaos as chaos_lib,
    elastic as elastic_lib,
    mesh as mesh_lib,
    probe as probe_lib,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.comms import (  # noqa: E402
    ring_neighbors,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import (  # noqa: E402
    Config,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.data import (  # noqa: E402
    adaptive_partition,
    contiguous_partition,
    efficiency_ratios,
    fixed_classes_for_rank,
    skew_partition,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import (  # noqa: E402
    train_global,
)


# ----------------------------------------------------------------------
# Chaos grammar + schedule
# ----------------------------------------------------------------------

class TestChaosSpec:
    def test_parses_all_kinds(self):
        ev = chaos_lib.parse_chaos_spec(
            "kill@2:w1, join@3; slow@1:w0x2.5, stall@4:w2+30*2")
        kinds = [(e.kind, e.round) for e in ev]
        assert kinds == [("slow", 1), ("kill", 2), ("join", 3),
                         ("stall", 4)]          # sorted by (round, kind)
        assert ev[0].factor == 2.5 and ev[0].worker == 0
        assert ev[3].seconds == 30.0 and ev[3].rounds == 2

    @pytest.mark.parametrize("bad", [
        "explode@2:w1",        # unknown kind
        "kill@0:w1",           # round 0 is the initial membership
        "kill@2",              # kill needs a target
        "slow@2:w1",           # slow needs a positive factor
        "stall@2:w1",          # stall needs positive seconds
        "kill@2:w1 join@3",    # missing separator
        "join@3:w5",           # joiners take the next free id, not :w
        "kill@2:w1+30",        # +seconds is stall-only
        "kill@1:w0x2",         # xfactor is slow-only
        "slow@2:w1x2*3",       # *rounds is stall-only (slow persists)
    ])
    def test_malformed_specs_raise(self, bad):
        with pytest.raises(ValueError):
            chaos_lib.parse_chaos_spec(bad)

    def test_config_validates_spec_eagerly(self):
        with pytest.raises(ValueError, match="chaos"):
            Config(chaos="kill@2")          # typo fails at config time
        with pytest.raises(ValueError, match="elastic_min_workers"):
            Config(elastic_min_workers=0)
        with pytest.raises(ValueError, match="chaos_grace"):
            Config(chaos_grace=-1.0)

    def test_random_schedule_reconstructable_from_seed(self):
        a = chaos_lib.random_events(7, 5, epochs_global=10)
        b = chaos_lib.random_events(7, 5, epochs_global=10)
        assert a == b and len(a) == 5
        assert all(1 <= e.round < 10 for e in a)
        # random kills carry a fractional target resolved against the
        # live roster at apply time
        sched = chaos_lib.ChaosSchedule(a)
        for e in a:
            wid = sched.resolve_target(e, [0, 2, 5])
            assert wid in (0, 2, 5)

    def test_random_wall_faults_pinned_to_logical_ids(self):
        # --chaos random: slow/stall targets resolve ONCE against the
        # round-0 roster; a membership change must not migrate a
        # persistent fault to whichever worker now occupies the frac's
        # roster position (and a pinned target that departs simply stops
        # perturbing — the fault followed the worker out)
        cfg = Config(model="mlp", dataset="mnist", chaos="random",
                     chaos_seed=3, chaos_events=12, epochs_global=8,
                     num_workers=4)
        sched = chaos_lib.ChaosSchedule.from_config(cfg)
        walls = [e for e in sched.events if e.kind in ("slow", "stall")]
        assert walls and all(e.worker is not None for e in walls)
        # driver-path pinning (num_workers=0 runs) is idempotent: a
        # second pin against a DIFFERENT roster must not re-target
        pinned = [e.worker for e in sched.events
                  if e.kind in ("slow", "stall")]
        sched.pin_wall_targets([7, 8, 9])
        assert [e.worker for e in sched.events
                if e.kind in ("slow", "stall")] == pinned
        e = walls[0]
        wid = e.worker
        full = list(range(4))
        before = sched.perturb_walls(e.round, full, np.ones(4))
        assert before[full.index(wid)] != 1.0
        shrunk = [w for w in full if w != wid]
        after = sched.perturb_walls(e.round, shrunk,
                                    np.ones(len(shrunk)))
        others = [e2 for e2 in walls[1:]
                  if e2.round <= e.round and e2.worker in shrunk]
        if not others:   # no other fault lands here: nothing perturbed
            assert after.tolist() == np.ones(len(shrunk)).tolist()

    def test_perturb_walls_slow_persists_stall_windows(self):
        ev = chaos_lib.parse_chaos_spec("slow@2:w1x3,stall@3:w0+10*2")
        sched = chaos_lib.ChaosSchedule(ev)
        ids = [0, 1, 2]
        ones = np.ones(3)
        assert sched.perturb_walls(1, ids, ones).tolist() == [1, 1, 1]
        assert sched.perturb_walls(2, ids, ones).tolist() == [1, 3, 1]
        assert sched.perturb_walls(3, ids, ones).tolist() == [11, 3, 1]
        assert sched.perturb_walls(4, ids, ones).tolist() == [11, 3, 1]
        assert sched.perturb_walls(5, ids, ones).tolist() == [1, 3, 1]
        # keyed by LOGICAL id: the perturbation follows the worker when
        # the roster reshuffles
        assert sched.perturb_walls(2, [2, 1], np.ones(2)).tolist() == [1, 3]


class TestStragglerPolicy:
    def test_retry_backoff_then_departure(self):
        pol = chaos_lib.StragglerPolicy(
            time_limit=10.0, grace=5.0, retries=1, backoff=0.5)
        ids = [0, 1]
        # round 1: worker 1 overruns 15s deadline -> tolerated retry,
        # deadline extends to 10 + 5*1.5 = 17.5
        departed, crashed, retries = pol.observe(ids,
                                                 np.array([1.0, 16.0]))
        assert departed == [] and crashed == [] and len(retries) == 1
        assert retries[0]["worker"] == 1 and retries[0]["attempt"] == 1
        assert retries[0]["next_deadline_s"] == 17.5
        # round 2: still past the EXTENDED deadline -> departed
        departed, crashed, retries = pol.observe(ids,
                                                 np.array([1.0, 18.0]))
        assert departed == [1] and crashed == [] and retries == []

    def test_recovery_resets_attempts(self):
        pol = chaos_lib.StragglerPolicy(10.0, 5.0, retries=1, backoff=0.5)
        pol.observe([0], np.array([16.0]))       # retry 1
        pol.observe([0], np.array([1.0]))        # recovered
        departed, crashed, retries = pol.observe([0], np.array([16.0]))
        assert departed == [] and retries[0]["attempt"] == 1

    def test_nonfinite_wall_is_the_distinct_crashed_verdict(self):
        # ISSUE 12: a missed round fence (non-finite wall) is CRASHED
        # immediately — no retry ladder, attempt state dropped — while a
        # finite overrun in the same round keeps the PR 8 ladder
        pol = chaos_lib.StragglerPolicy(10.0, 5.0, retries=1, backoff=0.5)
        departed, crashed, retries = pol.observe(
            [0, 1, 2], np.array([1.0, np.inf, 16.0]))
        assert crashed == [1] and departed == []
        assert [r["worker"] for r in retries] == [2]


# ----------------------------------------------------------------------
# Membership plan + reshard primitives
# ----------------------------------------------------------------------

class TestMembershipPlan:
    def test_kill_join_and_id_stability(self):
        plan = elastic_lib.MembershipPlan(4)
        ev = chaos_lib.parse_chaos_spec("kill@1:w1,join@1")
        ch = plan.apply(ev)
        assert ch.changed and ch.worker_ids == [0, 2, 3, 4]
        assert ch.kept_positions == [0, 2, 3] and ch.joiner_ids == [4]
        # ids are never recycled: the next joiner takes 5, not 1
        ch2 = plan.apply(chaos_lib.parse_chaos_spec("join@2"))
        assert ch2.worker_ids == [0, 2, 3, 4, 5]

    def test_snapshot_allocator_position_never_recycles_max_id(self):
        # killing the MAX-id worker must not let a fresh-twin plan
        # (rebuilt from the snapshot roster) recompute next_id as max+1
        # and recycle the dead worker's id — that would hand a later
        # joiner a different fold_in RNG stream than the continued run's
        plan = elastic_lib.MembershipPlan(4)
        ch = plan.apply(chaos_lib.parse_chaos_spec("kill@1:w3"))
        assert ch.worker_ids == [0, 1, 2] and plan.next_id == 4
        twin = elastic_lib.MembershipPlan(
            3, worker_ids=ch.worker_ids, next_id=plan.next_id)
        ch2 = twin.apply(chaos_lib.parse_chaos_spec("join@2"))
        assert ch2.joiner_ids == [4]          # NOT a recycled 3
        assert plan.apply(
            chaos_lib.parse_chaos_spec("join@2")).joiner_ids == [4]

    def test_quorum_floor_rejects_never_partially_applies(self):
        plan = elastic_lib.MembershipPlan(2, min_workers=2)
        ch = plan.apply(chaos_lib.parse_chaos_spec("kill@1:w0"))
        assert not ch.changed and plan.worker_ids == [0, 1]
        assert ch.rejected and "quorum" in ch.rejected[0]["reason"]

    def test_capacity_ceiling_rejects_join(self):
        plan = elastic_lib.MembershipPlan(3, max_workers=3)
        ch = plan.apply(chaos_lib.parse_chaos_spec("join@1"))
        assert not ch.changed
        assert "capacity" in ch.rejected[0]["reason"]

    def test_unknown_target_rejected(self):
        plan = elastic_lib.MembershipPlan(3)
        ch = plan.apply(chaos_lib.parse_chaos_spec("kill@1:w9"))
        assert not ch.changed and "not in membership" in \
            ch.rejected[0]["reason"]


class TestRingNeighbors:
    @pytest.mark.parametrize("n", [2, 3, 5, 8])
    def test_shift1_is_a_single_full_cycle(self, n):
        perm = ring_neighbors(n)
        assert sorted(s for s, _ in perm) == list(range(n))
        assert sorted(d for _, d in perm) == list(range(n))
        seen, cur = set(), 0
        nxt = dict(perm)
        while cur not in seen:
            seen.add(cur)
            cur = nxt[cur]
        assert seen == set(range(n))   # no stranded sub-ring

    def test_resize_rederives_the_table(self):
        # the elastic property: the table depends on the axis size alone
        assert ring_neighbors(4) != ring_neighbors(3)
        assert ring_neighbors(3, shift=2) == [(0, 2), (1, 0), (2, 1)]


class TestMeshResize:
    def test_resize_matches_fresh_build(self, devices):
        m4 = mesh_lib.build_mesh({"data": 4})
        m3 = mesh_lib.resize_data_axis(m4, 3)
        fresh = mesh_lib.build_mesh({"data": 3})
        assert m3.shape == fresh.shape
        assert list(m3.devices.flat) == list(fresh.devices.flat)
        assert mesh_lib.max_data_axis_size(m4) == 8

    def test_resize_past_capacity_raises(self, devices):
        m = mesh_lib.build_mesh({"data": 8})
        with pytest.raises(ValueError, match="devices"):
            mesh_lib.resize_data_axis(m, 9)
        with pytest.raises(ValueError, match=">= 1"):
            mesh_lib.resize_data_axis(m, 0)


class TestJoinerSeed:
    def test_modes(self):
        spb = np.array([1.0, 2.0, 4.0])
        assert probe_lib.joiner_sec_per_batch(spb, "mean") == pytest.approx(7 / 3)
        assert probe_lib.joiner_sec_per_batch(spb, "max") == 4.0
        assert probe_lib.joiner_sec_per_batch(spb, "min") == 1.0
        with pytest.raises(ValueError):
            probe_lib.joiner_sec_per_batch(np.array([]), "mean")
        with pytest.raises(ValueError):
            probe_lib.joiner_sec_per_batch(spb, "median")


class TestAdaptivePartition:
    def test_balanced_matches_driver_recipe(self):
        ratios = efficiency_ratios(np.array([1.0, 2.0, 1.0]), "inverse")
        assert all(
            (a == b).all() for a, b in zip(
                adaptive_partition(100, ratios),
                contiguous_partition(100, ratios)))

    def test_disbalanced_matches_skew_sequence(self):
        rng_a, rng_b = (np.random.default_rng(3) for _ in range(2))
        labels = np.random.default_rng(0).integers(0, 10, 200)
        ratios = efficiency_ratios(np.array([1.0, 1.0]), "inverse")
        fixed = [fixed_classes_for_rank(r, 10) for r in range(2)]
        got = adaptive_partition(200, ratios, labels=labels,
                                 fixed_classes=fixed, fixed_ratio=0.5,
                                 rng=rng_a)
        want = [skew_partition(labels, p, fixed[i], 0.5, rng_b)
                for i, p in enumerate(contiguous_partition(200, ratios))]
        assert all((a == b).all() for a, b in zip(got, want))
        # both rngs consumed the identical draw sequence
        assert rng_a.bit_generator.state == rng_b.bit_generator.state

    def test_disbalanced_requires_labels_and_rng(self):
        with pytest.raises(ValueError, match="labels and rng"):
            adaptive_partition(10, np.array([0.5, 0.5]),
                               fixed_classes=[[0], [1]])


class TestReshardState:
    def _host_state(self, mesh4):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import LocalSGDEngine
        # param_residency pinned replicated: these cases gate the PER-
        # WORKER row edit (survivor np.take, joiner clone, zero EF rows);
        # the compressed-weights config would otherwise auto-resolve the
        # ISSUE 11 resident layout, whose consensus params are re-TILED
        # instead of row-edited (tests/test_param_residency.py owns that)
        cfg = Config(model="mlp", batch_size=8, sync_compression="ef",
                     sync_dtype="bfloat16", aggregation_by="weights",
                     param_residency="replicated")
        eng = LocalSGDEngine(get_model("mlp", num_classes=10, hidden=8),
                             mesh4, cfg)
        state = eng.init_state(jax.random.key(0), np.zeros((8, 28, 28, 1),
                                                           np.float32))
        return eng, elastic_lib.host_state_snapshot(state)

    @pytest.fixture(scope="class")
    def mesh4(self, devices):
        return mesh_lib.build_mesh({"data": 4})

    def test_survivors_bit_exact_joiner_cloned(self, mesh4):
        eng, host = self._host_state(mesh4)
        out = elastic_lib.reshard_state(host, kept_positions=[0, 2, 3],
                                        joiner_ids=[4], seed=0)
        leaves_in = jax.tree_util.tree_leaves(host)
        leaves_out = jax.tree_util.tree_leaves(out)
        for a, b in zip(leaves_in, leaves_out):
            assert b.shape[0] == 4
            # survivor rows verbatim, in old relative order
            np.testing.assert_array_equal(b[:3], a[[0, 2, 3]])
        # the joiner clones the FIRST survivor's params/moments row ...
        p_in = jax.tree_util.tree_leaves(host.params)
        p_out = jax.tree_util.tree_leaves(out.params)
        for a, b in zip(p_in, p_out):
            np.testing.assert_array_equal(b[3], a[0])
        # ... but draws a FRESH rng stream keyed by its logical id
        expect = np.asarray(jax.random.key_data(
            jax.random.fold_in(jax.random.key(0), 4)))
        np.testing.assert_array_equal(out.rng[3], expect)
        assert not (out.rng[3] == out.rng[0]).all()
        # ... and zero EF residual (a cloned one would double-inject the
        # donor's accumulated quantization error)
        for r_in, r_out in zip(
                jax.tree_util.tree_leaves(host.sync_residual),
                jax.tree_util.tree_leaves(out.sync_residual)):
            np.testing.assert_array_equal(r_out[:3], r_in[[0, 2, 3]])
            assert (r_out[3] == 0).all()

    def test_no_survivors_raises(self, mesh4):
        _, host = self._host_state(mesh4)
        with pytest.raises(ValueError, match="no surviving"):
            elastic_lib.reshard_state(host, kept_positions=[],
                                      joiner_ids=[0], seed=0)


# ----------------------------------------------------------------------
# The elastic round loop (driver e2e, simulated N-worker CPU)
# ----------------------------------------------------------------------

def _cfg(**kw):
    base = dict(model="mlp", dataset="mnist", epochs_global=5,
                epochs_local=1, batch_size=16, limit_train_samples=400,
                limit_eval_samples=100, compute_dtype="float32",
                augment=False, aggregation_by="weights", seed=1,
                num_workers=4)
    base.update(kw)
    return Config(**base)


PROBE4 = np.array([1.0, 1.5, 1.0, 2.0])

TAIL_KEYS = ("global_train_losses", "global_val_losses",
             "global_train_accuracies", "global_val_accuracies",
             "step_caps", "shard_sizes")


def _assert_bitwise_tail(full, fresh, boundary: int):
    """The fresh-from-snapshot run's whole trajectory must equal the
    continued run's post-boundary tail EXACTLY (fp32 list equality —
    bitwise for the float entries)."""
    for k in TAIL_KEYS:
        assert full[k][boundary:] == fresh[k], f"results[{k!r}] diverged"


class TestElasticRoundLoop:
    def test_kill_mid_run_bitwise_matches_fresh_run(self):
        """THE acceptance gate: a worker killed at a round boundary, the
        run continues in process, and the post-event trajectory is
        bitwise-identical to a fresh run started from the captured
        membership snapshot — sanitized, zero unsanctioned retraces."""
        kw = dict(chaos="kill@2:w1", sanitize=True)
        walls = lambda e: np.ones(4 if e < 2 else 3)
        full = train_global(_cfg(**kw), progress=False,
                            simulated_durations=PROBE4,
                            simulated_round_durations=walls)
        el = full["elastic"]
        assert el["enabled"] and el["events"] == [
            {"round": 2, "kind": "kill", "worker": 1}]
        assert el["final_worker_ids"] == [0, 2, 3]
        assert el["rounds_degraded"] == 3 and len(el["reshard_ms"]) == 1
        assert el["reshard_ms"][0] > 0
        assert full["sanitize"]["retrace_count"] == 0
        assert full["sanitize"]["transfer_guard_violations"] == 0
        # the dead worker's per-worker curve freezes at the boundary
        assert len(full["all_workers_losses"]) == 4
        snap = el["snapshots"][0]
        assert (snap.epoch, snap.worker_ids) == (2, [0, 2, 3])
        fresh = train_global(_cfg(**kw), progress=False,
                             simulated_durations=PROBE4,
                             simulated_round_durations=walls,
                             elastic_snapshot=snap)
        assert len(fresh["global_train_losses"]) == 3
        assert fresh["sanitize"]["retrace_count"] == 0
        _assert_bitwise_tail(full, fresh, boundary=2)
        # per-worker curves too: survivors' tails match the fresh run
        for wid in (0, 2, 3):
            tail = full["all_workers_losses"][wid]
            assert tail[-len(fresh["all_workers_losses"][wid]):] == \
                fresh["all_workers_losses"][wid]

    def test_join_mid_run_completes_in_process(self):
        walls = lambda e: np.ones(4 if e < 2 else 5)
        res = train_global(_cfg(chaos="join@2", epochs_global=4,
                                sanitize=True),
                           progress=False, simulated_durations=PROBE4,
                           simulated_round_durations=walls)
        el = res["elastic"]
        assert el["events"] == [{"round": 2, "kind": "join", "worker": 4}]
        assert el["final_worker_ids"] == [0, 1, 2, 3, 4]
        assert el["rounds_degraded"] == 0
        assert res["sanitize"]["retrace_count"] == 0
        # the joiner trains from its admission round on
        assert len(res["all_workers_losses"]) == 5
        assert len(res["all_workers_losses"][4]) > 0
        assert np.isfinite(res["global_train_losses"]).all()
        # its shard was carved from the survivors' EMA-seeded share
        assert len(res["shard_sizes"][-1]) == 5

    def test_straggler_departs_after_retry_budget(self):
        # slow@1:w3x100 makes worker 3 overrun time_limit + grace from
        # round 1 on: round 1 = tolerated retry (backoff-extended
        # deadline), round 2 = retries exhausted -> departs at round 3's
        # boundary, shard redistributed — the retry/timeout/backoff
        # protocol end to end, no scripted kill involved
        res = train_global(
            _cfg(chaos="slow@1:w3x100", time_limit=10.0, chaos_grace=5.0,
                 chaos_retries=1, chaos_backoff=0.5),
            progress=False, simulated_durations=PROBE4,
            simulated_round_durations=lambda e: np.ones(4 if e < 3 else 3))
        el = res["elastic"]
        assert [r["worker"] for r in el["sync_retries"]] == [3]
        assert el["sync_retries"][0]["attempt"] == 1
        assert el["events"] == [{"round": 3, "kind": "depart", "worker": 3}]
        assert el["final_worker_ids"] == [0, 1, 2]
        assert np.isfinite(res["global_train_losses"]).all()

    def test_stall_retry_then_recovery_keeps_membership(self):
        # a one-round stall trips a retry but recovers inside the budget:
        # nobody departs, the attempt counter resets
        res = train_global(
            _cfg(chaos="stall@1:w2+100", epochs_global=4, time_limit=10.0,
                 chaos_grace=5.0, chaos_retries=1, chaos_backoff=0.5),
            progress=False, simulated_durations=PROBE4,
            simulated_round_durations=lambda e: np.ones(4))
        el = res["elastic"]
        assert [r["worker"] for r in el["sync_retries"]] == [2]
        assert el["events"] == [] and el["final_worker_ids"] == [0, 1, 2, 3]
        assert el["reshard_ms"] == []

    def test_quorum_floor_degrades_gracefully(self):
        # killing below --elastic_min_workers is rejected + recorded; the
        # surviving quorum keeps training with no membership change
        res = train_global(
            _cfg(chaos="kill@1:w0,kill@1:w1,kill@1:w2,kill@1:w3",
                 elastic_min_workers=2, epochs_global=3),
            progress=False, simulated_durations=PROBE4,
            simulated_round_durations=lambda e: np.ones(4 if e < 1 else 2))
        el = res["elastic"]
        assert len(el["events"]) == 2 and len(el["rejected"]) == 2
        assert all("quorum" in r["reason"] for r in el["rejected"])
        assert el["final_worker_ids"] == [2, 3]
        assert np.isfinite(res["global_train_losses"]).all()


@pytest.mark.slow
class TestElasticSlow:
    def test_join_bitwise_matches_fresh_run(self):
        kw = dict(chaos="join@2", sanitize=True)
        walls = lambda e: np.ones(4 if e < 2 else 5)
        full = train_global(_cfg(**kw), progress=False,
                            simulated_durations=PROBE4,
                            simulated_round_durations=walls)
        snap = full["elastic"]["snapshots"][0]
        assert snap.worker_ids == [0, 1, 2, 3, 4]
        fresh = train_global(_cfg(**kw), progress=False,
                             simulated_durations=PROBE4,
                             simulated_round_durations=walls,
                             elastic_snapshot=snap)
        _assert_bitwise_tail(full, fresh, boundary=2)
        assert full["all_workers_losses"][4] == \
            fresh["all_workers_losses"][4]

    def test_kill_max_id_then_join_bitwise_matches_fresh_run(self):
        # regression (code review): the snapshot carries the plan's id
        # allocator position.  Killing the MAX-id worker before the
        # join means a fresh-twin run recomputing next_id as max+1
        # would recycle id 3 for the joiner — a different RNG stream,
        # bitwise-diverging from the continued run (which hands out 4).
        kw = dict(chaos="kill@1:w3,join@3", sanitize=True)
        walls = lambda e: np.ones(4 if e < 1 else (3 if e < 3 else 4))
        full = train_global(_cfg(**kw), progress=False,
                            simulated_durations=PROBE4,
                            simulated_round_durations=walls)
        el = full["elastic"]
        assert el["final_worker_ids"] == [0, 1, 2, 4]   # 3 not recycled
        snap = el["snapshots"][0]            # post-kill boundary
        assert snap.next_worker_id == 4
        fresh = train_global(_cfg(**kw), progress=False,
                             simulated_durations=PROBE4,
                             simulated_round_durations=walls,
                             elastic_snapshot=snap)
        assert fresh["elastic"]["final_worker_ids"] == [0, 1, 2, 4]
        _assert_bitwise_tail(full, fresh, boundary=1)

    @pytest.mark.parametrize("topology", ["ring", "double_ring"])
    def test_gossip_topologies_kill_and_join(self, topology):
        # the dangerous case for rings: a departed worker must never
        # strand a ppermute neighbor — the rebuilt engine re-derives the
        # neighbor tables from the new axis size.  Full bitwise gate per
        # topology.
        kw = dict(chaos="kill@1:w2,join@2", topology=topology,
                  epochs_global=4)
        walls = lambda e: np.ones(4 if e < 1 else (3 if e < 2 else 4))
        full = train_global(_cfg(**kw), progress=False,
                            simulated_durations=PROBE4,
                            simulated_round_durations=walls)
        el = full["elastic"]
        assert el["final_worker_ids"] == [0, 1, 3, 4]
        assert np.isfinite(full["global_train_losses"]).all()
        snap = el["snapshots"][1]       # post-join boundary (round 2)
        fresh = train_global(_cfg(**kw), progress=False,
                             simulated_durations=PROBE4,
                             simulated_round_durations=walls,
                             elastic_snapshot=snap)
        _assert_bitwise_tail(full, fresh, boundary=2)

    def test_crash_during_reshard_resumes_and_replays(self, tmp_path,
                                                      monkeypatch):
        # the recovery story: a crash INSIDE the membership transition
        # (after the old state is snapshotted, before the new engine
        # exists) resumes from the last committed checkpoint and REPLAYS
        # the deterministic chaos schedule — the event re-applies at the
        # same boundary and the run completes without the crashed
        # process's in-memory state
        kw = dict(chaos="kill@2:w1", epochs_global=3,
                  checkpoint_dir=str(tmp_path), checkpoint_every=1)
        walls = lambda e: np.ones(4 if e < 2 else 3)
        run = lambda **o: train_global(
            _cfg(**kw, **o), progress=False, simulated_durations=PROBE4,
            simulated_round_durations=walls)
        monkeypatch.setenv("JAX_GRAFT_ELASTIC_TEST_CRASH", "mid_reshard")
        with pytest.raises(RuntimeError, match="elastic test crash hook"):
            run()
        monkeypatch.delenv("JAX_GRAFT_ELASTIC_TEST_CRASH")
        # snapshot the post-crash checkpoint dir so the recovery can run
        # twice from the identical on-disk state (the first resume
        # appends its own epoch-3 checkpoint)
        import shutil
        twin_dir = str(tmp_path) + "_twin"
        shutil.copytree(str(tmp_path), twin_dir)
        resumed = run(resume=True)
        el = resumed["elastic"]
        assert el["events"] == [{"round": 2, "kind": "kill", "worker": 1}]
        assert el["final_worker_ids"] == [0, 2, 3]
        # exactly the post-crash round ran (rounds 0-1 are committed;
        # the kill@2 boundary event re-applies on replay, NOT skipped)
        assert len(resumed["global_train_losses"]) == 1
        assert np.isfinite(resumed["global_train_losses"]).all()
        assert len(el["reshard_ms"]) == 1
        # the recovery is deterministic: a second resume from the same
        # on-disk state replays the schedule to a bitwise-identical tail
        # (host-side loop state — wall EMA, partition rng — recomputes
        # from the probe on ANY resume, so the uninterrupted run is not
        # the comparison point; the snapshot gate above covers that)
        again = train_global(
            _cfg(**{**kw, "checkpoint_dir": twin_dir}, resume=True),
            progress=False, simulated_durations=PROBE4,
            simulated_round_durations=walls)
        assert again["global_train_losses"] == \
            resumed["global_train_losses"]
        assert again["elastic"]["final_worker_ids"] == [0, 2, 3]

    def test_resume_across_earlier_membership_events_refused(
            self, tmp_path):
        kw = dict(chaos="kill@1:w1", epochs_global=3,
                  checkpoint_dir=str(tmp_path), checkpoint_every=1)
        walls = lambda e: np.ones(4 if e < 1 else 3)
        train_global(_cfg(**kw), progress=False,
                     simulated_durations=PROBE4,
                     simulated_round_durations=walls)
        with pytest.raises(ValueError, match="membership events"):
            train_global(_cfg(**{**kw, "epochs_global": 4}, resume=True),
                         progress=False, simulated_durations=PROBE4,
                         simulated_round_durations=walls)

    def test_resume_across_straggler_departure_refused(self, tmp_path):
        # a STRAGGLER-protocol departure never appears in the --chaos
        # schedule, so the scripted-event scan can't see it — the
        # manifest's recorded worker axis must refuse the resume with
        # the real reason instead of restore's opaque shape mismatch
        kw = dict(chaos="slow@1:w3x100", time_limit=10.0, chaos_grace=5.0,
                  chaos_retries=0, epochs_global=3,
                  checkpoint_dir=str(tmp_path), checkpoint_every=1)
        walls = lambda e: (np.ones(4) if e < 2 else np.ones(3))
        res = train_global(_cfg(**kw), progress=False,
                           simulated_durations=PROBE4,
                           simulated_round_durations=walls)
        assert res["elastic"]["final_worker_ids"] == [0, 1, 2]  # departed
        with pytest.raises(ValueError, match="worker"):
            train_global(_cfg(**{**kw, "epochs_global": 4}, resume=True),
                         progress=False, simulated_durations=PROBE4,
                         simulated_round_durations=walls)

    def test_random_chaos_run_completes(self):
        # seeded-random schedule: whatever the draw, the run must finish
        # on the surviving quorum with finite losses and consistent
        # telemetry (quorum floor 2 keeps kills survivable)
        res = train_global(
            _cfg(chaos="random", chaos_seed=11, chaos_events=4,
                 elastic_min_workers=2, epochs_global=5, time_limit=10.0),
            progress=False, simulated_durations=PROBE4)
        el = res["elastic"]
        assert el["enabled"]
        assert len(el["events"]) + len(el["rejected"]) >= 0
        assert len(el["final_worker_ids"]) >= 2
        assert np.isfinite(res["global_train_losses"]).all()
        assert len(res["global_train_losses"]) == 5

    def test_disbalanced_mode_kill_completes(self):
        # the skew re-draw path: fixed classes follow LOGICAL ids and the
        # partition re-draws from the post-event roster
        walls = lambda e: np.ones(4 if e < 2 else 3)
        res = train_global(
            _cfg(chaos="kill@2:w1", data_mode="disbalanced",
                 epochs_global=4),
            progress=False, simulated_durations=PROBE4,
            simulated_round_durations=walls)
        assert res["elastic"]["final_worker_ids"] == [0, 2, 3]
        assert np.isfinite(res["global_train_losses"]).all()
