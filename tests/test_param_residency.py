"""Scatter-resident consensus params (ISSUE 11 tentpole): round-loop FSDP.

The between-round parameter state under ``--param_residency resident`` is
each worker's 1/N bucket shard of the consensus (exactly the sync's
psum_scatter output, post-apply); the round program all_gathers the full
tree just-in-time at entry and the sync ends at the scatter — the
trailing all_gather moved from sync-exit to next-round-entry, so it moves
the SAME bytes and the trajectories are BITWISE identical to the
replicated twin:

- comms level: resident cycle (sync -> stay scattered -> entry gather)
  vs the replicated program, 2/4/8 workers, fp32 and the compressed
  wire's decoded handoff;
- engine level: whole rounds (fused CPU sync and the standalone/streamed
  sync program), equal active + weighted/gradients resolution;
- driver level (slow): sanitized e2e incl. an elastic kill+join and a
  checkpoint save/restore.

Resolution: resident requires the bucketed sharded engine + weights x
equal aggregation — the weighted blend's own-term and gradients-mode
params are irreducibly per-worker (the PR 9 ARCHITECTURE.md argument),
gossip has no scatter at all.  Checkpoints save the resident shards
directly (no gather on the write path) and re-layout across residency
modes on restore; elastic membership changes re-tile the shards.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu import (
    comms,
    elastic as elastic_lib,
    mesh as mesh_lib,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu import checkpoint as ckpt_lib
from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import (
    LocalSGDEngine,
    TrainState,
    rank0_variables,
)

N = 8
SHAPES = {"a": (13, 7), "b": (257,), "c": (31, 5), "d": (3,)}
TINY_BUCKET = 1024


def stacked_tree(n=N, seed=0):
    rng = np.random.default_rng(seed)
    return {k: jnp.asarray(rng.normal(size=(n, *s)), jnp.float32)
            for k, s in SHAPES.items()}


def per_worker_shapes():
    return {k: jax.ShapeDtypeStruct(s, jnp.float32)
            for k, s in SHAPES.items()}


def sub_mesh(k):
    return mesh_lib.build_mesh({"data": k}, devices=jax.devices()[:k])


def small_cfg(**kw):
    base = dict(model="mlp", dataset="mnist", epochs_local=2,
                epochs_global=2, batch_size=8, compute_dtype="float32",
                augment=False, aggregation_by="weights",
                sync_mode="sharded", sync_bucket_mb=0.001)
    base.update(kw)
    return Config(**base)


def make_engine(mesh, cfg):
    return LocalSGDEngine(get_model("mlp", num_classes=10, hidden=16),
                          mesh, cfg)


def make_packs(n=8, steps=4, b=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, steps, b, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, (n, steps, b)).astype(np.int32)
    m = np.ones((n, steps, b), np.float32)
    return x, y, m


def assert_trees_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)), a, b)


class TestResidencyResolution:
    def test_auto_follows_the_engine_and_aggregation(self):
        # resident needs the bucketed sharded engine AND a consensus to
        # shard (weights x equal); CPU fp32 auto resolves the dense twin
        assert small_cfg().resolve_param_residency("cpu") == "resident"
        assert small_cfg(
            sync_mode="auto",
            sync_bucket_mb=4.0).resolve_param_residency("cpu") == "replicated"
        assert small_cfg(
            sync_mode="auto",
            sync_bucket_mb=4.0).resolve_param_residency("tpu") == "resident"
        assert small_cfg(
            sync_dtype="bfloat16", sync_compression="ef", sync_mode="auto",
        ).resolve_param_residency("cpu") == "resident"

    def test_worker_local_states_resolve_replicated(self):
        # the weighted own-term and gradients-mode params are
        # irreducibly per-worker — the PR 9 documented argument
        for kw in (dict(aggregation_type="weighted"),
                   dict(aggregation_by="gradients")):
            cfg = small_cfg(param_residency="resident", **kw)
            assert cfg.resolve_param_residency("cpu") == "replicated", kw

    def test_explicit_resident_selects_the_fast_engine(self):
        cfg = small_cfg(sync_mode="auto", param_residency="resident",
                        sync_bucket_mb=4.0)
        assert cfg.resolve_sync_mode("cpu") == "sharded"
        assert cfg.resolve_param_residency("cpu") == "resident"

    def test_replicated_placement_resolves_residency_replicated(self):
        cfg = small_cfg(opt_placement="replicated")
        assert cfg.resolve_param_residency("cpu") == "replicated"

    @pytest.mark.parametrize("kw,match", [
        (dict(topology="ring"), "topology"),
        (dict(topology="double_ring"), "topology"),
        (dict(sync_mode="dense"), "dense"),
        (dict(opt_placement="replicated"), "replicated"),
    ])
    def test_eager_rejections(self, kw, match):
        with pytest.raises(ValueError, match=match):
            base = dict(param_residency="resident")
            base.update(kw)
            if "sync_mode" in kw or "opt_placement" in kw:
                small_cfg(**base)
            else:
                Config(**base)

    def test_engine_demotes_under_inner_axes(self):
        mesh = mesh_lib.build_mesh({"data": 4, "model": 2})
        eng = LocalSGDEngine(
            get_model("bert_tiny", num_classes=8, scan_layers=True),
            mesh, small_cfg(model="bert_tiny",
                            param_residency="resident",
                            mesh_shape="data=4,model=2"),
            param_specs_fn=lambda p: __import__(
                "learning_deep_neural_network_in_distributed_computing_environment_tpu.models.bert",
                fromlist=["tp_param_specs"]).tp_param_specs(p, axis="model"))
        assert eng.param_residency == "replicated"

    def test_comms_rejects_resident_without_equal_sharded(self, mesh8):
        tree = stacked_tree()
        with pytest.raises(Exception, match="equal blend"):
            comms.make_host_sync(
                mesh8, mode="sharded", how="weighted",
                param_residency="resident")(tree)
        with pytest.raises(ValueError, match="scatter"):
            comms.make_host_sync(mesh8, mode="gossip", topology="ring",
                                 param_residency="resident")

    def test_comms_rejects_single_worker_resident(self):
        mesh1 = sub_mesh(1)
        with pytest.raises(Exception, match="worker axis"):
            comms.make_host_sync(
                mesh1, mode="sharded",
                param_residency="resident")(stacked_tree(n=1))


class TestCommsResidentCycle:
    @pytest.mark.parametrize("k", [2, 4, 8])
    def test_fp32_cycle_bitwise_equals_replicated(self, k):
        """The acceptance gate, comms level: sync -> stay scattered ->
        entry gather reproduces the replicated program's output
        bit-for-bit (the gather moves the same bytes, one round later)."""
        mesh = sub_mesh(k)
        tree = stacked_tree(n=k)
        rep = comms.make_host_sync(mesh, mode="sharded",
                                   bucket_bytes=TINY_BUCKET)(tree)[0]
        res, _r = comms.make_host_sync(
            mesh, mode="sharded", bucket_bytes=TINY_BUCKET,
            param_residency="resident")(tree)
        for leaf in jax.tree_util.tree_leaves(res):
            assert leaf.shape[0] == k          # [n, padded/n] bucket rows
        gat = comms.make_resident_gather(mesh, per_worker_shapes(),
                                         bucket_bytes=TINY_BUCKET)(res)
        assert_trees_equal(gat, rep)

    def test_compressed_wire_handoff_bitwise(self, mesh8):
        # the resident shard stores the DECODED mean (own scale applied),
        # so the entry gather concatenates exactly what gather_decoded
        # would have produced — bitwise even on the int8 wire
        tree = stacked_tree()
        res0 = {k: jnp.zeros((N, *s), jnp.float32)
                for k, s in SHAPES.items()}
        for wdt in (jnp.bfloat16, jnp.int8):
            rep = comms.make_host_sync(
                mesh8, mode="sharded", wire_dtype=wdt,
                bucket_bytes=TINY_BUCKET)(tree, res0)[0]
            res, _r = comms.make_host_sync(
                mesh8, mode="sharded", wire_dtype=wdt,
                bucket_bytes=TINY_BUCKET,
                param_residency="resident")(tree, res0)
            gat = comms.make_resident_gather(
                mesh8, per_worker_shapes(), bucket_bytes=TINY_BUCKET)(res)
            assert_trees_equal(gat, rep)

    def test_host_twins_roundtrip_bitwise(self, mesh8):
        # resident_to_tree is the host twin of the device gather and
        # resident_from_tree its exact inverse
        tree = stacked_tree()
        res, _ = comms.make_host_sync(
            mesh8, mode="sharded", bucket_bytes=TINY_BUCKET,
            param_residency="resident")(tree)
        host = jax.device_get(res)
        rep = comms.make_host_sync(mesh8, mode="sharded",
                                   bucket_bytes=TINY_BUCKET)(tree)[0]
        consensus = comms.resident_to_tree(host, per_worker_shapes(),
                                           bucket_bytes=TINY_BUCKET)
        for k in SHAPES:
            np.testing.assert_array_equal(np.asarray(rep[k][0]),
                                          consensus[k])
        back = comms.resident_from_tree(consensus, N,
                                        bucket_bytes=TINY_BUCKET)
        for b in host:
            np.testing.assert_array_equal(host[b], back[b])

    def test_relayout_retiles_exactly(self, mesh8):
        tree = stacked_tree()
        res, _ = comms.make_host_sync(
            mesh8, mode="sharded", bucket_bytes=TINY_BUCKET,
            param_residency="resident")(tree)
        host = jax.device_get(res)
        down = comms.resident_relayout(host, per_worker_shapes(), 3,
                                       bucket_bytes=TINY_BUCKET)
        back = comms.resident_relayout(down, per_worker_shapes(), N,
                                       bucket_bytes=TINY_BUCKET)
        for b in host:
            np.testing.assert_array_equal(np.asarray(host[b]), back[b])
        with pytest.raises(ValueError, match="bucket"):
            comms.resident_relayout({}, per_worker_shapes(), 4,
                                    bucket_bytes=TINY_BUCKET)


class TestEngineResidency:
    def _run(self, mesh, cfg, rounds=2):
        engine = make_engine(mesh, cfg)
        n = mesh.shape["data"]
        x, y, m = make_packs(n=n)
        state = engine.init_state(jax.random.key(0), x[0, 0])
        mx = None
        for _ in range(rounds):
            state, mx = engine.round(state, (x, y, m), (x, y, m))
        return engine, state, mx

    @pytest.mark.parametrize("k", [2, 8])
    def test_rounds_bitwise_across_residencies(self, k):
        outs = {}
        for pr in ("replicated", "resident"):
            eng, st, mx = self._run(sub_mesh(k),
                                    small_cfg(param_residency=pr))
            assert eng.param_residency == pr
            outs[pr] = (eng, st, mx)
        eng_r, st_r, mx_r = outs["resident"]
        assert st_r.params is None and st_r.params_resident is not None
        assert_trees_equal(eng_r.materialize_params(st_r),
                           outs["replicated"][0].materialize_params(
                               outs["replicated"][1]))
        for key in ("train_loss", "val_loss", "global_train_loss",
                    "global_val_loss"):
            np.testing.assert_array_equal(np.asarray(mx_r[key]),
                                          np.asarray(outs["replicated"][2][key]))

    @pytest.mark.parametrize("how,by", [("weighted", "weights"),
                                        ("equal", "gradients")])
    def test_worker_local_modes_demote_and_match(self, mesh8, how, by):
        # the resolution cells where resident degrades to replicated:
        # the programs must be IDENTICAL, not merely close
        outs = {}
        for pr in ("replicated", "resident"):
            eng, st, mx = self._run(
                mesh8, small_cfg(param_residency=pr, aggregation_type=how,
                                 aggregation_by=by), rounds=1)
            assert eng.param_residency == "replicated"
            assert st.params is not None and st.params_resident is None
            outs[pr] = (st, mx)
        assert_trees_equal(outs["resident"][0].params,
                           outs["replicated"][0].params)
        for key in ("train_loss", "val_loss"):
            np.testing.assert_array_equal(
                np.asarray(outs["resident"][1][key]),
                np.asarray(outs["replicated"][1][key]))

    def test_streamed_round_uses_enter_program_and_matches(self, mesh8):
        # the streamed path runs the standalone donated sync program
        # (resident exit) plus the donated enter-gather program
        outs = {}
        for pr in ("replicated", "resident"):
            engine = make_engine(mesh8, small_cfg(param_residency=pr,
                                                  epochs_local=1))
            x, y, m = make_packs()
            state = engine.init_state(jax.random.key(0), x[0, 0])
            chunks = lambda e: iter([(x[:, :2], y[:, :2], m[:, :2]),
                                     (x[:, 2:], y[:, 2:], m[:, 2:])])
            for _ in range(2):
                state, mx = engine.round_streamed(state, chunks, chunks)
            outs[pr] = (engine, state, mx)
        eng_r, st_r, mx_r = outs["resident"]
        assert "enter" in eng_r._round_cache
        assert st_r.params is None
        assert_trees_equal(eng_r.materialize_params(st_r),
                           outs["replicated"][0].materialize_params(
                               outs["replicated"][1]))
        np.testing.assert_array_equal(
            np.asarray(mx_r["train_loss"]),
            np.asarray(outs["replicated"][2]["train_loss"]))

    def test_resident_state_bytes_exactly_one_nth(self, mesh8):
        eng, st, _ = self._run(mesh8, small_cfg(param_residency="resident"),
                               rounds=1)
        b = eng.state_resident_bytes(st)
        # the transient gathered peak is the padded full buffers — the
        # resident shard is EXACTLY 1/N of it
        assert b["params"] > 0
        assert b["params"] * N == b["params_gathered_peak"]

    def test_rank0_variables_needs_template(self, mesh8):
        eng, st, _ = self._run(mesh8, small_cfg(param_residency="resident"),
                               rounds=1)
        with pytest.raises(ValueError, match="params_template"):
            rank0_variables(st)
        v = eng.rank0_variables(st)
        assert set(v["params"])   # non-empty params tree


class TestCheckpointCrossResidency:
    def _engine_state(self, mesh, pr):
        engine = make_engine(mesh, small_cfg(param_residency=pr))
        x, y, m = make_packs(n=mesh.shape["data"])
        state = engine.init_state(jax.random.key(0), x[0, 0])
        state, _ = engine.round(state, (x, y, m), (x, y, m))
        return engine, state

    def test_resident_save_has_no_full_params_and_roundtrips(self, mesh8,
                                                             tmp_path):
        eng_s, st_s = self._engine_state(mesh8, "resident")
        eng_r, tmpl_r = self._engine_state(mesh8, "replicated")
        ckpt_lib.save_checkpoint(str(tmp_path / "s"), st_s, 1)
        latest = ckpt_lib.latest_checkpoint(str(tmp_path / "s"))
        tree, ep = ckpt_lib.host_tree(latest)
        assert ep == 1
        # the save path serialized the 1/N shards directly — no full
        # params leaf was ever materialized or written
        assert any(k.startswith(".params_resident") for k in tree)
        assert not any(k.startswith(".params[") for k in tree)
        # resident save -> replicated restore
        got_r, _ = ckpt_lib.restore_checkpoint(
            latest, tmpl_r, params_template=eng_r.params_template,
            bucket_bytes=eng_r.sync_bucket_bytes)
        assert got_r.params is not None and got_r.params_resident is None
        assert_trees_equal(
            jax.tree_util.tree_map(lambda x: np.asarray(x)[0],
                                   jax.device_get(got_r.params)),
            eng_s.materialize_params(st_s))
        # replicated save -> resident restore, closing the loop bitwise
        ckpt_lib.save_checkpoint(str(tmp_path / "r"), got_r, 2)
        got_s, ep2 = ckpt_lib.restore_checkpoint(
            ckpt_lib.latest_checkpoint(str(tmp_path / "r")), st_s,
            params_template=eng_s.params_template,
            bucket_bytes=eng_s.sync_bucket_bytes)
        assert ep2 == 2
        for b, rows in jax.device_get(st_s.params_resident).items():
            np.testing.assert_array_equal(
                rows, np.asarray(jax.device_get(got_s.params_resident)[b]))

    def test_pre_issue11_checkpoint_restores_into_resident(self, mesh8,
                                                           tmp_path):
        # a replicated-era checkpoint (post-sync consensus rows) restores
        # into a resident run unchanged
        eng_p, st_p = self._engine_state(mesh8, "replicated")
        eng_s, tmpl_s = self._engine_state(mesh8, "resident")
        ckpt_lib.save_checkpoint(str(tmp_path / "p"), st_p, 3)
        got, ep = ckpt_lib.restore_checkpoint(
            ckpt_lib.latest_checkpoint(str(tmp_path / "p")), tmpl_s,
            params_template=eng_s.params_template,
            bucket_bytes=eng_s.sync_bucket_bytes)
        assert ep == 3 and got.params is None
        assert_trees_equal(eng_s.materialize_params(got),
                           eng_p.materialize_params(st_p))

    def test_non_consensus_rows_refused(self, mesh8, tmp_path):
        # a gradients-mode state's params rows differ per worker; packing
        # row 0 silently would lose information — must refuse
        eng_g, st_g = self._engine_state(mesh8, "replicated")
        host = jax.device_get(st_g)
        bad = host.replace(params=jax.tree_util.tree_map(
            lambda x: np.asarray(x)
            + np.arange(x.shape[0], dtype=np.float32).reshape(
                (-1,) + (1,) * (np.ndim(x) - 1)), host.params))
        bad = jax.tree_util.tree_map(np.asarray, bad)
        ckpt_lib.save_checkpoint(str(tmp_path / "b"), bad, 4)
        eng_s, tmpl_s = self._engine_state(mesh8, "resident")
        with pytest.raises(ValueError, match="consensus"):
            ckpt_lib.restore_checkpoint(
                ckpt_lib.latest_checkpoint(str(tmp_path / "b")), tmpl_s,
                params_template=eng_s.params_template,
                bucket_bytes=eng_s.sync_bucket_bytes)


class TestElasticResidentRelayout:
    def _host_state(self, n=4):
        pw = per_worker_shapes()
        rng = np.random.default_rng(3)
        consensus = {k: rng.normal(size=s).astype(np.float32)
                     for k, s in SHAPES.items()}
        resident = comms.resident_from_tree(consensus, n,
                                            bucket_bytes=TINY_BUCKET)
        opt = {k: np.zeros((n, *s), np.float32) for k, s in SHAPES.items()}
        return consensus, TrainState(
            params=None, params_resident=resident, batch_stats={},
            opt_state={"mu": opt},
            lr_epoch=np.zeros((n,), np.int32),
            rng=np.zeros((n, 2), np.uint32)), pw

    def test_kill_join_retiles_the_consensus(self):
        consensus, host, pw = self._host_state()
        out = elastic_lib.reshard_state(
            host, kept_positions=[0, 2, 3], joiner_ids=[4], seed=0,
            sync_bucket_bytes=TINY_BUCKET, params_template=pw)
        # same n: the consensus vector is preserved exactly (kill+join
        # is a swap; joiners need no params clone — the consensus IS
        # every worker's value)
        got = comms.resident_to_tree(out.params_resident, pw,
                                     bucket_bytes=TINY_BUCKET)
        assert_trees_equal(got, consensus)
        # per-worker rows still row-edited
        assert out.lr_epoch.shape == (4,)

    def test_shrink_retiles_and_quorum_of_one_demotes(self):
        consensus, host, pw = self._host_state()
        down = elastic_lib.reshard_state(
            host, kept_positions=[0, 1, 2], joiner_ids=[], seed=0,
            sync_bucket_bytes=TINY_BUCKET, params_template=pw)
        assert down.params is None
        got = comms.resident_to_tree(down.params_resident, pw,
                                     bucket_bytes=TINY_BUCKET)
        assert_trees_equal(got, consensus)
        solo = elastic_lib.reshard_state(
            host, kept_positions=[2], joiner_ids=[], seed=0,
            sync_bucket_bytes=TINY_BUCKET, params_template=pw)
        # a 1-worker engine runs replicated: materialized and tiled
        assert solo.params_resident is None
        assert_trees_equal(
            jax.tree_util.tree_map(lambda x: x[0], solo.params), consensus)

    def test_missing_layout_kwargs_raise(self):
        _c, host, _pw = self._host_state()
        with pytest.raises(ValueError, match="params_template"):
            elastic_lib.reshard_state(host, kept_positions=[0, 1],
                                      joiner_ids=[], seed=0)


# ----------------------------------------------------------------------
# Driver e2e composition (slow: each case is multiple train_global runs)
# ----------------------------------------------------------------------

def _e2e_cfg(**kw):
    base = dict(model="mlp", dataset="mnist", epochs_global=4,
                epochs_local=1, batch_size=16, limit_train_samples=400,
                limit_eval_samples=100, compute_dtype="float32",
                augment=False, seed=1, num_workers=4,
                aggregation_by="weights", sync_mode="sharded",
                sync_bucket_mb=0.001)
    base.update(kw)
    return Config(**base)


PROBE4 = np.array([1.0, 1.5, 1.0, 2.0])

# pinned round walls: the repartition EMA consumes measured wall times,
# so an A/B of two runs must feed both the same vector or the shards
# (and with them the trajectories) drift apart from round 2 on
WALLS4 = lambda e: np.ones(4)

TAIL_KEYS = ("global_train_losses", "global_val_losses",
             "global_train_accuracies", "global_val_accuracies",
             "step_caps", "shard_sizes")


@pytest.mark.slow
class TestDriverResidency:
    """The acceptance gate at the sanitized-driver level: fp32 resident
    trajectories bitwise-match the replicated twin across the
    equal/weighted x weights/gradients matrix, including through an
    elastic kill+join and a checkpoint save/restore."""

    @pytest.mark.parametrize("how,by", [("equal", "weights"),
                                        ("weighted", "weights"),
                                        ("equal", "gradients")])
    def test_sanitized_trajectories_bitwise(self, how, by):
        runs = {}
        for pr in ("replicated", "resident"):
            res = train_global(
                _e2e_cfg(param_residency=pr, aggregation_type=how,
                         aggregation_by=by, sanitize=True),
                progress=False, simulated_durations=PROBE4,
                simulated_round_durations=WALLS4)
            assert res["sanitize"]["retrace_count"] == 0
            assert res["sanitize"]["transfer_guard_violations"] == 0
            runs[pr] = res
        # equal x weights actually runs resident; the worker-local cells
        # resolve to replicated — either way the trajectories must match
        expect = ("resident" if (how, by) == ("equal", "weights")
                  else "replicated")
        assert runs["resident"]["sync_engine"]["param_residency"] == expect
        for k in TAIL_KEYS:
            assert runs["resident"][k] == runs["replicated"][k], k
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                       np.asarray(b)),
            runs["resident"]["variables"], runs["replicated"]["variables"])

    def test_kill_join_keeps_the_bitwise_gate(self):
        kw = dict(chaos="kill@2:w1,join@2", sanitize=True)
        runs = {}
        for pr in ("replicated", "resident"):
            runs[pr] = train_global(
                _e2e_cfg(param_residency=pr, **kw), progress=False,
                simulated_durations=PROBE4,
                simulated_round_durations=WALLS4)
            assert len(runs[pr]["elastic"]["events"]) == 2
            assert runs[pr]["sanitize"]["retrace_count"] == 0
        for k in TAIL_KEYS:
            assert runs["resident"][k] == runs["replicated"][k], k
        # and the resident run's own fresh twin from the snapshot
        snap = runs["resident"]["elastic"]["snapshots"][0]
        assert snap.host_state.params_resident is not None
        assert snap.params_template is not None
        fresh = train_global(
            _e2e_cfg(param_residency="resident", **kw), progress=False,
            simulated_durations=PROBE4, simulated_round_durations=WALLS4,
            elastic_snapshot=snap)
        for k in TAIL_KEYS:
            assert runs["resident"][k][2:] == fresh[k], k

    def test_checkpoint_save_restore_through_the_driver(self, tmp_path):
        runs = {}
        for pr in ("replicated", "resident"):
            d = str(tmp_path / pr)
            first = train_global(
                _e2e_cfg(param_residency=pr, epochs_global=2,
                         checkpoint_dir=d, checkpoint_every=1),
                progress=False, simulated_durations=PROBE4,
                simulated_round_durations=WALLS4)
            resumed = train_global(
                _e2e_cfg(param_residency=pr, epochs_global=4,
                         checkpoint_dir=d, checkpoint_every=1,
                         resume=True),
                progress=False, simulated_durations=PROBE4,
                simulated_round_durations=WALLS4)
            assert len(resumed["global_train_losses"]) == 2
            runs[pr] = (first, resumed)
            assert ckpt_lib.manifest_metadata(d)["param_residency"] == pr
        for k in TAIL_KEYS:
            assert runs["resident"][0][k] == runs["replicated"][0][k], k
            assert runs["resident"][1][k] == runs["replicated"][1][k], k
