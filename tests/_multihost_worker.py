"""Worker process for tests/test_multihost.py — NOT a pytest module.

Joins a 2-process JAX coordination-service rendezvous on CPU (4 virtual
devices per process -> 8-worker global mesh) and runs the real driver
end-to-end, printing its view of the global metrics as JSON.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config  # noqa: E402
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global  # noqa: E402
from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import initialize_distributed  # noqa: E402


def main() -> None:
    initialize_distributed()
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4

    ckpt_dir = os.environ.get("MH_CKPT_DIR", "")
    cfg = Config(model="mlp", dataset="mnist", epochs_global=2,
                 epochs_local=1, batch_size=8, limit_train_samples=320,
                 limit_eval_samples=64, compute_dtype="float32",
                 augment=False, aggregation_by="weights", seed=0,
                 checkpoint_dir=ckpt_dir,
                 checkpoint_every=1 if ckpt_dir else 0)
    res = train_global(cfg, progress=False)
    print("MHRESULT " + json.dumps({
        "process": jax.process_index(),
        "losses": res["global_train_losses"],
        "val_losses": res["global_val_losses"],
    }), flush=True)


if __name__ == "__main__":
    main()
