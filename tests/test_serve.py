"""Serving engine (ISSUE 7): paged-decode equivalence + continuous
batching + direct-to-device checkpoint restore.

Correctness gates, tier-1 style:

- paged prefill logits are BITWISE equal to the full-sequence forward
  (identical op order over the same cached keys), incremental decode
  matches at fp32 tolerance with argmax equality — gpt, llama, GQA, MoE;
- batched continuous decoding emits the identical token stream a
  single-sequence decode would, per slot, greedy AND temperature
  (sampling keys derive from (seed, rid, position) only);
- the decode loop re-dispatches exactly the prefill-bucket + decode-step
  programs: a post-warmup run adds ZERO jaxpr traces / backend compiles
  across a >= 32-step decode;
- evicted sequences' pages recycle (literally the next ids handed out),
  admission under full occupancy blocks instead of failing, EOS and
  max-token stops finish with the right reason;
- ``from_checkpoint`` restores a training-mesh sharded checkpoint onto
  the serving mesh (worker-0 row, leaf-streamed) and manifest metadata
  self-configures the model.
"""

import dataclasses
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from learning_deep_neural_network_in_distributed_computing_environment_tpu import (  # noqa: E402
    checkpoint as ckpt_lib,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import (  # noqa: E402
    Config,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import (  # noqa: E402
    decode as D,
    get_model,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.serve import (  # noqa: E402
    ContinuousBatchingScheduler,
    PageAllocator,
    Request,
    ServeEngine,
    page_prefix_keys,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.utils.batching import (  # noqa: E402
    pad_to_batches,
    pad_to_bucket,
    pick_bucket,
)

VOCAB = 97
PROMPT = [5, 9, 3, 7, 2, 11, 4, 1]

FAMILIES = {
    "gpt": ("gpt_tiny", {}),
    "llama": ("llama_tiny", {}),
    "llama_gqa": ("llama_tiny", {"num_kv_heads": 2}),
    "gpt_moe": ("gpt_tiny", {"num_experts": 2, "capacity_factor": 2.0}),
}


@pytest.fixture(scope="module")
def served(request):
    """(model, variables) per family, built once per module."""
    cache = {}

    def build(fam):
        if fam not in cache:
            name, kw = FAMILIES[fam]
            m = get_model(name, num_classes=VOCAB, scan_layers=True, **kw)
            v = m.init(jax.random.key(0),
                       np.asarray(PROMPT, np.int32)[None])
            cache[fam] = (m, v)
        return cache[fam]

    return build


def _engine(model, variables, **kw):
    base = dict(max_batch=3, page_size=4, max_pages=32,
                prompt_buckets=(8, 16), max_seq=24, seed=0)
    base.update(kw)
    return ServeEngine(model, variables["params"], **base)


# ----------------------------------------------------------------------
# Paged-vs-dense logit equivalence
# ----------------------------------------------------------------------

class TestPagedEquivalence:
    @pytest.mark.parametrize("fam", list(FAMILIES))
    def test_prefill_bitwise_and_decode_tolerance(self, served, fam):
        model, v = served(fam)
        toks = np.asarray(PROMPT, np.int32)[None]
        full = np.asarray(model.apply(v, toks, train=False))
        spec = D.spec_from_model(model)
        table = jnp.asarray(np.array([[1, 2, 3, 4]], np.int32))
        # whole-prompt prefill: same op order over the same keys => bitwise
        kc, vc = D.init_paged_cache(spec, 8, 4)
        lg, kc, vc = D.forward_paged(
            spec, v["params"], jnp.asarray(toks), jnp.zeros(1, jnp.int32),
            jnp.array([8], jnp.int32), table, kc, vc)
        np.testing.assert_array_equal(np.asarray(lg), full)
        # prefill 4 + decode 4 single tokens: fp32 tolerance + argmax
        kc, vc = D.init_paged_cache(spec, 8, 4)
        lg4, kc, vc = D.forward_paged(
            spec, v["params"], jnp.asarray(toks[:, :4]),
            jnp.zeros(1, jnp.int32), jnp.array([4], jnp.int32), table,
            kc, vc)
        outs = [np.asarray(lg4)]
        for i in range(4, 8):
            lgi, kc, vc = D.forward_paged(
                spec, v["params"], jnp.asarray(toks[:, i:i + 1]),
                jnp.array([i], jnp.int32), jnp.array([1], jnp.int32),
                table, kc, vc)
            outs.append(np.asarray(lgi))
        inc = np.concatenate(outs, axis=1)
        np.testing.assert_allclose(inc, full, rtol=0, atol=5e-6)
        np.testing.assert_array_equal(inc.argmax(-1), full.argmax(-1))

    def test_spec_rejects_non_autoregressive_and_unscanned(self):
        bert = get_model("bert_tiny", num_classes=VOCAB, scan_layers=True)
        with pytest.raises(ValueError, match="no decode path"):
            D.spec_from_model(bert)
        unrolled = get_model("gpt_tiny", num_classes=VOCAB)
        with pytest.raises(ValueError, match="scan_layers"):
            D.spec_from_model(unrolled)


# ----------------------------------------------------------------------
# Continuous batching == single-sequence decode, per slot
# ----------------------------------------------------------------------

class TestBatchedVsSingle:
    @pytest.mark.parametrize("fam", ["gpt", "llama"])
    def test_token_streams_identical(self, served, fam):
        model, v = served(fam)
        rng = np.random.default_rng(7)
        # mixed greedy + temperature, ragged lengths, more requests than
        # slots so admissions interleave with running decodes
        reqs = [Request(rid=i,
                        prompt=rng.integers(1, VOCAB, 4 + i).tolist(),
                        max_new_tokens=5,
                        temperature=0.0 if i % 2 == 0 else 0.8)
                for i in range(5)]
        batched = ContinuousBatchingScheduler(
            _engine(model, v), eos_id=-1).run(reqs)
        assert batched["admitted"] == batched["evicted"] == 5
        by_rid = {c.rid: c.tokens for c in batched["completions"]}
        # ONE reused engine for all single runs: each run decodes over
        # recycled pages still holding the previous run's stale KV — the
        # cache-offset mask must make that invisible
        single_eng = _engine(model, v)
        for r in reqs:
            single = ContinuousBatchingScheduler(
                single_eng, eos_id=-1, max_active=1).run(
                    [Request(rid=r.rid, prompt=r.prompt, max_new_tokens=5,
                             temperature=r.temperature)])
            assert single["completions"][0].tokens == by_rid[r.rid], (
                f"rid {r.rid} (temp {r.temperature}) diverged between "
                "batched and single-sequence decode")


# ----------------------------------------------------------------------
# Page pool: recycle, occupancy accounting, admission backpressure
# ----------------------------------------------------------------------

class TestPages:
    def test_allocator_recycles_freed_pages_first(self):
        a = PageAllocator(8)        # pages 1..7
        first = a.alloc(3)
        assert first == [1, 2, 3] and a.in_use == 3
        a.free(first)
        assert a.alloc(3) == [1, 2, 3]   # literally the recycled ids
        assert a.alloc(99) is None       # over-ask leaves state intact
        assert a.in_use == 3 and a.peak_in_use == 3

    def test_allocator_guards(self):
        with pytest.raises(ValueError, match="trash page"):
            PageAllocator(1)
        a = PageAllocator(4)
        got = a.alloc(2)
        a.free(got)
        with pytest.raises(ValueError, match="double free"):
            a.free(got)
        with pytest.raises(ValueError, match="invalid page"):
            a.free([0])

    def test_scheduler_recycles_and_never_leaks(self, served):
        model, v = served("gpt")
        eng = _engine(model, v, max_batch=2, max_pages=8)
        # 2 pages/request (4 prompt + 4 new @ page_size 4); 7 free pages
        # hold 3 concurrent => the 4th request rides recycled pages
        reqs = [Request(rid=i, prompt=PROMPT[:4], max_new_tokens=4)
                for i in range(4)]
        out = ContinuousBatchingScheduler(eng, eos_id=-1).run(reqs)
        assert out["evicted"] == 4
        assert out["pages"]["leaked"] == 0
        assert out["pages"]["peak_in_use"] <= 4   # 2 slots x 2 pages
        assert out["pages"]["page_bytes"] == eng.page_bytes()
        assert eng.allocator.free_pages == 7      # all returned

    def test_admission_blocks_under_full_occupancy(self, served):
        model, v = served("gpt")
        # pool of 3 usable pages; each request needs 2 => strictly one
        # in flight, the rest wait (blocked counted, nothing fails)
        eng = _engine(model, v, max_batch=2, max_pages=4, max_seq=8,
                      prompt_buckets=(4,))
        reqs = [Request(rid=i, prompt=PROMPT[:4], max_new_tokens=4)
                for i in range(3)]
        sched = ContinuousBatchingScheduler(eng, eos_id=-1)
        out = sched.run(reqs)
        assert out["admission_blocked"] > 0
        assert out["evicted"] == 3 and out["pages"]["leaked"] == 0

    def test_oversized_request_fails_at_submit(self, served):
        model, v = served("gpt")
        eng = _engine(model, v)
        sched = ContinuousBatchingScheduler(eng)
        with pytest.raises(ValueError, match="exceeds the largest"):
            sched.run([Request(rid=0, prompt=[1] * 17, max_new_tokens=2)])
        with pytest.raises(ValueError, match="max_seq"):
            sched.run([Request(rid=0, prompt=PROMPT, max_new_tokens=100)])
        # out-of-vocab ids would silently clamp/wrap inside the gather —
        # must fail at submit instead of decoding confidently wrong
        with pytest.raises(ValueError, match="prompt ids"):
            sched.run([Request(rid=0, prompt=[1, VOCAB], max_new_tokens=2)])
        with pytest.raises(ValueError, match="prompt ids"):
            sched.run([Request(rid=0, prompt=[-3, 1], max_new_tokens=2)])


# ----------------------------------------------------------------------
# Stop conditions
# ----------------------------------------------------------------------

class TestStops:
    def test_max_token_budget_stop(self, served):
        model, v = served("gpt")
        out = ContinuousBatchingScheduler(
            _engine(model, v), eos_id=-1).run(
                [Request(rid=0, prompt=PROMPT, max_new_tokens=3)])
        c = out["completions"][0]
        assert c.reason == "length" and len(c.tokens) == 3

    def test_eos_stop(self, served):
        model, v = served("gpt")
        # learn the greedy continuation, then declare its second token
        # the EOS id — the rerun must stop there with reason "eos"
        probe = ContinuousBatchingScheduler(
            _engine(model, v), eos_id=-1).run(
                [Request(rid=0, prompt=PROMPT, max_new_tokens=4)])
        stream = probe["completions"][0].tokens
        eos = stream[1]
        out = ContinuousBatchingScheduler(
            _engine(model, v), eos_id=eos).run(
                [Request(rid=0, prompt=PROMPT, max_new_tokens=4)])
        c = out["completions"][0]
        stop = stream.index(eos)
        assert c.reason == "eos" and c.tokens == stream[:stop + 1]

    def test_request_timeout_evicts_stuck_sequence(self, served):
        # ISSUE 8 satellite: a sequence decoding past its wall-clock
        # budget is evicted (reason "timeout", counted in timed_out)
        # instead of pinning its slot + pages forever — and the freed
        # capacity admits the queue behind it (max_batch=1 forces the
        # second request to ride the eviction)
        model, v = served("gpt")
        eng = _engine(model, v, max_batch=1)
        out = ContinuousBatchingScheduler(
            eng, eos_id=-1, request_timeout=1e-6).run(
                [Request(rid=0, prompt=PROMPT[:4], max_new_tokens=8),
                 Request(rid=1, prompt=PROMPT[:4], max_new_tokens=8)])
        assert out["timed_out"] == 2 and out["evicted"] == 2
        for rid in (0, 1):
            c = out["completions"][rid]
            assert c.reason == "timeout"
            assert len(c.tokens) < 8     # cut off before its budget
        assert out["pages"]["leaked"] == 0
        assert eng.allocator.in_use == 0  # everything freed on eviction

    def test_request_timeout_off_by_default(self, served):
        model, v = served("gpt")
        out = ContinuousBatchingScheduler(
            _engine(model, v), eos_id=-1).run(
                [Request(rid=0, prompt=PROMPT[:4], max_new_tokens=3)])
        assert out["timed_out"] == 0
        assert out["completions"][0].reason == "length"


# ----------------------------------------------------------------------
# Two compiled programs: zero retraces after warmup
# ----------------------------------------------------------------------

class TestCompilePrograms:
    def test_zero_retraces_across_long_decode(self, served):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.xla_flags import (
            compile_event_counts,
            install_compile_counter,
        )
        model, v = served("gpt")
        eng = _engine(model, v, max_seq=48)
        assert install_compile_counter()
        # warmup: compile the one bucket this workload uses + the decode
        # step (2-token generation exercises both programs)
        ContinuousBatchingScheduler(eng, eos_id=-1).run(
            [Request(rid=100, prompt=PROMPT, max_new_tokens=2)])
        before = compile_event_counts()
        # steady state: >= 32 decode steps, fresh rids/lengths/pages —
        # the loop must re-dispatch the SAME two programs only
        out = ContinuousBatchingScheduler(eng, eos_id=-1).run(
            [Request(rid=i, prompt=PROMPT[:4 + i], max_new_tokens=36)
             for i in range(2)])
        after = compile_event_counts()
        assert out["decode_steps"] >= 32
        assert after["traces"] == before["traces"], "steady-state retrace"
        assert after["compiles"] == before["compiles"], "steady-state compile"


# ----------------------------------------------------------------------
# Checkpoint restore onto the serving mesh + manifest metadata
# ----------------------------------------------------------------------

def _worker_stacked_state(params, n):
    """A TrainState-shaped tree with every leaf worker-stacked, as the
    training checkpoints store it (worker row 0 = the served params)."""
    from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import (
        TrainState,
    )
    stack = jax.tree.map(
        lambda x: np.broadcast_to(np.asarray(x), (n, *np.shape(x))).copy(),
        params)
    # rows beyond worker 0 perturbed: restore must take row 0, not a mean
    stack = jax.tree.map(
        lambda x: np.concatenate([x[:1], x[1:] + 1.0], axis=0), stack)
    return TrainState(params=stack, batch_stats={}, opt_state={},
                      lr_epoch=np.zeros(n, np.int32),
                      rng=np.zeros((n, 2), np.uint32))


class TestCheckpointRestore:
    def test_row0_restore_across_meshes(self, served, tmp_path):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import (
            build_mesh,
        )
        model, v = served("gpt")
        n = 2
        train_mesh = build_mesh({"data": n}, devices=jax.devices()[:n])
        sharding = NamedSharding(train_mesh, P("data"))
        state = jax.tree.map(
            lambda x: jax.device_put(x, sharding),
            _worker_stacked_state(v["params"], n))
        meta = {"model": "gpt_tiny", "num_classes": VOCAB,
                "scan_layers": True, "compute_dtype": "float32",
                "num_kv_heads": 0, "num_experts": 0}
        ckpt_lib.save_checkpoint(str(tmp_path), state, 1, metadata=meta)
        # serving mesh is a DIFFERENT, single-device mesh
        serve_mesh = build_mesh({"data": 1}, devices=jax.devices()[:1])
        eng = ServeEngine.from_checkpoint(
            str(tmp_path), mesh=serve_mesh, max_batch=2, page_size=4,
            max_pages=16, prompt_buckets=(8,), max_seq=12)
        for a, b in zip(jax.tree.leaves(eng.params),
                        jax.tree.leaves(v["params"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        out = ContinuousBatchingScheduler(eng, eos_id=-1).run(
            [Request(rid=0, prompt=PROMPT, max_new_tokens=3)])
        # greedy decode off the restored params == full-forward argmax
        ids = list(PROMPT)
        for _ in range(3):
            lg = model.apply(v, np.asarray(ids, np.int32)[None],
                             train=False)
            ids.append(int(np.asarray(lg)[0, -1].argmax()))
        assert out["completions"][0].tokens == ids[len(PROMPT):]

    def test_resident_checkpoint_serves_from_bucket_rows(self, served,
                                                         tmp_path):
        """ISSUE 12 satellite: a scatter-resident checkpoint (params
        stored as 1/N bucket shard rows, no ``.params`` leaves) serves —
        the consensus unpacks template-free from the manifest metadata's
        ``params_leaves`` (PR 11 left a hard refusal here), bitwise the
        source params; a resident checkpoint WITHOUT the template keeps
        a clear refusal."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu import (
            comms,
        )
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import (
            TrainState,
        )
        model, v = served("gpt")
        n = 2
        resident = comms.resident_from_tree(
            jax.tree.map(np.asarray, v["params"]), n)
        state = TrainState(params=None, params_resident=resident,
                           batch_stats={}, opt_state={},
                           lr_epoch=np.zeros(n, np.int32),
                           rng=np.zeros((n, 2), np.uint32))
        flat = jax.tree_util.tree_flatten_with_path(v["params"])[0]
        meta = {"model": "gpt_tiny", "num_classes": VOCAB,
                "scan_layers": True, "compute_dtype": "float32",
                "num_kv_heads": 0, "num_experts": 0,
                "param_residency": "resident", "sync_bucket_mb": 4.0,
                "params_leaves": [
                    [[str(getattr(k, "key", k)) for k in path],
                     [int(d) for d in np.shape(leaf)],
                     str(np.asarray(leaf).dtype)]
                    for path, leaf in flat]}
        ckpt_lib.save_checkpoint(str(tmp_path), state, 1, metadata=meta)
        eng = ServeEngine.from_checkpoint(
            str(tmp_path), max_batch=2, page_size=4, max_pages=16,
            prompt_buckets=(8,), max_seq=12)
        for a, b in zip(jax.tree.leaves(eng.params),
                        jax.tree.leaves(v["params"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        out = ContinuousBatchingScheduler(eng, eos_id=-1).run(
            [Request(rid=0, prompt=PROMPT, max_new_tokens=3)])
        ids = list(PROMPT)
        for _ in range(3):
            lg = model.apply(v, np.asarray(ids, np.int32)[None],
                             train=False)
            ids.append(int(np.asarray(lg)[0, -1].argmax()))
        assert out["completions"][0].tokens == ids[len(PROMPT):]
        # a pre-ISSUE-12 resident checkpoint (no params_leaves) still
        # refuses with instructions instead of crashing
        legacy = dict(meta)
        legacy.pop("params_leaves")
        old = tmp_path / "legacy"
        ckpt_lib.save_checkpoint(str(old), state, 1, metadata=legacy)
        with pytest.raises(ValueError, match="params_leaves"):
            ServeEngine.from_checkpoint(str(old))

    def test_manifest_metadata_roundtrip_and_absence(self, served,
                                                     tmp_path):
        model, v = served("gpt")
        state = _worker_stacked_state(v["params"], 1)
        meta = {"model": "gpt_tiny", "num_classes": VOCAB,
                "scan_layers": True}
        ckpt_lib.save_checkpoint(str(tmp_path), state, 3, metadata=meta)
        # epoch dir and checkpoint root both resolve
        assert ckpt_lib.manifest_metadata(
            str(tmp_path / "ckpt_3")) == meta
        assert ckpt_lib.manifest_metadata(str(tmp_path)) == meta
        # a metadata-less save reads back {} (pre-metadata engines)
        bare = tmp_path / "bare"
        ckpt_lib.save_checkpoint(str(bare), state, 1)
        assert ckpt_lib.manifest_metadata(str(bare)) == {}
        assert ckpt_lib.manifest_metadata(str(tmp_path / "nope")) == {}
        with pytest.raises(ValueError, match="no serve metadata"):
            ServeEngine.from_checkpoint(str(bare))
        # metadata-less + an EXPLICIT --model: the CLI fallback rebuilds
        # the arch with num_classes recovered from the manifest leaves
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.serve.api import (
            run_serve,
        )
        cfg = Config(model="gpt_tiny", checkpoint_dir=str(bare),
                     serve_prompt="5,9,3", serve_requests=1,
                     serve_max_new_tokens=2, serve_max_batch=2,
                     serve_page_size=8, serve_max_pages=16,
                     serve_prompt_buckets="8")
        with pytest.raises(ValueError, match="no serve metadata"):
            run_serve(cfg, model_flag_given=False)
        out = run_serve(cfg, model_flag_given=True)
        assert out["engine"].spec.vocab == VOCAB
        assert len(out["completions"][0].tokens) == 2

    def test_model_from_metadata_guards(self):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.serve.engine import (
            model_from_metadata,
        )
        with pytest.raises(ValueError, match="autoregressive"):
            model_from_metadata({"model": "bert_tiny",
                                 "scan_layers": True, "num_classes": 10})
        with pytest.raises(ValueError, match="layer_scan"):
            model_from_metadata({"model": "gpt_tiny",
                                 "scan_layers": False, "num_classes": 10})
        m = model_from_metadata({"model": "llama_tiny",
                                 "scan_layers": True, "num_classes": VOCAB,
                                 "num_kv_heads": 2})
        assert type(m).__name__ == "LlamaForCausalLM"
        assert m.num_kv_heads == 2 and m.scan_layers


# ----------------------------------------------------------------------
# Batching helpers (the eval/serve shared padding satellite)
# ----------------------------------------------------------------------

class TestBatchingHelpers:
    def test_pad_to_batches_masks_tail(self):
        x = np.arange(10, dtype=np.float32)[:, None]
        y = np.arange(10, dtype=np.int32)
        xs, ys, m = pad_to_batches(x, y, 4)
        assert xs.shape == (3, 4, 1) and m.shape == (3, 4)
        assert m.sum() == 10 and m[2].tolist() == [1.0, 1.0, 0.0, 0.0]
        # padding repeats the final real example (in-domain values)
        assert ys[2].tolist() == [8, 9, 9, 9]
        with pytest.raises(ValueError):
            pad_to_batches(x[:0], y[:0], 4)

    def test_pick_and_pad_bucket(self):
        assert pick_bucket(5, (8, 16)) == 8
        assert pick_bucket(8, (8, 16)) == 8
        assert pick_bucket(9, (8, 16)) == 16
        with pytest.raises(ValueError, match="largest bucket"):
            pick_bucket(17, (8, 16))
        padded = pad_to_bucket(np.array([3, 1, 4]), 8)
        assert padded.tolist() == [3, 1, 4, 0, 0, 0, 0, 0]
        with pytest.raises(ValueError):
            pad_to_bucket(np.array([1] * 9), 8)


# ----------------------------------------------------------------------
# End-to-end: train -> checkpoint -> serve (the full driver path)
# ----------------------------------------------------------------------

@pytest.mark.slow
class TestServeEndToEnd:
    def test_train_checkpoint_serve_greedy_matches_argmax(self, tmp_path):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import (
            train_global,
        )
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.serve.api import (
            run_serve,
        )
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import (
            rank0_variables,
        )
        cfg = Config(model="gpt_tiny", dataset="synthetic_lm",
                     epochs_global=1, epochs_local=1, batch_size=8,
                     limit_train_samples=64, limit_eval_samples=16,
                     compute_dtype="float32", augment=False,
                     aggregation_by="weights", checkpoint_dir=str(tmp_path),
                     checkpoint_every=1, seed=3)
        res = train_global(cfg, progress=False)
        out = run_serve(cfg.replace(
            serve_prompt="5,9,3,7,2", serve_requests=2,
            serve_max_new_tokens=4, serve_max_batch=2, serve_page_size=8,
            serve_max_pages=16, serve_prompt_buckets="8"))
        v = rank0_variables(res["state"])
        ids = [5, 9, 3, 7, 2]
        for _ in range(4):
            lg = res["model"].apply(v, np.asarray(ids, np.int32)[None],
                                    train=False)
            ids.append(int(np.asarray(lg)[0, -1].argmax()))
        for c in out["completions"]:
            assert c.tokens == ids[5:]
        tele = out["serve"]
        assert tele["tokens_generated"] == 8
        assert tele["pages"]["leaked"] == 0


# ----------------------------------------------------------------------
# PR 17: paged prefix cache — content keys + refcounted allocator
# ----------------------------------------------------------------------

class TestPrefixKeys:
    def test_rolling_hash_keys_whole_prefix(self):
        keys = page_prefix_keys(PROMPT, 4)
        assert len(keys) == 2            # two FULL pages of 4
        assert page_prefix_keys(PROMPT[:7], 4) == keys[:1]  # partial page
        # unshared pages: same page-1 tokens but a different page 0 must
        # change BOTH keys — the hash rolls over the whole prefix, not
        # the page in isolation (position safety of the shared pages)
        other = [1, 1, 1, 1] + PROMPT[4:]
        assert page_prefix_keys(other, 4)[0] != keys[0]
        assert page_prefix_keys(other, 4)[1] != keys[1]
        # shared prefix, divergent tail: first key equal, second differs
        fork = PROMPT[:4] + [2, 2, 2, 2]
        assert page_prefix_keys(fork, 4)[0] == keys[0]
        assert page_prefix_keys(fork, 4)[1] != keys[1]

    def test_refcount_lifecycle(self):
        a = PageAllocator(8)
        p0, p1 = a.alloc(2)
        a.register(b"k0", p0)
        a.claim(p0)                      # a second sequence shares p0
        assert a.refcount(p0) == 2 and a.in_use == 2
        a.free([p0, p1])                 # first owner exits
        assert a.refcount(p0) == 1       # still referenced — not cached
        assert a.cached_pages == 0 and a.in_use == 1
        a.free([p0])                     # last reference drops
        assert a.in_use == 0 and a.cached_pages == 1
        assert a.lookup([b"k0"]) == [p0]         # retained, KV intact
        with pytest.raises(ValueError, match="double free"):
            a.free([p0])                 # cached != free: still guarded
        a.claim(p0)                      # resurrect off the LRU
        assert a.cached_pages == 0 and a.refcount(p0) == 1
        with pytest.raises(ValueError, match="no live reference"):
            a.register(b"kX", p1)        # p1 went back to the free list
        with pytest.raises(ValueError, match="neither"):
            a.claim(7)                   # never allocated
        # identity the telemetry gates on, at every state above
        assert a.in_use + a.cached_pages + a.free_pages == 7

    def test_lru_eviction_oldest_first_and_first_writer_wins(self):
        a = PageAllocator(5)             # pages 1..4
        pages = a.alloc(3)               # [1, 2, 3]
        for i, p in enumerate(pages):
            a.register(bytes([i]), p)
        a.free(pages)                    # all three park on the LRU
        assert a.cached_pages == 3 and a.free_pages == 1
        # a 3-page ask: free list first (page 4), then evict the two
        # OLDEST cached pages — their keys die, the newest survives
        got = a.alloc(3)
        assert got == [4, 1, 2] and a.cache_evictions == 2
        assert a.lookup([bytes(), bytes([0])]) == []
        assert a.lookup([bytes([2])]) == [3]
        # first writer wins: key 2 is taken, and got[0] can carry only
        # one key ever
        assert a.register(bytes([2]), got[0]) is False
        assert a.register(bytes([9]), got[0]) is True
        assert a.register(bytes([10]), got[0]) is False

    def test_lookup_stops_at_first_miss(self):
        a = PageAllocator(8)
        pages = a.alloc(3)
        a.register(b"a", pages[0])
        a.register(b"c", pages[2])
        # consecutive-run semantics: a hole at key 1 hides page 2 even
        # though its key is indexed (its CONTENT depends on pages 0-1)
        assert a.lookup([b"a", b"b", b"c"]) == [pages[0]]


class _AuditAllocator(PageAllocator):
    """PageAllocator that re-checks the sharing invariants on every
    operation: a page is never handed out while referenced, refcounts
    mirror the claim/free history exactly, and the occupancy identity
    ``in_use + cached + free == usable`` never breaks."""

    def __init__(self, max_pages):
        super().__init__(max_pages)
        self.shadow: dict = {}
        self.ops = 0

    def _check(self):
        self.ops += 1
        live = {p for p, r in self.shadow.items() if r > 0}
        assert len(live) == self.in_use, "in_use drifted from refcounts"
        for p, r in self.shadow.items():
            assert self.refcount(p) == r, f"page {p} refcount drifted"
        assert (self.in_use + self.cached_pages + self.free_pages
                == self.max_pages - 1), "occupancy identity broke"

    def alloc(self, count):
        got = super().alloc(count)
        if got is not None:
            for p in got:
                assert self.shadow.get(p, 0) == 0, (
                    f"page {p} recycled while referenced")
                self.shadow[p] = 1
        self._check()
        return got

    def free(self, pages):
        super().free(pages)              # double-free raises in the base
        for p in pages:
            self.shadow[p] -= 1
        self._check()

    def claim(self, page):
        super().claim(page)
        self.shadow[page] = self.shadow.get(page, 0) + 1
        self._check()


class TestPrefixCache:
    @pytest.mark.parametrize("fam", ["gpt", "llama", "llama_gqa"])
    def test_hit_decode_trajectory_bitwise_vs_cold_twin(self, served, fam):
        model, v = served(fam)
        reqs = [Request(rid=i, prompt=PROMPT, max_new_tokens=6,
                        temperature=0.0 if i == 0 else 0.8)
                for i in range(2)]
        # the cold twin: same engine config, cache OFF
        cold = ContinuousBatchingScheduler(_engine(model, v)).run(
            [Request(**dataclasses.asdict(r)) for r in reqs])
        eng = _engine(model, v, prefix_cache=True)
        sched = ContinuousBatchingScheduler(eng)
        warm = sched.run(reqs)
        for cc, cw in zip(cold["completions"], warm["completions"]):
            assert cw.tokens == cc.tokens, (
                f"rid {cw.rid}: prefix-hit trajectory diverged from the "
                "cold twin")
        # rid 0 was cold (2 prompt pages, 0 hits), rid 1 hit the one
        # shareable page ((plen-1)//page_size caps the reuse at 1)
        assert warm["page_reuse_ratio"] == pytest.approx(1 / 4)
        assert warm["prefill_tokens_saved"] == 4
        assert warm["pages"]["leaked"] == 0
        assert warm["pages"]["cached_pages"] > 0
        assert cold["page_reuse_ratio"] == 0.0

    def test_shared_system_prompt_reuse_ratio(self, served):
        model, v = served("gpt")
        rng = np.random.default_rng(11)
        sys_prefix = rng.integers(1, VOCAB, 8).tolist()
        reqs = [Request(rid=i,
                        prompt=sys_prefix + rng.integers(
                            1, VOCAB, 4).tolist(),
                        max_new_tokens=4)
                for i in range(4)]
        eng = _engine(model, v, prefix_cache=True)
        out = ContinuousBatchingScheduler(eng).run(
            [Request(**dataclasses.asdict(r)) for r in reqs])
        # 12-token prompts: 3 prompt pages each, the 2 sys-prefix pages
        # shareable; request 0 pays them cold, 1..3 hit both
        assert out["page_reuse_ratio"] == pytest.approx(6 / 12)
        assert out["prefill_tokens_saved"] == 3 * 8
        assert out["pages"]["leaked"] == 0
        # every stream still equals its solo cold run (one plain engine,
        # reused: streams are batch- and cache-independent by design)
        plain = _engine(model, v)
        for r in reqs:
            solo = ContinuousBatchingScheduler(plain, max_active=1).run(
                [Request(rid=r.rid, prompt=r.prompt, max_new_tokens=4)])
            got = next(c for c in out["completions"] if c.rid == r.rid)
            assert got.tokens == solo["completions"][0].tokens

    def test_page_never_recycled_while_referenced_property(self, served):
        """Property-style sweep of the refcount invariants: shared
        prefixes + a pool tight enough to force LRU evictions and
        admission backpressure, plus timeout and EOS evictions — every
        allocator operation re-audited (never recycled while referenced,
        never double-freed, occupancy identity byte-exact)."""
        model, v = served("gpt")
        rng = np.random.default_rng(23)
        sys_prefix = rng.integers(1, VOCAB, 8).tolist()

        def mk(rid, tail, new=4):
            return Request(rid=rid,
                           prompt=sys_prefix + rng.integers(
                               1, VOCAB, tail).tolist(),
                           max_new_tokens=new)

        eng = _engine(model, v, prefix_cache=True, prefill_chunk=4,
                      max_pages=14)
        eng.allocator = _AuditAllocator(14)
        sched = ContinuousBatchingScheduler(eng)
        out = sched.run([mk(i, 1 + (i % 5)) for i in range(8)])
        assert out["page_reuse_ratio"] > 0
        assert out["pages"]["peak_bytes"] == (
            out["pages"]["peak_in_use"] * eng.page_bytes())
        # timeout evictions (possibly mid-prefill) release cleanly too
        out2 = ContinuousBatchingScheduler(
            eng, request_timeout=1e-6).run(
                [mk(100 + i, 3, new=8) for i in range(4)])
        assert out2["timed_out"] == 4
        # EOS on the very first token exercises the admission-time finish
        eos = out["completions"][0].tokens[0]
        ContinuousBatchingScheduler(eng, eos_id=eos).run(
            [mk(200 + i, 1 + (i % 5)) for i in range(4)])
        assert eng.allocator.in_use == 0, "references leaked"
        assert eng.allocator.ops > 50
        assert out["pages"]["leaked"] == 0 and out2["pages"]["leaked"] == 0

    def test_zero_retraces_with_prefix_hits(self, served):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.xla_flags import (
            compile_event_counts,
            install_compile_counter,
        )
        model, v = served("gpt")
        eng = _engine(model, v, prefix_cache=True, max_seq=48)
        assert install_compile_counter()
        rng = np.random.default_rng(5)
        long_prompt = rng.integers(1, VOCAB, 16).tolist()
        # warmup covers both buckets AND the hit path (rerunning PROMPT
        # prefills only its tail, at a smaller effective length)
        sched = ContinuousBatchingScheduler(eng)
        sched.run([Request(rid=100, prompt=PROMPT, max_new_tokens=2),
                   Request(rid=101, prompt=long_prompt, max_new_tokens=2)])
        ContinuousBatchingScheduler(eng).run(
            [Request(rid=102, prompt=PROMPT, max_new_tokens=2)])
        before = compile_event_counts()
        # steady state: full hits, partial hits, and cold prompts
        out = ContinuousBatchingScheduler(eng).run(
            [Request(rid=0, prompt=PROMPT, max_new_tokens=8),
             Request(rid=1, prompt=PROMPT[:4] + [13, 17, 19, 23, 29, 31,
                                                 37, 41],
                     max_new_tokens=8),
             Request(rid=2, prompt=rng.integers(1, VOCAB, 16).tolist(),
                     max_new_tokens=8)])
        after = compile_event_counts()
        assert out["page_reuse_ratio"] > 0
        assert after["traces"] == before["traces"], "hit-path retrace"
        assert after["compiles"] == before["compiles"], "hit-path compile"

    def test_engine_headroom_guard(self, served):
        model, v = served("gpt")
        # max_seq 24 @ page_size 4 = 6 pages/sequence; 7 pages in the
        # pool leave 6 usable — one sequence pins everything, nothing
        # could ever stay cached
        with pytest.raises(ValueError, match="headroom"):
            _engine(model, v, prefix_cache=True, max_pages=7)
        _engine(model, v, prefix_cache=True, max_pages=8)   # fits


# ----------------------------------------------------------------------
# PR 17: chunked prefill — one [1, C] program interleaved into decode
# ----------------------------------------------------------------------

class TestChunkedPrefill:
    @pytest.mark.parametrize("fam", ["gpt", "llama", "llama_gqa"])
    @pytest.mark.parametrize("chunk", [4, 8])
    def test_bitwise_logits_and_cache_vs_monolithic(self, served, fam,
                                                    chunk):
        model, v = served(fam)
        prompt = np.asarray(PROMPT + [6, 2, 8, 3], np.int32)   # 12 tokens
        kw = dict(prompt_buckets=(16,), max_seq=16)
        em = _engine(model, v, **kw)
        ec = _engine(model, v, prefill_chunk=chunk, **kw)
        row_m = em.table_row(em.allocator.alloc(em.pages_for(16)))
        row_c = ec.table_row(ec.allocator.alloc(ec.pages_for(16)))
        tok_m, lg_m = em.prefill(prompt, row_m, 0.0, 7)
        tok_c = lg_c = None
        for s in range(0, len(prompt), chunk):
            tok_c, lg_c = ec.prefill_chunk_step(
                prompt[s:s + chunk], s, row_c, 0.0, 7)
        assert tok_c == tok_m
        np.testing.assert_array_equal(np.asarray(lg_c), np.asarray(lg_m))
        # the sequence's written pages are bitwise the monolithic
        # prefill's — chunked decode continues from EXACTLY the same
        # state (page 0 is the trash page: bucket padding scribbles
        # there, chunk-aligned spans don't, and decode never reads it)
        np.testing.assert_array_equal(np.asarray(ec.kcache)[:, 1:5],
                                      np.asarray(em.kcache)[:, 1:5])
        np.testing.assert_array_equal(np.asarray(ec.vcache)[:, 1:5],
                                      np.asarray(em.vcache)[:, 1:5])
        assert ec.compiled_buckets == []   # no bucket ever specialized

    def test_streams_identical_and_chunk_counts(self, served):
        model, v = served("gpt")
        rng = np.random.default_rng(3)
        reqs = [Request(rid=i,
                        prompt=rng.integers(1, VOCAB, 5 + 3 * i).tolist(),
                        max_new_tokens=5,
                        temperature=0.0 if i % 2 == 0 else 0.7)
                for i in range(4)]                 # lengths 5, 8, 11, 14
        mono = ContinuousBatchingScheduler(_engine(model, v)).run(
            [Request(**dataclasses.asdict(r)) for r in reqs])
        chk = ContinuousBatchingScheduler(
            _engine(model, v, prefill_chunk=4)).run(reqs)
        assert ([c.tokens for c in chk["completions"]]
                == [c.tokens for c in mono["completions"]])
        # ceil(plen / 4) chunks per prompt: 2 + 2 + 3 + 4
        assert chk["prefill_chunks"] == 11
        assert mono["prefill_chunks"] == 0
        assert chk["prefill_buckets"] == []
        assert chk["pages"]["leaked"] == 0

    def test_chunks_interleave_with_running_decode(self, served):
        model, v = served("gpt")
        eng = _engine(model, v, prefill_chunk=4)
        calls = []
        orig_chunk, orig_decode = eng.prefill_chunk_step, eng.decode
        eng.prefill_chunk_step = (
            lambda *a, **k: (calls.append("chunk"),
                             orig_chunk(*a, **k))[1])
        eng.decode = (
            lambda *a, **k: (calls.append("decode"),
                             orig_decode(*a, **k))[1])
        out = ContinuousBatchingScheduler(eng).run(
            [Request(rid=0, prompt=PROMPT[:4], max_new_tokens=12),
             Request(rid=1, prompt=PROMPT * 2, max_new_tokens=2)])
        # the 16-token prompt prefills one chunk per scheduler tick WHILE
        # rid 0 keeps decoding: some decode call lands strictly between
        # two chunk calls instead of the monolithic stall
        first, last = calls.index("chunk"), len(calls) - 1 - calls[
            ::-1].index("chunk")
        assert "decode" in calls[first:last], (
            f"prefill was not interleaved with decode: {calls}")
        assert out["pages"]["leaked"] == 0
        # the short stream is unperturbed by the long prefill riding along
        solo = ContinuousBatchingScheduler(
            _engine(model, v, prefill_chunk=4)).run(
                [Request(rid=0, prompt=PROMPT[:4], max_new_tokens=12)])
        assert (next(c for c in out["completions"] if c.rid == 0).tokens
                == solo["completions"][0].tokens)

    def test_prompt_beyond_largest_bucket_admits(self, served):
        model, v = served("gpt")
        long_prompt = (PROMPT * 3)[:18]            # 18 > largest bucket 16
        with pytest.raises(ValueError, match="exceeds the largest"):
            ContinuousBatchingScheduler(_engine(model, v)).run(
                [Request(rid=0, prompt=long_prompt, max_new_tokens=2)])
        out = ContinuousBatchingScheduler(
            _engine(model, v, prefill_chunk=4)).run(
                [Request(rid=0, prompt=long_prompt, max_new_tokens=2)])
        c = out["completions"][0]
        assert c.reason == "length" and len(c.tokens) == 2
        assert out["prefill_chunks"] == 5          # ceil(18 / 4)

    def test_zero_retraces_chunked(self, served):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.xla_flags import (
            compile_event_counts,
            install_compile_counter,
        )
        model, v = served("gpt")
        eng = _engine(model, v, prefill_chunk=4, max_seq=48)
        assert install_compile_counter()
        # ONE warm request (2 chunks) compiles the chunk program + decode
        ContinuousBatchingScheduler(eng).run(
            [Request(rid=100, prompt=PROMPT, max_new_tokens=2)])
        before = compile_event_counts()
        rng = np.random.default_rng(9)
        out = ContinuousBatchingScheduler(eng).run(
            [Request(rid=i, prompt=rng.integers(
                1, VOCAB, 3 + 5 * i).tolist(), max_new_tokens=35)
             for i in range(3)])                   # lengths 3, 8, 13
        after = compile_event_counts()
        assert out["decode_steps"] >= 32
        assert after["traces"] == before["traces"], (
            "chunked steady-state retrace — the [1, C] program must "
            "cover every prompt length")
        assert after["compiles"] == before["compiles"]

    def test_engine_rejects_non_page_multiple_chunk(self, served):
        model, v = served("gpt")
        with pytest.raises(ValueError, match="multiple of page_size"):
            _engine(model, v, prefill_chunk=3)
        with pytest.raises(ValueError, match="multiple of page_size"):
            _engine(model, v, prefill_chunk=-4)


# ----------------------------------------------------------------------
# PR 17 satellites: latency split + eager config validation
# ----------------------------------------------------------------------

class TestLatencyTelemetry:
    def test_ttft_split_from_decode_gaps(self, served):
        model, v = served("gpt")
        out = ContinuousBatchingScheduler(_engine(model, v)).run(
            [Request(rid=0, prompt=PROMPT, max_new_tokens=5)])
        c = out["completions"][0]
        assert c.ttft_s is not None and c.ttft_s > 0
        # the first token's wall (prefill included) is NOT a decode gap
        assert len(c.decode_latencies_s) == len(c.tokens) - 1
        for key in ("p50", "p99", "mean"):
            assert out["ttft_ms"][key] > 0
            assert out["latency_ms"][key] > 0

    def test_zero_filled_schema_on_empty_run(self, served):
        model, v = served("gpt")
        out = ContinuousBatchingScheduler(_engine(model, v)).run([])
        zero = {"p50": 0.0, "p99": 0.0, "mean": 0.0}
        assert out["latency_ms"] == zero
        assert out["ttft_ms"] == zero
        assert out["page_reuse_ratio"] == 0.0
        assert out["prefill_tokens_saved"] == 0
        assert out["prefill_chunks"] == 0
        assert out["tokens_per_s"] == 0.0


class TestServeFastPathConfig:
    def test_chunk_must_be_positive_page_multiple(self):
        with pytest.raises(ValueError, match="positive multiple"):
            Config(serve_prefill_chunk=5)
        with pytest.raises(ValueError, match="positive multiple"):
            Config(serve_prefill_chunk=-16)
        with pytest.raises(ValueError, match="positive multiple"):
            Config(serve_prefill_chunk=24, serve_page_size=16)
        assert Config(serve_prefill_chunk=32).serve_prefill_chunk == 32
        assert Config(serve_prefill_chunk=24,
                      serve_page_size=8).serve_prefill_chunk == 24

    def test_prefix_cache_needs_pool_headroom(self):
        # default buckets 16,64 + 16 new tokens = 80-token sequences =
        # 5 pages @ page_size 16: a 6-page pool (5 usable) is pinned
        # whole by one sequence — rejected with the real reason
        with pytest.raises(ValueError, match="headroom"):
            Config(serve_prefix_cache=True, serve_max_pages=6)
        cfg = Config(serve_prefix_cache=True, serve_max_pages=7)
        assert cfg.serve_prefix_cache

    def test_fast_path_flags_rejected_outside_serve_mode(self):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import (
            train_global,
        )
        for kw in (dict(serve_prefix_cache=True),
                   dict(serve_prefill_chunk=16),
                   dict(serve_draft_ckpt="/tmp/x", serve_spec_tokens=4)):
            with pytest.raises(ValueError, match="serving fast path"):
                train_global(Config(**kw))

    def test_spec_flags_required_together(self):
        with pytest.raises(ValueError, match="TOGETHER"):
            Config(serve_draft_ckpt="/tmp/x")
        with pytest.raises(ValueError, match="TOGETHER"):
            Config(serve_spec_tokens=4)
        cfg = Config(serve_draft_ckpt="/tmp/x", serve_spec_tokens=4)
        assert cfg.serve_spec_tokens == 4

    def test_spec_rejects_temperature(self):
        # eager v1 rejection with the real reason: greedy argmax
        # acceptance only — the stochastic rejection-sampling rule is
        # not implemented
        with pytest.raises(ValueError, match="rejection-sampling"):
            Config(serve_draft_ckpt="/tmp/x", serve_spec_tokens=4,
                   serve_temperature=0.8)

    def test_spec_prefix_cache_headroom_counts_spec_tokens(self):
        # the verify program overshoots k positions past max_new, so the
        # headroom math must include them: 7 pages pass without spec
        # (80-token sequences = 5 pages) but 16 spec tokens push a
        # sequence to 96 tokens = 6 pages == the 6 usable — rejected
        Config(serve_prefix_cache=True, serve_max_pages=7)
        with pytest.raises(ValueError, match="serve_spec_tokens"):
            Config(serve_prefix_cache=True, serve_max_pages=7,
                   serve_draft_ckpt="/tmp/x", serve_spec_tokens=16)


# ----------------------------------------------------------------------
# ISSUE 18: speculative decoding — draft pool + fused verify
# ----------------------------------------------------------------------

def _spec_pair(model, tv, draft_model, dv, k, **kw):
    """(target engine paired with a draft, twin plain engine) sharing
    one geometry."""
    draft = _engine(draft_model, dv, **kw)
    eng = ServeEngine(model, tv["params"], draft=draft, spec_tokens=k,
                      **{**dict(max_batch=3, page_size=4, max_pages=32,
                                prompt_buckets=(8, 16), max_seq=24,
                                seed=0), **kw})
    return eng


class TestSpeculativeAccept:
    """Device accept math vs a plain-python reference."""

    def _ref(self, logits, draft):
        b, k = draft.shape
        tgt = logits.argmax(-1)
        out_e = np.full((b, k), -1, np.int32)
        out_a = np.zeros(b, np.int32)
        for i in range(b):
            n = 0
            while n < k and draft[i, n] == tgt[i, n]:
                n += 1
            acc = min(n, k - 1)
            out_a[i] = acc
            out_e[i, :acc] = draft[i, :acc]
            out_e[i, acc] = tgt[i, acc]
        return out_e, out_a

    @pytest.mark.parametrize("k", [1, 2, 4])
    def test_matches_reference(self, k):
        rng = np.random.default_rng(5)
        logits = rng.standard_normal((6, k + 1, 13)).astype(np.float32)
        draft = rng.integers(0, 13, (6, k)).astype(np.int32)
        # row 0: force full acceptance to exercise the k-1 cap; row 1:
        # force total rejection (first draft wrong)
        full = logits[0].argmax(-1)
        draft[0] = full[:k]
        draft[1, 0] = (logits[1, 0].argmax() + 1) % 13
        emitted, acc = D.speculative_accept(jnp.asarray(logits),
                                            jnp.asarray(draft))
        ref_e, ref_a = self._ref(logits, draft)
        np.testing.assert_array_equal(np.asarray(acc), ref_a)
        np.testing.assert_array_equal(np.asarray(emitted), ref_e)
        assert int(acc[0]) == k - 1          # cap engaged
        assert int(acc[1]) == 0              # burst collapses to bonus

    def test_cap_emits_identical_stream(self):
        # when every draft matches, the bonus token t_{k-1} IS d_k: the
        # capped burst d_1..d_{k-1}, t_{k-1} equals d_1..d_k — capping
        # costs nothing, ever
        rng = np.random.default_rng(7)
        logits = rng.standard_normal((2, 5, 11)).astype(np.float32)
        tgt = logits.argmax(-1)
        draft = tgt[:, :4].astype(np.int32)
        emitted, acc = D.speculative_accept(jnp.asarray(logits),
                                            jnp.asarray(draft))
        np.testing.assert_array_equal(np.asarray(emitted), draft)


class TestSpeculative:
    # tier-1 keeps the trickiest cell (GQA at the full k=4 burst); the
    # rest of the 3x2 matrix runs in the slow tier on the 1-core CI
    # host — gpt k=2 bitwise coverage also rides tier-1 through the
    # batched-vs-single and zero-retrace tests below
    @pytest.mark.parametrize("fam,k", [
        ("llama_gqa", 4),
        pytest.param("gpt", 2, marks=pytest.mark.slow),
        pytest.param("llama", 2, marks=pytest.mark.slow),
        pytest.param("llama_gqa", 2, marks=pytest.mark.slow),
        pytest.param("gpt", 4, marks=pytest.mark.slow),
        pytest.param("llama", 4, marks=pytest.mark.slow),
    ])
    def test_bitwise_vs_nonspeculative_twin(self, served, fam, k):
        """THE gate: greedy speculative output is bitwise the twin's —
        the draft (same family, independently initialized, so real
        disagreement) only ever changes WHEN tokens appear, never WHICH."""
        model, v = served(fam)
        name, mkw = FAMILIES[fam]
        draft_model = get_model(name, num_classes=VOCAB, scan_layers=True,
                                **mkw)
        dv = draft_model.init(jax.random.key(99),
                              np.asarray(PROMPT, np.int32)[None])
        reqs = lambda: [Request(rid=i, prompt=PROMPT[:4 + 2 * i],  # noqa: E731
                                max_new_tokens=6) for i in range(3)]
        twin = ContinuousBatchingScheduler(
            _engine(model, v), eos_id=-1).run(reqs())
        eng = _spec_pair(model, v, draft_model, dv, k)
        out = ContinuousBatchingScheduler(eng, eos_id=-1).run(reqs())
        assert ([c.tokens for c in out["completions"]]
                == [c.tokens for c in twin["completions"]]), (
            f"{fam} k={k}: speculative stream diverged from the twin")
        assert out["spec"]["verify_steps"] > 0
        assert out["spec"]["draft_steps"] == k * out["spec"]["verify_steps"]
        assert out["pages"]["leaked"] == 0
        assert out["pages"]["draft_leaked"] == 0

    def test_composes_with_prefix_cache_and_chunked(self, served):
        """All three fast-path features at once — warm prefix hits +
        chunked prefill + speculation — still bitwise, in both pools."""
        model, v = served("gpt")
        draft_model = get_model("gpt_tiny", num_classes=VOCAB,
                                scan_layers=True)
        dv = draft_model.init(jax.random.key(99),
                              np.asarray(PROMPT, np.int32)[None])
        kw = dict(max_pages=48, prefix_cache=True, prefill_chunk=4)
        reqs = lambda: [Request(rid=i, prompt=PROMPT, max_new_tokens=6)  # noqa: E731
                        for i in range(2)]
        twin = ContinuousBatchingScheduler(
            _engine(model, v), eos_id=-1).run(reqs())
        base = [c.tokens for c in twin["completions"]]
        eng = _spec_pair(model, v, draft_model, dv, 4, **kw)
        cold = ContinuousBatchingScheduler(eng, eos_id=-1).run(reqs())
        warm = ContinuousBatchingScheduler(eng, eos_id=-1).run(reqs())
        assert [c.tokens for c in cold["completions"]] == base
        assert [c.tokens for c in warm["completions"]] == base
        assert warm["page_reuse_ratio"] > 0    # the hits really happened
        assert warm["prefill_chunks"] > 0
        assert warm["pages"]["leaked"] == 0
        assert warm["pages"]["draft_leaked"] == 0

    def test_batched_vs_single_speculative(self, served):
        """PR 7 gate extended: a slot's ACCEPTED tokens are independent
        of its batch neighbors (greedy end-to-end, and the verify's
        per-row masking keeps inactive rows out of every gather)."""
        model, v = served("gpt")
        draft_model = get_model("gpt_tiny", num_classes=VOCAB,
                                scan_layers=True)
        dv = draft_model.init(jax.random.key(99),
                              np.asarray(PROMPT, np.int32)[None])
        reqs = [Request(rid=i, prompt=PROMPT[:3 + i], max_new_tokens=5)
                for i in range(3)]
        eng = _spec_pair(model, v, draft_model, dv, 2)
        batched = ContinuousBatchingScheduler(eng, eos_id=-1).run(reqs)
        by_rid = {c.rid: c.tokens for c in batched["completions"]}
        # the same engine pair serves the single-slot runs: engines are
        # stateless between scheduler runs, and reusing the compiled
        # programs keeps this in the tier-1 budget on a 1-core host
        for r in reqs:
            single = ContinuousBatchingScheduler(
                eng, eos_id=-1, max_active=1).run(
                    [Request(rid=r.rid, prompt=r.prompt,
                             max_new_tokens=5)])
            assert single["completions"][0].tokens == by_rid[r.rid], (
                f"rid {r.rid} diverged between batched and single "
                "speculative decode")

    def test_self_similar_deterministic_acceptance(self, served):
        """Draft sharing the target's params accepts every proposal:
        acceptance pins at (k-1)/k (the cap) and target steps per
        emitted token at ~1/k — the backend-robust bench bar."""
        model, v = served("gpt")
        k = 4
        draft = _engine(model, v, max_seq=32)
        eng = ServeEngine(model, v["params"], draft=draft, spec_tokens=k,
                          max_batch=3, page_size=4, max_pages=32,
                          prompt_buckets=(8, 16), max_seq=32, seed=0)
        # 17 = 1 prefill token + 16 speculative = exactly 4 full bursts
        out = ContinuousBatchingScheduler(eng, eos_id=-1).run(
            [Request(rid=i, prompt=PROMPT, max_new_tokens=17)
             for i in range(2)])
        assert out["spec"]["acceptance_rate"] == (k - 1) / k
        assert out["spec"]["target_steps_per_token"] == 1 / k
        twin = ContinuousBatchingScheduler(
            _engine(model, v, max_seq=32), eos_id=-1).run(
            [Request(rid=i, prompt=PROMPT, max_new_tokens=17)
             for i in range(2)])
        assert ([c.tokens for c in out["completions"]]
                == [c.tokens for c in twin["completions"]])

    def test_eos_truncates_burst_like_twin(self, served):
        """An eos landing mid-burst must cut the stream exactly where
        the twin stops — committed one token at a time, the tail of the
        burst is discarded."""
        model, v = served("gpt")
        probe = ContinuousBatchingScheduler(
            _engine(model, v), eos_id=-1).run(
                [Request(rid=0, prompt=PROMPT, max_new_tokens=6)])
        stream = probe["completions"][0].tokens
        eos = stream[2]     # third token: lands mid-burst at k=4
        draft = _engine(model, v)
        eng = ServeEngine(model, v["params"], draft=draft, spec_tokens=4,
                          max_batch=3, page_size=4, max_pages=32,
                          prompt_buckets=(8, 16), max_seq=24, seed=0)
        out = ContinuousBatchingScheduler(eng, eos_id=eos).run(
            [Request(rid=0, prompt=PROMPT, max_new_tokens=6)])
        c = out["completions"][0]
        stop = stream.index(eos)
        assert c.reason == "eos" and c.tokens == stream[:stop + 1]
        assert out["pages"]["leaked"] == 0
        assert out["pages"]["draft_leaked"] == 0

    def test_zero_retraces_speculative(self, served):
        """Steady state re-dispatches exactly the compiled pair set
        (draft decode + fused verify on the hot loop, prefill on the
        admission path) — fresh rids/lengths/pages add ZERO traces."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.xla_flags import (
            compile_event_counts,
            install_compile_counter,
        )
        model, v = served("gpt")
        draft_model = get_model("gpt_tiny", num_classes=VOCAB,
                                scan_layers=True)
        dv = draft_model.init(jax.random.key(99),
                              np.asarray(PROMPT, np.int32)[None])
        eng = _spec_pair(model, v, draft_model, dv, 2, max_seq=48,
                         max_pages=64)
        assert install_compile_counter()
        ContinuousBatchingScheduler(eng, eos_id=-1).run(
            [Request(rid=100, prompt=PROMPT, max_new_tokens=2)])
        before = compile_event_counts()
        out = ContinuousBatchingScheduler(eng, eos_id=-1).run(
            [Request(rid=i, prompt=PROMPT[:4 + i], max_new_tokens=32)
             for i in range(2)])
        after = compile_event_counts()
        assert out["spec"]["verify_steps"] >= 16
        assert after["traces"] == before["traces"], "speculative retrace"
        assert after["compiles"] == before["compiles"]

    def test_spec_telemetry_zero_filled_without_draft(self, served):
        model, v = served("gpt")
        out = ContinuousBatchingScheduler(_engine(model, v)).run(
            [Request(rid=0, prompt=PROMPT[:4], max_new_tokens=3)])
        assert out["spec"] == {"acceptance_rate": 0.0, "draft_steps": 0,
                               "verify_steps": 0,
                               "target_steps_per_token": 0.0}
        assert out["pages"]["draft_peak_in_use"] == 0
        assert out["pages"]["draft_leaked"] == 0

    def test_pairing_rejections(self, served):
        model, v = served("gpt")
        draft_model = get_model("gpt_tiny", num_classes=VOCAB,
                                scan_layers=True)
        dv = draft_model.init(jax.random.key(99),
                              np.asarray(PROMPT, np.int32)[None])
        # one flag without the other is inert — rejected
        with pytest.raises(ValueError, match="BOTH"):
            _engine(model, v, draft=_engine(draft_model, dv))
        with pytest.raises(ValueError, match="BOTH"):
            _engine(model, v, spec_tokens=4)
        # vocab mismatch: ids from different id spaces
        other = get_model("gpt_tiny", num_classes=VOCAB + 1,
                          scan_layers=True)
        ov = other.init(jax.random.key(1),
                        np.asarray(PROMPT, np.int32)[None])
        with pytest.raises(ValueError, match="vocabulary mismatch"):
            _engine(model, v, draft=_engine(other, ov), spec_tokens=2)
        # MoE draft: densely-evaluated experts cost MORE than the dense
        # twin at decode — a draft exists to be cheap
        moe, mv = served("gpt_moe")
        with pytest.raises(ValueError, match="MoE draft"):
            _engine(model, v, draft=_engine(moe, mv), spec_tokens=2)
        # geometry mismatch: the pools must stay position-paired
        with pytest.raises(ValueError, match="geometry"):
            _engine(model, v,
                    draft=_engine(draft_model, dv, page_size=8),
                    spec_tokens=2)
        # per-request temperature rejected at submit in spec mode
        eng = _spec_pair(model, v, draft_model, dv, 2)
        with pytest.raises(ValueError, match="temperature"):
            ContinuousBatchingScheduler(eng).run(
                [Request(rid=0, prompt=PROMPT[:4], max_new_tokens=2,
                         temperature=0.7)])


class TestSpeculativePages:
    def test_paired_admit_rolls_back_both_pools(self):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.serve.cache import (
            paired_admit,
        )
        tgt, dra = PageAllocator(8), PageAllocator(8)
        # plain success: both pools advance together
        got = paired_admit(tgt, dra, [], [], 3)
        assert got is not None and tgt.in_use == dra.in_use == 3
        # draft pool exhausted -> target's claim + alloc fully unwound
        dra2 = PageAllocator(4)                  # 3 usable
        pin = dra2.alloc(2)
        assert paired_admit(tgt, dra2, [], [], 3) is None
        assert tgt.in_use == 3                   # back to entry state
        assert dra2.in_use == 2
        dra2.free(pin)
        # target pool exhausted -> nothing touched in the draft pool
        tgt2 = PageAllocator(4)
        tgt2.alloc(3)
        assert paired_admit(tgt2, dra, [], [], 3) is None
        assert dra.in_use == 3
        # unequal hit runs break the one-shared-offset contract
        with pytest.raises(ValueError, match="equal length"):
            paired_admit(tgt, dra, [1], [], 2)

    def test_dual_pool_joint_occupancy_audit(self, served):
        """PR 17 shadow-refcount property test extended to the pool
        PAIR: speculation + prefix cache + chunked prefill over tight
        twin pools, every allocator operation re-audited in BOTH, and
        the pools' joint occupancy mirroring through accept/rollback
        cycles, LRU eviction, backpressure and timeout eviction."""
        model, v = served("gpt")
        rng = np.random.default_rng(41)
        sys_prefix = rng.integers(1, VOCAB, 8).tolist()

        def mk(rid, tail, new=6):
            return Request(rid=rid,
                           prompt=sys_prefix + rng.integers(
                               1, VOCAB, tail).tolist(),
                           max_new_tokens=new)

        kw = dict(prefix_cache=True, prefill_chunk=4, max_pages=18,
                  max_seq=28)
        draft = _engine(model, v, **kw)
        draft.allocator = _AuditAllocator(18)
        eng = ServeEngine(model, v["params"], draft=draft, spec_tokens=2,
                          max_batch=3, page_size=4, prompt_buckets=(8, 16),
                          seed=0, **kw)
        eng.allocator = _AuditAllocator(18)
        out = ContinuousBatchingScheduler(eng).run(
            [mk(i, 1 + (i % 5)) for i in range(8)])
        assert out["page_reuse_ratio"] > 0
        assert out["spec"]["verify_steps"] > 0
        # the joint invariant: admission is all-or-nothing across the
        # pair, so the two pools' referenced-page counts track each
        # other exactly at every quiescent point
        assert eng.allocator.in_use == draft.allocator.in_use == 0
        assert eng.allocator.ops > 20 and draft.allocator.ops > 20
        # timeout eviction releases BOTH pools' spans
        out2 = ContinuousBatchingScheduler(
            eng, request_timeout=1e-6).run(
                [mk(100 + i, 3, new=8) for i in range(4)])
        assert out2["timed_out"] == 4
        assert eng.allocator.in_use == draft.allocator.in_use == 0
        assert out2["pages"]["leaked"] == 0
        assert out2["pages"]["draft_leaked"] == 0
