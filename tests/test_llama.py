"""Llama-style decoder family (``models/llama.py``): RMSNorm + RoPE +
SwiGLU on the shared causal-attention stack, with the full parallelism
matrix (TP with vocab-parallel head, SP ring with RoPE offsets, GPipe PP,
MoE, FSDP) exercised through the driver."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models.llama import rope


class TestRoPE:
    def test_norm_preserving_and_relative(self):
        """Rotations preserve per-pair norms, and q.k after RoPE depends
        only on the RELATIVE position offset (the property that makes RoPE
        work)."""
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(1, 8, 2, 16)), jnp.float32)
        pos = jnp.arange(8)
        r = rope(x, pos)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(r), axis=-1),
            np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5)
        # relative property: <rope(q,p1), rope(k,p2)> == f(p1-p2)
        q = jnp.asarray(rng.normal(size=(1, 1, 1, 16)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(1, 1, 1, 16)), jnp.float32)
        def dot(p1, p2):
            rq = rope(q, jnp.asarray([p1]))
            rk = rope(k, jnp.asarray([p2]))
            return float((rq * rk).sum())
        np.testing.assert_allclose(dot(5, 3), dot(9, 7), rtol=1e-5)
        assert abs(dot(5, 3) - dot(5, 4)) > 1e-6

    def test_zero_position_is_identity(self):
        x = jnp.asarray(np.random.default_rng(1).normal(size=(1, 1, 2, 8)),
                        jnp.float32)
        np.testing.assert_allclose(rope(x, jnp.zeros(1, jnp.int32)), x,
                                   atol=1e-6)


class TestLlamaModule:
    def test_forward_shape_and_causality(self):
        m = get_model("llama_tiny", num_classes=1000)
        x = jnp.asarray(np.random.default_rng(0).integers(2, 100, (2, 16)),
                        jnp.int32)
        v = jax.jit(lambda k: m.init(k, x))(jax.random.key(0))
        out = m.apply(v, x)
        assert out.shape == (2, 16, 1000)
        x2 = x.at[:, 8:].set(7)  # perturb the future
        out2 = m.apply(v, x2)
        np.testing.assert_allclose(out[:, :8], out2[:, :8], atol=2e-5)
        assert np.abs(np.asarray(out[:, 8:]) -
                      np.asarray(out2[:, 8:])).max() > 1e-3

    def test_no_biases_no_position_table(self):
        """The Llama recipe: RMSNorm scales + kernels + embeddings only —
        no bias params, no learned position embedding."""
        m = get_model("llama_tiny", num_classes=1000)
        vs = jax.eval_shape(
            lambda: m.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32)))
        names = [jax.tree_util.keystr(p) for p, _ in
                 jax.tree_util.tree_leaves_with_path(vs["params"])]
        assert not any("bias" in n for n in names), names
        assert not any("pos_emb" in n for n in names), names

    def test_param_count_formula(self):
        """llama_tiny params = vocab*h (embed) + vocab*h (untied head)
        + per-layer (4h^2 attn + 3*h*ffn SwiGLU + 2h RMS) + h final RMS."""
        m = get_model("llama_tiny", num_classes=1000)
        vs = jax.eval_shape(
            lambda: m.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32)))
        n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(vs["params"]))
        h, f, L, v = 64, 176, 2, 1000
        assert n == 2 * v * h + L * (4 * h * h + 3 * h * f + 2 * h) + h


def _run(devices, mesh_axes, **extra):
    mesh = build_mesh(mesh_axes, devices)
    cfg = Config(model="llama_tiny", dataset="synthetic_lm",
                 epochs_global=2, epochs_local=1, batch_size=8,
                 limit_train_samples=128, limit_eval_samples=32,
                 compute_dtype="float32", augment=False,
                 aggregation_by="weights", seed=3, **extra)
    return train_global(cfg, mesh=mesh, progress=False)


class TestDriverLlama:
    def test_dp_loss_decreases(self, devices):
        res = _run(devices[:2], {"data": 2})
        l = res["global_train_losses"]
        assert l[-1] < l[0], l

    def test_tensor_parallel_matches_dense(self, devices):
        """TP with the vocab-parallel lm_head (bert._tp_parts 'lm_head'
        pattern) must reproduce the dense numerics."""
        dense = _run(devices[:2], {"data": 2})
        tp = _run(devices[:4], {"data": 2, "model": 2})
        np.testing.assert_allclose(tp["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)

    @pytest.mark.parametrize("axes,extra", [
        ({"data": 2, "seq": 2}, {"sequence_parallel": "ring"}),
        ({"data": 2, "pipe": 2}, {}),
        ({"data": 2, "fsdp": 2}, {}),
        ({"data": 2, "expert": 2}, {"num_experts": 4}),
        ({"data": 2, "pipe": 2, "model": 2}, {}),
    ], ids=["seq_ring", "pipeline", "fsdp", "expert_moe", "pp_tp"])
    def test_parallel_modes(self, axes, extra, devices):
        n = int(np.prod(list(axes.values())))
        res = _run(devices[:n], axes, **extra)
        assert np.isfinite(res["global_train_losses"]).all()

    def test_seq_parallel_matches_dense(self, devices):
        """RoPE offsets under ring attention: seq-sharded run must match
        the dense data=2 run (absolute positions via axis_index)."""
        dense = _run(devices[:2], {"data": 2})
        sp = _run(devices[:4], {"data": 2, "seq": 2},
                  sequence_parallel="ring")
        np.testing.assert_allclose(sp["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)
