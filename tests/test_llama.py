"""Llama-style decoder family (``models/llama.py``): RMSNorm + RoPE +
SwiGLU on the shared causal-attention stack, with the full parallelism
matrix (TP with vocab-parallel head, SP ring with RoPE offsets, GPipe PP,
MoE, FSDP) exercised through the driver."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models.llama import rope


class TestRoPE:
    def test_norm_preserving_and_relative(self):
        """Rotations preserve per-pair norms, and q.k after RoPE depends
        only on the RELATIVE position offset (the property that makes RoPE
        work)."""
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(1, 8, 2, 16)), jnp.float32)
        pos = jnp.arange(8)
        r = rope(x, pos)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(r), axis=-1),
            np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5)
        # relative property: <rope(q,p1), rope(k,p2)> == f(p1-p2)
        q = jnp.asarray(rng.normal(size=(1, 1, 1, 16)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(1, 1, 1, 16)), jnp.float32)
        def dot(p1, p2):
            rq = rope(q, jnp.asarray([p1]))
            rk = rope(k, jnp.asarray([p2]))
            return float((rq * rk).sum())
        np.testing.assert_allclose(dot(5, 3), dot(9, 7), rtol=1e-5)
        assert abs(dot(5, 3) - dot(5, 4)) > 1e-6

    def test_zero_position_is_identity(self):
        x = jnp.asarray(np.random.default_rng(1).normal(size=(1, 1, 2, 8)),
                        jnp.float32)
        np.testing.assert_allclose(rope(x, jnp.zeros(1, jnp.int32)), x,
                                   atol=1e-6)


class TestLlamaModule:
    def test_forward_shape_and_causality(self):
        m = get_model("llama_tiny", num_classes=1000)
        x = jnp.asarray(np.random.default_rng(0).integers(2, 100, (2, 16)),
                        jnp.int32)
        v = jax.jit(lambda k: m.init(k, x))(jax.random.key(0))
        out = m.apply(v, x)
        assert out.shape == (2, 16, 1000)
        x2 = x.at[:, 8:].set(7)  # perturb the future
        out2 = m.apply(v, x2)
        np.testing.assert_allclose(out[:, :8], out2[:, :8], atol=2e-5)
        assert np.abs(np.asarray(out[:, 8:]) -
                      np.asarray(out2[:, 8:])).max() > 1e-3

    def test_no_biases_no_position_table(self):
        """The Llama recipe: RMSNorm scales + kernels + embeddings only —
        no bias params, no learned position embedding."""
        m = get_model("llama_tiny", num_classes=1000)
        vs = jax.eval_shape(
            lambda: m.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32)))
        names = [jax.tree_util.keystr(p) for p, _ in
                 jax.tree_util.tree_leaves_with_path(vs["params"])]
        assert not any("bias" in n for n in names), names
        assert not any("pos_emb" in n for n in names), names

    def test_param_count_formula(self):
        """llama_tiny params = vocab*h (embed) + vocab*h (untied head)
        + per-layer (4h^2 attn + 3*h*ffn SwiGLU + 2h RMS) + h final RMS."""
        m = get_model("llama_tiny", num_classes=1000)
        vs = jax.eval_shape(
            lambda: m.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32)))
        n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(vs["params"]))
        h, f, L, v = 64, 176, 2, 1000
        assert n == 2 * v * h + L * (4 * h * h + 3 * h * f + 2 * h) + h


def _run(devices, mesh_axes, **extra):
    mesh = build_mesh(mesh_axes, devices)
    cfg = Config(model="llama_tiny", dataset="synthetic_lm",
                 epochs_global=2, epochs_local=1, batch_size=8,
                 limit_train_samples=128, limit_eval_samples=32,
                 compute_dtype="float32", augment=False,
                 aggregation_by="weights", seed=3, **extra)
    return train_global(cfg, mesh=mesh, progress=False)


@pytest.mark.slow
class TestDriverLlama:
    def test_dp_loss_decreases(self, devices):
        res = _run(devices[:2], {"data": 2})
        l = res["global_train_losses"]
        assert l[-1] < l[0], l

    def test_tensor_parallel_matches_dense(self, devices):
        """TP with the vocab-parallel lm_head (bert._tp_parts 'lm_head'
        pattern) must reproduce the dense numerics."""
        dense = _run(devices[:2], {"data": 2})
        tp = _run(devices[:4], {"data": 2, "model": 2})
        np.testing.assert_allclose(tp["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)

    @pytest.mark.parametrize("axes,extra", [
        ({"data": 2, "seq": 2}, {"sequence_parallel": "ring"}),
        ({"data": 2, "pipe": 2}, {}),
        ({"data": 2, "fsdp": 2}, {}),
        ({"data": 2, "expert": 2}, {"num_experts": 4}),
        ({"data": 2, "pipe": 2, "model": 2}, {}),
    ], ids=["seq_ring", "pipeline", "fsdp", "expert_moe", "pp_tp"])
    def test_parallel_modes(self, axes, extra, devices):
        n = int(np.prod(list(axes.values())))
        res = _run(devices[:n], axes, **extra)
        assert np.isfinite(res["global_train_losses"]).all()

    def test_seq_parallel_matches_dense(self, devices):
        """RoPE offsets under ring attention: seq-sharded run must match
        the dense data=2 run (absolute positions via axis_index)."""
        dense = _run(devices[:2], {"data": 2})
        sp = _run(devices[:4], {"data": 2, "seq": 2},
                  sequence_parallel="ring")
        np.testing.assert_allclose(sp["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)


@pytest.mark.slow
class TestGQA:
    """Grouped-query attention: separate q / kv projections, kv heads
    shared across query groups, broadcast after RoPE."""

    def _model(self, **kw):
        return get_model("llama_tiny", num_classes=1000, num_kv_heads=2,
                         **kw)

    def test_param_structure_and_count(self):
        m = self._model()
        vs = jax.eval_shape(
            lambda: m.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32)))
        names = [jax.tree_util.keystr(p) for p, _ in
                 jax.tree_util.tree_leaves_with_path(vs["params"])]
        assert any("['q']" in n for n in names)
        assert any("['kv']" in n for n in names)
        assert not any("qkv" in n for n in names)
        n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(vs["params"]))
        # attn per layer: q h*h + kv 2*(kv/heads)*h*h + out h*h
        h, f, L, v, kvfrac = 64, 176, 2, 1000, 2 / 4
        attn = h * h + 2 * int(kvfrac * h * h) + h * h
        assert n == 2 * v * h + L * (attn + 3 * h * f + 2 * h) + h

    def test_causality_and_finite(self):
        m = self._model()
        x = jnp.asarray(np.random.default_rng(0).integers(2, 100, (2, 16)),
                        jnp.int32)
        v = jax.jit(lambda k: m.init(k, x))(jax.random.key(0))
        out = m.apply(v, x)
        assert np.isfinite(np.asarray(out)).all()
        x2 = x.at[:, 8:].set(7)
        out2 = m.apply(v, x2)
        np.testing.assert_allclose(out[:, :8], out2[:, :8], atol=2e-5)

    def test_kv_heads_must_divide(self):
        m = get_model("llama_tiny", num_classes=1000, num_kv_heads=3)
        x = jnp.zeros((1, 8), jnp.int32)
        with pytest.raises(ValueError, match="num_kv_heads"):
            m.init(jax.random.key(0), x)

    def test_gqa_tp_matches_single_device(self, devices):
        """GQA under TP: q sharded by head, kv by kv-head (bert._tp_parts
        'q'/'kv' patterns); sharded forward == dense forward."""
        from jax.sharding import Mesh, PartitionSpec as P
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models.bert import (
            tp_param_specs,
        )
        dense = self._model()
        tp = self._model(tp_size=2, model_axis="model")
        x = jnp.asarray(np.random.default_rng(1).integers(2, 100, (2, 16)),
                        jnp.int32)
        params = dense.init(jax.random.key(1), x)["params"]
        specs = tp_param_specs(params, axis="model")
        mesh = Mesh(np.array(devices[:2]), ("model",))
        f = jax.jit(jax.shard_map(
            lambda p, x: tp.apply({"params": p}, x, train=False),
            mesh=mesh, in_specs=(specs, P()),
            out_specs=P(None, None, "model")))
        np.testing.assert_allclose(
            f(params, x),
            dense.apply({"params": params}, x, train=False), atol=2e-4)

    def test_gqa_via_driver_flag(self, devices):
        """--num_kv_heads plumbs through the driver (TP mesh) and trains."""
        res = _run(devices[:4], {"data": 2, "model": 2}, num_kv_heads=2)
        assert np.isfinite(res["global_train_losses"]).all()

    def test_gqa_flag_rejected_for_non_llama(self, devices):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        cfg = Config(model="bert_tiny", dataset="synthetic_mlm",
                     batch_size=8, limit_train_samples=64,
                     limit_eval_samples=16, augment=False, num_kv_heads=2)
        mesh = build_mesh({"data": 2}, devices[:2])
        with pytest.raises(ValueError, match="num_kv_heads"):
            train_global(cfg, mesh=mesh, progress=False)
