"""Tensor parallelism (Megatron construction, ``parallel/tp.py``).

Correctness is asserted against the dense module on a 2-device ``model``
mesh (forward AND parameter gradients — the custom-vjp region markers must
make replicated-parameter grads exact), and end-to-end through the driver
on a (data=2, model=2) mesh against the dense data=2 run with identical
seed/config.  Beyond-reference capability (the reference is data-parallel
only, SURVEY.md 2.3).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models.bert import (
    tp_param_specs,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import (
    softmax_cross_entropy,
)


@pytest.fixture(scope="module")
def tp_mesh(devices):
    return Mesh(np.array(devices[:2]), ("model",))


VOCAB = 97


def _models():
    dense = get_model("bert_tiny", num_classes=VOCAB)
    tp = get_model("bert_tiny", num_classes=VOCAB, tp_size=2,
                   model_axis="model")
    return dense, tp


def _data(b=2, l=16, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.integers(0, VOCAB, (b, l)), jnp.int32)
    y = jnp.asarray(rng.integers(0, VOCAB, (b, l)), jnp.int32)
    return x, y


class TestTPModule:
    def test_forward_matches_dense(self, tp_mesh):
        dense, tp = _models()
        x, _ = _data()
        params = dense.init(jax.random.key(0), x, train=False)["params"]
        specs = tp_param_specs(params, axis="model")
        f = jax.jit(jax.shard_map(
            lambda p, x: tp.apply({"params": p}, x, train=False),
            mesh=tp_mesh, in_specs=(specs, P()), out_specs=P()))
        np.testing.assert_allclose(
            f(params, x), dense.apply({"params": params}, x, train=False),
            atol=1e-4)

    def test_param_grads_match_dense(self, tp_mesh):
        dense, tp = _models()
        x, y = _data(seed=1)
        params = dense.init(jax.random.key(1), x, train=False)["params"]
        specs = tp_param_specs(params, axis="model")

        def loss(model):
            def f(p, x, y):
                logits = model.apply({"params": p}, x, train=False)
                return softmax_cross_entropy(logits, y).mean()
            return f

        sharded = jax.jit(jax.shard_map(
            loss(tp), mesh=tp_mesh, in_specs=(specs, P(), P()),
            out_specs=P()))
        g = jax.grad(sharded)(params, x, y)
        gref = jax.grad(loss(dense))(params, x, y)
        flat = jax.tree_util.tree_leaves_with_path(g)
        ref = dict(jax.tree_util.tree_leaves_with_path(gref))
        for path, leaf in flat:
            np.testing.assert_allclose(
                leaf, ref[path], atol=2e-4,
                err_msg=jax.tree_util.keystr(path))

    def test_specs_cover_sharded_params(self):
        dense, _ = _models()
        x, _ = _data()
        params = dense.init(jax.random.key(0), x, train=False)["params"]
        specs = tp_param_specs(params, axis="model")
        flat = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda s: "model" in s, specs,
                                   is_leaf=lambda s: isinstance(s, P)))
        # every encoder layer contributes 4 sharded kernels + 2 sharded
        # biases (qkv kernel+bias, out kernel, ffn_in kernel+bias, ffn_out
        # kernel); bert_tiny has 2 layers
        assert sum(flat) == 2 * 6


class TestDriverTensorParallel:
    """BERT training TP-sharded over a (data=2, model=2) mesh must match
    the dense data=2 run: same shards, same rng, numerics within fp32
    tolerance."""

    def _run(self, devices, mesh_axes):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        mesh = build_mesh(mesh_axes, devices)
        cfg = Config(model="bert_tiny", dataset="synthetic_mlm",
                     epochs_global=2, epochs_local=1, batch_size=8,
                     limit_train_samples=128, limit_eval_samples=32,
                     compute_dtype="float32", augment=False,
                     aggregation_by="weights", seed=7)
        return train_global(cfg, mesh=mesh, progress=False)

    def test_matches_dense_run(self, devices):
        dense = self._run(devices[:2], {"data": 2})
        tp = self._run(devices[:4], {"data": 2, "model": 2})
        np.testing.assert_allclose(tp["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)
        assert tp["global_train_losses"][-1] < tp["global_train_losses"][0]

    def test_requires_attention_model(self, devices):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        mesh = build_mesh({"data": 2, "model": 2}, devices[:4])
        cfg = Config(model="mlp", dataset="mnist", limit_train_samples=64,
                     limit_eval_samples=16, augment=False)
        with pytest.raises(ValueError, match="model"):
            train_global(cfg, mesh=mesh, progress=False)
