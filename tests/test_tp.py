"""Tensor parallelism (Megatron construction, ``parallel/tp.py``).

Correctness is asserted against the dense module on a 2-device ``model``
mesh (forward AND parameter gradients — the custom-vjp region markers must
make replicated-parameter grads exact), and end-to-end through the driver
on a (data=2, model=2) mesh against the dense data=2 run with identical
seed/config.  Beyond-reference capability (the reference is data-parallel
only, SURVEY.md 2.3).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models.bert import (
    tp_param_specs,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import (
    softmax_cross_entropy,
)


@pytest.fixture(scope="module")
def tp_mesh(devices):
    return Mesh(np.array(devices[:2]), ("model",))


VOCAB = 96  # divisible by tp_size=2 (vocab-parallel head)


def _models():
    dense = get_model("bert_tiny", num_classes=VOCAB)
    tp = get_model("bert_tiny", num_classes=VOCAB, tp_size=2,
                   model_axis="model")
    return dense, tp


def _data(b=2, l=16, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.integers(0, VOCAB, (b, l)), jnp.int32)
    y = jnp.asarray(rng.integers(0, VOCAB, (b, l)), jnp.int32)
    return x, y


class TestTPModule:
    def test_forward_matches_dense(self, tp_mesh):
        dense, tp = _models()
        x, _ = _data()
        params = dense.init(jax.random.key(0), x, train=False)["params"]
        specs = tp_param_specs(params, axis="model")
        # the TP model's output is its LOCAL vocab slice; stitching the
        # model axis back (out_specs) must reproduce the dense logits
        f = jax.jit(jax.shard_map(
            lambda p, x: tp.apply({"params": p}, x, train=False),
            mesh=tp_mesh, in_specs=(specs, P()),
            out_specs=P(None, None, "model")))
        np.testing.assert_allclose(
            f(params, x), dense.apply({"params": params}, x, train=False),
            atol=1e-4)

    def test_param_grads_match_dense(self, tp_mesh):
        dense, tp = _models()
        x, y = _data(seed=1)
        params = dense.init(jax.random.key(1), x, train=False)["params"]
        specs = tp_param_specs(params, axis="model")

        def loss(model):
            def f(p, x, y):
                logits = model.apply({"params": p}, x, train=False)
                return softmax_cross_entropy(logits, y).mean()
            return f

        def tp_loss(p, x, y):
            # vocab-parallel CE over the sharded-logit output
            from learning_deep_neural_network_in_distributed_computing_environment_tpu.parallel.tp import (
                vocab_parallel_token_stats)
            logits = tp.apply({"params": p}, x, train=False)
            ce, w, _ = vocab_parallel_token_stats(
                logits, y, jnp.ones(y.shape[:1], jnp.float32), "model")
            return (ce * w).sum() / w.sum()

        sharded = jax.jit(jax.shard_map(
            tp_loss, mesh=tp_mesh, in_specs=(specs, P(), P()),
            out_specs=P()))
        g = jax.grad(sharded)(params, x, y)
        gref = jax.grad(loss(dense))(params, x, y)
        flat = jax.tree_util.tree_leaves_with_path(g)
        ref = dict(jax.tree_util.tree_leaves_with_path(gref))
        for path, leaf in flat:
            np.testing.assert_allclose(
                leaf, ref[path], atol=2e-4,
                err_msg=jax.tree_util.keystr(path))

    def test_specs_cover_sharded_params(self):
        dense, _ = _models()
        x, _ = _data()
        params = dense.init(jax.random.key(0), x, train=False)["params"]
        specs = tp_param_specs(params, axis="model")
        flat = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda s: "model" in s, specs,
                                   is_leaf=lambda s: isinstance(s, P)))
        # every encoder layer contributes 4 sharded kernels + 2 sharded
        # biases (qkv kernel+bias, out kernel, ffn_in kernel+bias, ffn_out
        # kernel); bert_tiny has 2 layers; + the vocab-parallel MLM decode
        # kernel and bias
        assert sum(flat) == 2 * 6 + 2


class TestVocabParallelStats:
    def test_matches_masked_token_stats(self, devices):
        """vp CE/accuracy over vocab-sharded logits == the dense stats on
        the gathered logits, including ignore-index (-1) labels."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.parallel.tp import (
            vocab_parallel_token_stats)
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import (
            masked_token_stats)
        mesh = Mesh(np.array(devices[:2]), ("model",))
        rng = np.random.default_rng(3)
        logits = jnp.asarray(rng.normal(size=(4, 8, VOCAB)), jnp.float32)
        labels = jnp.asarray(rng.integers(-1, VOCAB, (4, 8)), jnp.int32)
        mask = jnp.asarray([1.0, 1.0, 0.0, 1.0], jnp.float32)

        f = jax.jit(jax.shard_map(
            lambda lg: vocab_parallel_token_stats(lg, labels, mask, "model"),
            mesh=mesh, in_specs=P(None, None, "model"),
            out_specs=(P(), P(), P())))
        ce, w, correct = f(logits)
        ce_ref, w_ref, correct_ref = masked_token_stats(logits, labels, mask)
        np.testing.assert_allclose(ce, ce_ref, atol=1e-5)
        np.testing.assert_allclose(w, w_ref)
        np.testing.assert_allclose(correct, correct_ref)

    def test_grad_matches_dense(self, devices):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.parallel.tp import (
            vocab_parallel_token_stats)
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import (
            masked_token_stats)
        mesh = Mesh(np.array(devices[:2]), ("model",))
        rng = np.random.default_rng(4)
        logits = jnp.asarray(rng.normal(size=(2, 4, VOCAB)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, VOCAB, (2, 4)), jnp.int32)
        mask = jnp.ones((2,), jnp.float32)

        def vp_loss(lg):
            ce, w, _ = vocab_parallel_token_stats(lg, labels, mask, "model")
            return (ce * w).sum() / w.sum()

        g = jax.jit(jax.grad(jax.shard_map(
            vp_loss, mesh=mesh, in_specs=P(None, None, "model"),
            out_specs=P())))(logits)

        def dense_loss(lg):
            ce, w, _ = masked_token_stats(lg, labels, mask)
            return (ce * w).sum() / w.sum()

        np.testing.assert_allclose(g, jax.grad(dense_loss)(logits),
                                   atol=1e-6)


@pytest.mark.slow
class TestDriverTensorParallel:
    """BERT training TP-sharded over a (data=2, model=2) mesh must match
    the dense data=2 run: same shards, same rng, numerics within fp32
    tolerance."""

    def _run(self, devices, mesh_axes):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        mesh = build_mesh(mesh_axes, devices)
        cfg = Config(model="bert_tiny", dataset="synthetic_mlm",
                     epochs_global=2, epochs_local=1, batch_size=8,
                     limit_train_samples=128, limit_eval_samples=32,
                     compute_dtype="float32", augment=False,
                     aggregation_by="weights", seed=7)
        return train_global(cfg, mesh=mesh, progress=False)

    def test_matches_dense_run(self, devices):
        dense = self._run(devices[:2], {"data": 2})
        tp = self._run(devices[:4], {"data": 2, "model": 2})
        np.testing.assert_allclose(tp["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)
        assert tp["global_train_losses"][-1] < tp["global_train_losses"][0]

    def test_gradients_mode_with_sharded_params(self, devices):
        """aggregation_by=gradients (the reference default) under TP: the
        aggregated-gradient norm must psum sharded leaves over 'model'
        (regression: optax.global_norm of sharded grads varies over the
        model axis and broke the metrics out_spec replication check)."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        mesh = build_mesh({"data": 2, "model": 2}, devices[:4])
        cfg = Config(model="bert_tiny", dataset="synthetic_mlm",
                     epochs_global=1, epochs_local=1, batch_size=8,
                     limit_train_samples=64, limit_eval_samples=16,
                     compute_dtype="float32", augment=False,
                     aggregation_by="gradients", seed=7)
        res = train_global(cfg, mesh=mesh, progress=False)
        assert np.isfinite(res["global_train_losses"]).all()

    def test_requires_attention_model(self, devices):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        mesh = build_mesh({"data": 2, "model": 2}, devices[:4])
        cfg = Config(model="mlp", dataset="mnist", limit_train_samples=64,
                     limit_eval_samples=16, augment=False)
        with pytest.raises(ValueError, match="model"):
            train_global(cfg, mesh=mesh, progress=False)
