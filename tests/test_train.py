"""Engine-level tests: StepLR semantics, masking, local-SGD invariants."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import (
    LocalSGDEngine,
    softmax_cross_entropy,
    steplr,
)


def small_cfg(**kw):
    base = dict(model="mlp", dataset="mnist", epochs_local=2, epochs_global=2,
                batch_size=8, compute_dtype="float32", augment=False,
                aggregation_by="weights")
    base.update(kw)
    return Config(**base)


def make_engine(mesh8, cfg):
    model = get_model("mlp", num_classes=10, hidden=16)
    return LocalSGDEngine(model, mesh8, cfg), model


def make_packs(n=8, steps=4, b=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, steps, b, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, (n, steps, b)).astype(np.int32)
    m = np.ones((n, steps, b), np.float32)
    return x, y, m


class TestStepLR:
    def test_matches_torch_steplr(self):
        # StepLR(step_size=25, gamma=0.1), stepped per local epoch
        # (ref main.py:54, trainer.py:218)
        lrs = [float(steplr(1e-3, 0.1, 25, jnp.asarray(e)))
               for e in [0, 24, 25, 49, 50]]
        np.testing.assert_allclose(
            lrs, [1e-3, 1e-3, 1e-4, 1e-4, 1e-5], rtol=1e-6)


class TestCrossEntropy:
    def test_matches_manual(self):
        logits = jnp.asarray([[2.0, 0.5, -1.0]])
        labels = jnp.asarray([0])
        p = np.exp([2.0, 0.5, -1.0])
        expect = -np.log(p[0] / p.sum())
        np.testing.assert_allclose(
            np.asarray(softmax_cross_entropy(logits, labels)), [expect],
            rtol=1e-6)

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_fused_vjp_matches_log_softmax_path(self, dtype):
        # the production CE is a custom_vjp whose residuals avoid the f32
        # [.., vocab] log_softmax array; values AND gradients must match
        # the plain log_softmax twin to float rounding
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import (
            softmax_cross_entropy_reference,
        )
        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.normal(size=(4, 7, 33)) * 3, dtype)
        labels = jnp.asarray(rng.integers(0, 33, (4, 7)), jnp.int32)

        got = softmax_cross_entropy(logits, labels)
        want = softmax_cross_entropy_reference(logits, labels)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)

        def mean_ce(fn):
            return lambda lg: fn(lg, labels).mean()

        g_got = jax.grad(mean_ce(softmax_cross_entropy))(logits)
        g_want = jax.grad(mean_ce(softmax_cross_entropy_reference))(logits)
        assert g_got.dtype == logits.dtype
        np.testing.assert_allclose(
            np.asarray(g_got, np.float32), np.asarray(g_want, np.float32),
            rtol=1e-5, atol=1e-6)


class TestEngine:
    def test_round_learns_and_lr_epoch_advances(self, mesh8):
        cfg = small_cfg()
        engine, _ = make_engine(mesh8, cfg)
        x, y, m = make_packs()
        state = engine.init_state(jax.random.key(0), x[0, 0])
        state, mx = engine.round(state, (x, y, m), (x, y, m))
        assert np.all(np.asarray(state.lr_epoch) == cfg.epochs_local)
        assert mx["train_loss"].shape == (8, cfg.epochs_local)
        # learning on random labels still reduces loss epoch-over-epoch
        # (memorization) for at least most workers
        assert mx["train_loss"][:, -1].mean() < mx["train_loss"][:, 0].mean()

    def test_masked_steps_do_not_update(self, mesh8):
        cfg = small_cfg(epochs_local=1)
        engine, _ = make_engine(mesh8, cfg)
        x, y, m = make_packs(steps=4)
        m2 = m.copy()
        m2[:, 2:] = 0.0  # last two steps are padding
        state = engine.init_state(jax.random.key(0), x[0, 0])
        s_full, _ = engine.round(state, (x[:, :2], y[:, :2], m[:, :2]),
                                 (x, y, m))
        state2 = engine.init_state(jax.random.key(0), x[0, 0])
        s_masked, _ = engine.round(state2, (x, y, m2), (x, y, m))
        # 2 real steps == 4 steps with last 2 masked
        a = jax.tree_util.tree_leaves(s_full.params)
        b = jax.tree_util.tree_leaves(s_masked.params)
        for u, v in zip(a, b):
            np.testing.assert_allclose(np.asarray(u), np.asarray(v),
                                       rtol=1e-5, atol=1e-6)

    def test_weights_equal_allreduce_syncs_replicas(self, mesh8):
        cfg = small_cfg(aggregation_by="weights", aggregation_type="equal",
                        topology="allreduce")
        engine, _ = make_engine(mesh8, cfg)
        x, y, m = make_packs()
        state = engine.init_state(jax.random.key(0), x[0, 0])
        state, _ = engine.round(state, (x, y, m), (x, y, m))
        # after FedAvg sync all replicas hold identical params
        for leaf in jax.tree_util.tree_leaves(state.params):
            arr = np.asarray(leaf)
            np.testing.assert_allclose(arr, np.broadcast_to(arr[:1], arr.shape),
                                       rtol=1e-5, atol=1e-6)

    def test_gradients_mode_leaves_params_independent(self, mesh8):
        # reference gradients mode: collectives run but weights are NOT
        # synchronized (SURVEY.md 3.2)
        cfg = small_cfg(aggregation_by="gradients")
        engine, _ = make_engine(mesh8, cfg)
        x, y, m = make_packs()
        state = engine.init_state(jax.random.key(0), x[0, 0])
        state, mx = engine.round(state, (x, y, m), (x, y, m))
        assert float(mx["agg_grad_norm"][0]) > 0.0
        leaf = np.asarray(jax.tree_util.tree_leaves(state.params)[0])
        # different data per worker => diverged replicas
        assert not np.allclose(leaf[0], leaf[1])

    def test_ring_weighted_param_mixing(self, mesh8):
        cfg = small_cfg(aggregation_by="weights", aggregation_type="weighted",
                        topology="ring", local_weight=0.5, epochs_local=1)
        engine, _ = make_engine(mesh8, cfg)
        x, y, m = make_packs(steps=1)
        state0 = engine.init_state(jax.random.key(0), x[0, 0])
        # run an independent round first to diverge replicas
        cfg_ind = small_cfg(aggregation_by="gradients", epochs_local=1)
        eng_ind = LocalSGDEngine(engine.model, mesh8, cfg_ind)
        s1, _ = eng_ind.round(state0, (x, y, m), (x, y, m))
        before = np.asarray(jax.tree_util.tree_leaves(s1.params)[0]).copy()
        # now one ring round with zero further training (masked steps)
        zm = np.zeros_like(m)
        s2, _ = engine.round(s1, (x, y, zm), (x, y, m))
        after = np.asarray(jax.tree_util.tree_leaves(s2.params)[0])
        expect = 0.5 * before + 0.5 * np.roll(before, 1, axis=0)
        np.testing.assert_allclose(after, expect, rtol=1e-5, atol=1e-6)

    def test_bn_stats_never_synced(self, mesh8):
        cfg = small_cfg(aggregation_by="weights")
        model = get_model("enhanced_cnn", num_classes=10, width=4)
        engine = LocalSGDEngine(model, mesh8, cfg)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 2, 4, 16, 16, 3)).astype(np.float32)
        y = rng.integers(0, 10, (8, 2, 4)).astype(np.int32)
        m = np.ones((8, 2, 4), np.float32)
        state = engine.init_state(jax.random.key(0), x[0, 0])
        state, _ = engine.round(state, (x, y, m), (x, y, m))
        # params synced ...
        p = np.asarray(jax.tree_util.tree_leaves(state.params)[0])
        np.testing.assert_allclose(p, np.broadcast_to(p[:1], p.shape),
                                   rtol=1e-5, atol=1e-6)
        # ... BN running stats stay per-worker (ref communication.py:5,22)
        bs = np.asarray(jax.tree_util.tree_leaves(state.batch_stats)[0])
        assert not np.allclose(bs[0], bs[1])
