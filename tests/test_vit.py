"""Vision Transformer (``models/vit.py``) — beyond-reference model family
on the shared encoder stack.

The reshape+matmul patchify is golden-tested against the equivalent
stride-p convolution; the driver paths cover plain DP, tensor parallelism
(reusing bert.tp_param_specs via the shared EncoderLayer), and GPipe
pipeline parallelism over scanned layers.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model


class TestViTModule:
    def test_forward_shape_and_finite(self):
        model = get_model("vit_tiny", num_classes=10)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 32, 32, 3)),
                        jnp.float32)
        variables = model.init(jax.random.key(0), x, train=False)
        out = model.apply(variables, x, train=False)
        assert out.shape == (2, 10)
        assert np.isfinite(np.asarray(out)).all()

    def test_patchify_equals_stride_conv(self):
        """reshape+Dense patch embedding == Conv(kernel=p, stride=p) with
        the same weights (the TPU-first formulation is exact, not an
        approximation)."""
        model = get_model("vit_tiny", num_classes=10)
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(2, 32, 32, 3)), jnp.float32)
        variables = model.init(jax.random.key(1), x, train=False)
        kernel = variables["params"]["patch_embed"]["kernel"]  # [p*p*c, H]
        bias = variables["params"]["patch_embed"]["bias"]
        p, c, hdim = 8, 3, kernel.shape[1]

        # the module's own patch tokens
        xt = x.reshape(2, 4, p, 4, p, c).transpose(0, 1, 3, 2, 4, 5)
        tokens = xt.reshape(2, 16, p * p * c) @ kernel + bias

        conv_kernel = kernel.reshape(p, p, c, hdim)
        conv_out = lax.conv_general_dilated(
            x, conv_kernel, (p, p), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC")) + bias
        np.testing.assert_allclose(
            tokens, conv_out.reshape(2, 16, hdim), rtol=2e-5, atol=1e-5)

    def test_patchify_einsum_equals_reshape(self):
        """The r5 default 'einsum' patchify (no explicit 6-D transpose;
        VERDICT r4 'next' #3) computes EXACTLY the same function as the
        r4 'reshape' lowering, with an identical parameter tree — one
        init serves both variants."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models.vit import ViT
        kw = dict(num_classes=10, patch=8, num_layers=2, hidden=64,
                  num_heads=2, ffn_dim=128)
        ein = ViT(**kw)                      # patchify='einsum' (default)
        ref = ViT(**kw, patchify="reshape")
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.normal(size=(2, 32, 32, 3)), jnp.float32)
        variables = ein.init(jax.random.key(2), x, train=False)
        assert (jax.tree_util.tree_structure(variables)
                == jax.tree_util.tree_structure(
                    ref.init(jax.random.key(2), x, train=False)))
        np.testing.assert_allclose(
            np.asarray(ein.apply(variables, x, train=False)),
            np.asarray(ref.apply(variables, x, train=False)),
            rtol=1e-5, atol=1e-5)

    def test_param_count_vit_s16(self):
        """ViT-S/16 at 224^2/1000 classes: ~22M params (sanity that the
        geometry matches the standard family)."""
        model = get_model("vit_s16", num_classes=1000)
        x = jnp.zeros((1, 224, 224, 3), jnp.float32)
        variables = jax.eval_shape(
            lambda k: model.init(k, x, train=False), jax.random.key(0))
        n = sum(int(np.prod(l.shape)) for l in
                jax.tree_util.tree_leaves(variables["params"]))
        assert 21_000_000 < n < 23_500_000, n


def _run(devices, mesh_axes, **cfg_kw):
    mesh = build_mesh(mesh_axes, devices)
    cfg = Config(model="vit_tiny", dataset="cifar10", epochs_global=2,
                 epochs_local=1, batch_size=8, limit_train_samples=128,
                 limit_eval_samples=32, compute_dtype="float32",
                 augment=False, aggregation_by="weights", seed=13, **cfg_kw)
    return train_global(cfg, mesh=mesh, progress=False)


@pytest.mark.slow
class TestDriverViT:
    def test_plain_dp_loss_decreases(self, devices):
        res = _run(devices[:2], {"data": 2})
        assert np.isfinite(res["global_train_losses"]).all()
        assert res["global_train_losses"][-1] < res["global_train_losses"][0]

    def test_tensor_parallel_matches_dense(self, devices):
        dense = _run(devices[:2], {"data": 2})
        tp = _run(devices[:4], {"data": 2, "model": 2})
        np.testing.assert_allclose(tp["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)

    def test_pipeline_parallel_runs(self, devices):
        res = _run(devices[:4], {"data": 2, "pipe": 2})
        assert np.isfinite(res["global_train_losses"]).all()

    def test_fsdp_matches_dense(self, devices):
        dense = _run(devices[:2], {"data": 2})
        fsdp = _run(devices[:4], {"data": 2, "fsdp": 2})
        np.testing.assert_allclose(fsdp["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)

    def test_sequence_parallel_rejected(self, devices):
        mesh = build_mesh({"data": 2, "seq": 2}, devices[:4])
        cfg = Config(model="vit_tiny", dataset="cifar10", batch_size=8,
                     limit_train_samples=64, limit_eval_samples=16,
                     augment=False, sequence_parallel="ring")
        with pytest.raises(ValueError, match="token-sequence"):
            train_global(cfg, mesh=mesh, progress=False)
