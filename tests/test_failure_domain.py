"""Unplanned-failure domain (ISSUE 12): buddy-redundant resident shards,
mid-round crash detection + bounded rollback recovery, NaN quarantine.

Three layers, mirroring the elastic suite's structure:

- comms unit tests — the buddy hop's ring copy is bitwise the owner's
  resident row at WIRE-dtype hop cost, the no-redundancy program is
  bitwise-unchanged, ``buddy_restore_rows`` reconstructs a lost span
  without ever reading the dead row, and the NaN/Inf screen quarantines
  + renormalizes identically across all three sync implementations
  (clean rounds bitwise-identical to the unscreened twin);
- chaos grammar — crash/nan events, suffix-misuse rejection, the
  ``--chaos_kinds`` random-mode selection, round-0 target pinning;
- driver e2e — a mid-round crash is detected as the distinct CRASHED
  verdict (a missed round fence: non-finite wall), the round is voided,
  the state rolls back to the boundary snapshot with the crashed
  worker's resident spans reconstructed from its buddy, membership
  re-plans through the PR 8 snapshot path, and the recovered trajectory
  bitwise-matches a fresh twin from the recovery snapshot — sanitized.
  The heavy matrix (topologies x residency x fallback ladder) is
  slow-marked up front.
"""

import numpy as np

import jax
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu import chaos as chaos_lib
from learning_deep_neural_network_in_distributed_computing_environment_tpu import comms
from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh


# ----------------------------------------------------------------------
# Chaos grammar: crash/nan events + --chaos_kinds (ISSUE 12 satellite)
# ----------------------------------------------------------------------

class TestCrashNanGrammar:
    def test_parses_crash_and_nan(self):
        ev = chaos_lib.parse_chaos_spec("crash@3:w1, nan@2:w0")
        assert [(e.kind, e.round, e.worker) for e in ev] == [
            ("nan", 2, 0), ("crash", 3, 1)]

    @pytest.mark.parametrize("bad", [
        "crash@2",            # crash needs a target
        "nan@2",              # nan needs a target
        "crash@2:w1x2",       # xfactor is slow-only
        "crash@2:w1+30",      # +seconds is stall-only
        "nan@2:w1*3",         # *rounds is stall-only
        "crash@0:w1",         # round 0 has no entering boundary
    ])
    def test_suffix_misuse_rejected(self, bad):
        with pytest.raises(ValueError):
            chaos_lib.parse_chaos_spec(bad)

    def test_config_validates_crash_spec_eagerly(self):
        with pytest.raises(ValueError):
            Config(chaos="crash@2:w1x5")
        Config(chaos="crash@2:w1,nan@3:w0")   # valid

    def test_chaos_kinds_validation(self):
        assert Config(chaos_kinds="kill,crash,nan").parse_chaos_kinds() \
            == ("kill", "crash", "nan")
        with pytest.raises(ValueError):
            Config(chaos_kinds="kill,typo")
        with pytest.raises(ValueError):
            Config(chaos_kinds=" , ")

    def test_random_defaults_never_draw_crash_or_nan(self):
        ev = chaos_lib.random_events(seed=3, count=64, epochs_global=10)
        assert ev and all(e.kind in chaos_lib.DEFAULT_RANDOM_KINDS
                          for e in ev)

    def test_random_with_kinds_draws_them_and_pins_targets(self):
        ev = chaos_lib.random_events(seed=3, count=64, epochs_global=10,
                                     kinds=("crash", "nan"))
        assert ev and {e.kind for e in ev} == {"crash", "nan"}
        sched = chaos_lib.ChaosSchedule(ev)
        assert all(e.worker is None for e in sched.events)
        sched.pin_wall_targets(range(4))
        # crash/nan targets pin to round-0 logical ids (a migrated crash
        # target would diverge the fresh twin's recovery), idempotently
        pinned = [e.worker for e in sched.events]
        assert all(w is not None and 0 <= w < 4 for w in pinned)
        sched.pin_wall_targets(range(2))
        assert [e.worker for e in sched.events] == pinned

    def test_perturb_walls_crash_is_nonfinite_once(self):
        sched = chaos_lib.ChaosSchedule(
            chaos_lib.parse_chaos_spec("crash@2:w1"))
        ids = [0, 1, 2, 3]
        w1 = sched.perturb_walls(1, ids, np.ones(4))
        assert np.isfinite(w1).all()
        w2 = sched.perturb_walls(2, ids, np.ones(4))
        assert not np.isfinite(w2[1]) and np.isfinite(w2[[0, 2, 3]]).all()
        # post-recovery roster (worker 1 gone): the re-run of round 2
        # and later rounds resolve no target
        w2b = sched.perturb_walls(2, [0, 2, 3], np.ones(3))
        assert np.isfinite(w2b).all()

    def test_nan_targets_resolve_per_round(self):
        sched = chaos_lib.ChaosSchedule(
            chaos_lib.parse_chaos_spec("nan@2:w1,nan@2:w3,nan@4:w0"))
        assert sched.nan_targets(2, [0, 1, 2, 3]) == [1, 3]
        assert sched.nan_targets(3, [0, 1, 2, 3]) == []
        assert sched.nan_targets(2, [0, 2, 3]) == [3]   # 1 departed
        assert sched.has_kind("nan") and not sched.has_kind("crash")


# ----------------------------------------------------------------------
# Buddy hop (comms): ring copy bitwise, baseline untouched, restore
# ----------------------------------------------------------------------

def _tree(n, seed=0):
    rng = np.random.default_rng(seed)
    return {"a": rng.standard_normal((n, 7, 5)).astype(np.float32),
            "b": rng.standard_normal((n, 13)).astype(np.float32)}


def _tmpl(tree):
    return {k: jax.ShapeDtypeStruct(v.shape[1:], v.dtype)
            for k, v in tree.items()}


class TestBuddyHop:
    @pytest.mark.parametrize("wire", [None, "bfloat16", "int8"])
    def test_buddy_rows_are_ring_predecessors_bitwise(self, mesh8, wire):
        """The buddy hop ppermutes the WIRE-dtype payload and decodes on
        the receiver, so buddy[w] is bitwise the owner (w-1)'s resident
        row on every wire format."""
        import jax.numpy as jnp
        wdt = {"bfloat16": jnp.bfloat16, "int8": jnp.int8}.get(wire)
        tree = _tree(8)
        res = ({k: np.zeros_like(v) for k, v in tree.items()}
               if wire else None)
        run = comms.make_host_sync(mesh8, mode="sharded", how="equal",
                                   wire_dtype=wdt,
                                   param_residency="resident",
                                   redundancy="buddy")
        d = run(tree, res, None)
        resident = jax.device_get(d["out"])
        buddy = jax.device_get(d["buddy"])
        assert resident and set(resident) == set(buddy)
        for name, rows in resident.items():
            np.testing.assert_array_equal(
                np.roll(np.asarray(rows), 1, axis=0),
                np.asarray(buddy[name]["params"]))

    def test_no_redundancy_program_bitwise_unchanged(self, mesh8):
        """Redundancy on must be pure data movement: the resident rows
        (and under EF the residual) are bitwise those of the
        redundancy-off program."""
        import jax.numpy as jnp
        tree = _tree(8, seed=4)
        res = {k: (0.01 * _tree(8, seed=5)[k]).astype(np.float32)
               for k in tree}
        on = comms.make_host_sync(mesh8, mode="sharded", how="equal",
                                  wire_dtype=jnp.bfloat16,
                                  param_residency="resident",
                                  redundancy="buddy")(tree, res, None)
        off_out, off_res = comms.make_host_sync(
            mesh8, mode="sharded", how="equal", wire_dtype=jnp.bfloat16,
            param_residency="resident")(tree, res)
        for name in jax.device_get(off_out):
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(on["out"])[name]),
                np.asarray(jax.device_get(off_out)[name]))
        for k in tree:
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(on["residual"])[k]),
                np.asarray(jax.device_get(off_res)[k]))

    def test_tracker_buddy_rows_are_ring_predecessors(self, mesh8):
        """Gradients mode x sharded placement: the fresh mu/nu shard
        rows ride the same hop."""
        tree = _tree(8, seed=6)
        trk = comms.round_opt_init(_tmpl(tree), 8, placement="sharded")
        trk = jax.tree_util.tree_map(np.asarray, trk)
        run = comms.make_host_sync(mesh8, mode="sharded", how="equal",
                                   track_opt=True, redundancy="buddy")
        d = run(tree, None, trk)
        new_trk = jax.device_get(d["tracker"])
        buddy = jax.device_get(d["buddy"])
        for name in new_trk:
            for m in ("mu", "nu"):
                np.testing.assert_array_equal(
                    np.roll(np.asarray(new_trk[name][m]), 1, axis=0),
                    np.asarray(buddy[name][m]))

    def test_ef_span_buddy_matches_host_derivation(self, mesh8):
        """The residual own-span copy equals ``derive_buddy``'s host
        twin of the fresh residual — the recovery fold's data source."""
        import jax.numpy as jnp
        tree = _tree(8, seed=7)
        res = {k: (0.01 * _tree(8, seed=8)[k]).astype(np.float32)
               for k in tree}
        run = comms.make_host_sync(mesh8, mode="sharded", how="equal",
                                   wire_dtype=jnp.int8,
                                   param_residency="resident",
                                   redundancy="buddy")
        d = run(tree, res, None)
        derived = comms.derive_buddy(
            _tmpl(tree), 8,
            params_resident=jax.tree_util.tree_map(
                np.asarray, jax.device_get(d["out"])),
            residual=jax.tree_util.tree_map(
                np.asarray, jax.device_get(d["residual"])))
        buddy = jax.device_get(d["buddy"])
        for name in derived:
            np.testing.assert_array_equal(
                derived[name]["res"], np.asarray(buddy[name]["res"]))

    def test_buddy_restore_never_reads_the_dead_row(self, mesh8):
        tree = _tree(4)
        run = comms.make_host_sync(
            build_mesh({"data": 4}), mode="sharded", how="equal",
            param_residency="resident", redundancy="buddy")
        d = run(tree, None, None)
        truth = {k: np.asarray(v).copy()
                 for k, v in jax.device_get(d["out"]).items()}
        parts = {"params_resident": {k: v.copy()
                                     for k, v in truth.items()}}
        for k in parts["params_resident"]:
            parts["params_resident"][k][2] = np.nan   # the "lost" row
        patched = comms.buddy_restore_rows(
            parts, jax.device_get(d["buddy"]), [2], _tmpl(tree))
        for k in truth:
            np.testing.assert_array_equal(
                patched["params_resident"][k], truth[k])

    def test_double_fault_raises(self, mesh8):
        tree = _tree(4)
        run = comms.make_host_sync(
            build_mesh({"data": 4}), mode="sharded", how="equal",
            param_residency="resident", redundancy="buddy")
        d = run(tree, None, None)
        parts = {"params_resident": jax.tree_util.tree_map(
            np.asarray, jax.device_get(d["out"]))}
        with pytest.raises(ValueError, match="double fault"):
            comms.buddy_restore_rows(parts, jax.device_get(d["buddy"]),
                                     [2, 3], _tmpl(tree))

    def test_buddy_requires_something_resident(self):
        with pytest.raises(ValueError):
            comms.make_host_sync(build_mesh({"data": 4}), mode="sharded",
                                 redundancy="buddy")
        with pytest.raises(ValueError):
            comms.make_host_sync(build_mesh({"data": 4}), mode="gossip",
                                 topology="ring", redundancy="buddy")

    def test_config_rejects_buddy_without_sharded_engine(self):
        with pytest.raises(ValueError):
            Config(shard_redundancy="buddy", topology="ring")
        with pytest.raises(ValueError):
            Config(shard_redundancy="buddy", sync_mode="dense")


# ----------------------------------------------------------------------
# NaN/Inf integrity screen (comms): quarantine + renormalized blends
# ----------------------------------------------------------------------

SCREEN_MODES = [("sharded", "allreduce"), ("gossip", "ring"),
                ("gossip", "double_ring"), ("dense", "allreduce"),
                ("dense", "ring"), ("dense", "double_ring")]


class TestNanScreen:
    @pytest.mark.parametrize("mode,topology", SCREEN_MODES)
    @pytest.mark.parametrize("how", ["equal", "weighted"])
    def test_clean_round_bitwise_identical_to_unscreened(
            self, mesh8, mode, topology, how):
        tree = _tree(8, seed=11)
        scr = comms.make_host_sync(mesh8, mode=mode, topology=topology,
                                   how=how, screen=True)
        d = scr(tree, None, None, np.zeros(8, bool))
        assert np.all(np.asarray(jax.device_get(d["ok"])) == 1.0)
        plain = comms.make_host_sync(mesh8, mode=mode, topology=topology,
                                     how=how)
        out, _ = plain(tree, None)
        for k in tree:
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(d["out"])[k]),
                np.asarray(jax.device_get(out)[k]))

    def test_sharded_equal_quarantine_renormalizes_over_survivors(
            self, mesh8):
        tree = _tree(8, seed=12)
        tree["a"][3, 0, 0] = np.inf   # a genuinely non-finite contribution
        poison = np.zeros(8, bool)
        poison[5] = True              # plus an injected one
        scr = comms.make_host_sync(mesh8, mode="sharded", how="equal",
                                   screen=True)
        d = scr(tree, None, None, poison)
        okv = np.asarray(jax.device_get(d["ok"])).reshape(-1)
        assert okv.tolist() == [1, 1, 1, 0, 1, 0, 1, 1]
        keep = [0, 1, 2, 4, 6, 7]
        for k in tree:
            expect = np.broadcast_to(tree[k][keep].mean(0),
                                     tree[k].shape)
            np.testing.assert_allclose(
                np.asarray(jax.device_get(d["out"])[k]), expect,
                rtol=1e-6)

    def test_ring_quarantine_keeps_own_value_when_predecessor_poisoned(
            self, mesh8):
        tree = _tree(8, seed=13)
        poison = np.zeros(8, bool)
        poison[2] = True
        scr = comms.make_host_sync(mesh8, mode="gossip", topology="ring",
                                   how="equal", screen=True)
        d = scr(tree, None, None, poison)
        out = jax.device_get(d["out"])
        for k in tree:
            got = np.asarray(out[k])
            # worker 3's predecessor (2) is quarantined: keeps own value
            np.testing.assert_allclose(got[3], tree[k][3], rtol=1e-6)
            # worker 2 itself adopts its valid predecessor's value
            np.testing.assert_allclose(got[2], tree[k][1], rtol=1e-6)
            # an untouched pair blends exactly as before
            np.testing.assert_array_equal(
                got[5], (tree[k][5] + tree[k][4]) / 2.0)

    def test_weighted_quarantined_worker_adopts_valid_consensus(
            self, mesh8):
        tree = _tree(8, seed=14)
        poison = np.zeros(8, bool)
        poison[0] = True
        scr = comms.make_host_sync(mesh8, mode="sharded", how="weighted",
                                   local_weight=0.25, screen=True)
        d = scr(tree, None, None, poison)
        out = jax.device_get(d["out"])
        keep = list(range(1, 8))
        for k in tree:
            got = np.asarray(out[k])
            np.testing.assert_allclose(got[0], tree[k][keep].mean(0),
                                       rtol=1e-5)
            # a valid worker's peer mean excludes the quarantined term
            peers = (tree[k][keep].sum(0) - tree[k][3]) / 6.0
            np.testing.assert_allclose(
                got[3], 0.25 * tree[k][3] + 0.75 * peers, rtol=1e-5)

    def test_quarantined_residual_resets_for_the_round(self, mesh8):
        import jax.numpy as jnp
        tree = _tree(8, seed=15)
        tree["b"][6, :] = np.nan
        res = {k: (0.1 * _tree(8, seed=16)[k]).astype(np.float32)
               for k in tree}
        scr = comms.make_host_sync(mesh8, mode="sharded", how="equal",
                                   wire_dtype=jnp.bfloat16, screen=True)
        d = scr(tree, res, None, np.zeros(8, bool))
        okv = np.asarray(jax.device_get(d["ok"])).reshape(-1)
        assert okv[6] == 0.0
        new_res = jax.device_get(d["residual"])
        # the quarantined worker's stage-1 (contribution) residual
        # resets — but quarantine invalidates its CONTRIBUTION, not its
        # shard-OWNER role, so the stage-2 fold (the survivors' mean's
        # rounding error at the span it owns: bucket offsets 36..41,
        # i.e. inside leaf "b") legitimately remains.  Leaf "a"
        # (offsets 0..34, outside the span) must be exactly zero.
        assert np.all(np.asarray(new_res["a"])[6] == 0.0)
        for k in tree:
            assert np.isfinite(np.asarray(new_res[k])).all()
            assert np.isfinite(
                np.asarray(jax.device_get(d["out"])[k])).all()


# ----------------------------------------------------------------------
# Wire accounting + derived-buddy invariants
# ----------------------------------------------------------------------

class TestBuddyAccounting:
    def test_derive_buddy_none_when_nothing_resident(self):
        tmpl = _tmpl(_tree(4))
        assert comms.derive_buddy(tmpl, 4) is None
        assert comms.derive_buddy(tmpl, 1, params_resident={}) is None

    def test_buddy_wire_bytes_formula(self):
        tmpl = _tmpl(_tree(4))
        leaves = list(jax.tree_util.tree_leaves(tmpl))
        rows = sum(b.padded // 4 for b in comms.bucket_plan(leaves, 4))
        assert comms.buddy_wire_bytes(tmpl, 4) == rows * 4
        assert comms.buddy_wire_bytes(tmpl, 4, wire_dtype="bfloat16") \
            == rows * 2
        assert comms.buddy_wire_bytes(
            tmpl, 4, params=False, tracker=True) == 2 * rows * 4
        assert comms.buddy_wire_bytes(
            tmpl, 4, wire_dtype="int8", ef=True) == rows * 1 + rows * 4
        assert comms.buddy_wire_bytes(tmpl, 1) == 0


# ----------------------------------------------------------------------
# Driver e2e: crash -> rollback -> buddy recovery (simulated N workers)
# ----------------------------------------------------------------------

def _cfg(**kw):
    base = dict(model="mlp", dataset="mnist", epochs_global=4,
                epochs_local=1, batch_size=16, limit_train_samples=400,
                limit_eval_samples=100, compute_dtype="float32",
                augment=False, aggregation_by="weights", seed=1,
                num_workers=4, sync_mode="sharded")
    base.update(kw)
    return Config(**base)


PROBE4 = np.array([1.0, 1.5, 1.0, 2.0])

TAIL_KEYS = ("global_train_losses", "global_val_losses",
             "global_train_accuracies", "global_val_accuracies",
             "step_caps", "shard_sizes")

# logical-id-indexed (the driver maps it onto the live roster): serves
# BOTH membership sizes of the crashed round's two attempts
WALLS4 = lambda e: np.ones(4)


class TestCrashRecovery:
    def test_crash_recovers_from_buddy_and_matches_fresh_twin(self):
        """THE acceptance gate: worker 1 vanishes mid-round-2 (missed
        fence), the driver voids the round, reconstructs its resident
        spans from the buddy, re-plans membership, re-runs round 2 on
        the survivors — recovery_source=buddy, ZERO checkpoint reads —
        and the recovered trajectory bitwise-matches a fresh twin from
        the recovery snapshot.  Sanitized: the recovery is a sanctioned
        reshard window, everything else keeps the zero-retrace budget."""
        kw = dict(chaos="crash@2:w1", sanitize=True)
        full = train_global(_cfg(**kw), progress=False,
                            simulated_durations=PROBE4,
                            simulated_round_durations=WALLS4)
        el = full["elastic"]
        assert el["events"] == [{"round": 2, "kind": "crash", "worker": 1}]
        assert el["crashes"] == 1 and el["recoveries"] == 1
        assert el["recovery_source"] == ["buddy"]
        assert len(el["recovery_ms"]) == 1 and el["recovery_ms"][0] > 0
        assert el["final_worker_ids"] == [0, 2, 3]
        assert full["sync_engine"]["param_residency"] == "resident"
        assert full["sanitize"]["retrace_count"] == 0
        assert full["sanitize"]["transfer_guard_violations"] == 0
        # round 2 was re-run, not skipped: every round reported
        assert len(full["global_train_losses"]) == 4
        snap = el["snapshots"][0]
        assert (snap.epoch, snap.worker_ids) == (2, [0, 2, 3])
        fresh = train_global(_cfg(**kw), progress=False,
                             simulated_durations=PROBE4,
                             simulated_round_durations=WALLS4,
                             elastic_snapshot=snap)
        assert fresh["sanitize"]["retrace_count"] == 0
        for k in TAIL_KEYS:
            assert full[k][2:] == fresh[k], f"results[{k!r}] diverged"

    def test_sync_bytes_carry_the_buddy_hop(self):
        """ISSUE 12 satellite twin of the test_sync accounting case, at
        the driver level: a resident run with redundancy on reports
        baseline + buddy bytes in every round's sync_bytes."""
        on = train_global(_cfg(epochs_global=1), progress=False,
                          simulated_durations=PROBE4,
                          simulated_round_durations=WALLS4)
        off = train_global(_cfg(epochs_global=1, shard_redundancy="off"),
                           progress=False, simulated_durations=PROBE4,
                           simulated_round_durations=WALLS4)
        sb_on = on["round_timings"][0]["sync_bytes"]
        sb_off = off["round_timings"][0]["sync_bytes"]
        assert sb_on > sb_off
        # exact: baseline + one hop of the resident rows (fp32 wire)
        expect = comms.buddy_wire_bytes(
            _state_template(on), 4, bucket_bytes=int(4.0 * (1 << 20)))
        assert sb_on == sb_off + expect, (sb_on, sb_off, expect)


def _state_template(results):
    """Per-worker params ShapeDtypeStructs recovered from a finished
    run's consensus variables."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
        results["variables"]["params"])


class TestNanDriver:
    def test_nan_quarantine_then_escalation(self):
        """nan@1/2:w2 poisons worker 2's contribution twice: each round
        is quarantined (blend renormalized, run stays finite), and the
        second consecutive strike exhausts --chaos_retries -> the worker
        departs at the next boundary through the PR 8 elastic path."""
        res = train_global(
            _cfg(chaos="nan@1:w2,nan@2:w2", chaos_retries=1,
                 epochs_global=5),
            progress=False, simulated_durations=PROBE4,
            simulated_round_durations=WALLS4)
        el = res["elastic"]
        assert el["quarantined_rounds"] == 2
        assert el["events"] == [{"round": 3, "kind": "depart",
                                 "worker": 2}]
        assert el["final_worker_ids"] == [0, 1, 3]
        assert np.isfinite(res["global_train_losses"]).all()


@pytest.mark.slow
class TestCrashRecoverySlow:
    """The full unplanned-failure matrix: topologies x residency x the
    degradation ladder (slow-marked up front, like the PR 8/9/11 e2e
    matrices)."""

    @pytest.mark.parametrize("topology,residency,source", [
        ("allreduce", "auto", "buddy"),        # resident -> buddy
        ("allreduce", "replicated", "snapshot"),  # nothing uniquely held
        ("ring", "auto", "snapshot"),          # gossip: worker-local
        ("double_ring", "auto", "snapshot"),
    ])
    def test_crash_matrix_bitwise_twin(self, topology, residency,
                                       source):
        kw = dict(chaos="crash@2:w1", sanitize=True, topology=topology,
                  param_residency=residency)
        if topology != "allreduce":
            kw.pop("sync_mode", None)
        cfg = _cfg(**kw)
        full = train_global(cfg, progress=False,
                            simulated_durations=PROBE4,
                            simulated_round_durations=WALLS4)
        el = full["elastic"]
        assert el["recovery_source"] == [source], (topology, residency)
        assert el["crashes"] == 1 and el["recoveries"] == 1
        assert full["sanitize"]["retrace_count"] == 0
        snap = el["snapshots"][0]
        fresh = train_global(cfg, progress=False,
                             simulated_durations=PROBE4,
                             simulated_round_durations=WALLS4,
                             elastic_snapshot=snap)
        for k in TAIL_KEYS:
            assert full[k][2:] == fresh[k], f"results[{k!r}] diverged"

    @pytest.mark.parametrize("n", [2, 8])
    def test_worker_counts(self, n):
        """2 workers (crash -> quorum of 1, resident demotes) and 8
        workers, both through the buddy path where anything is
        resident."""
        walls = lambda e: np.ones(n)
        # equal probe: an unequal one drifts the partition sizes toward
        # the measured walls and a step-count change would recompile the
        # round program mid-segment (legitimate, but it would trip the
        # sanitizer's zero-retrace budget for test-config reasons)
        probe = np.ones(n)
        kw = dict(chaos="crash@2:w1", sanitize=True, num_workers=n)
        full = train_global(_cfg(**kw), progress=False,
                            simulated_durations=probe,
                            simulated_round_durations=walls)
        el = full["elastic"]
        assert el["recovery_source"] == ["buddy"]
        assert len(el["final_worker_ids"]) == n - 1
        assert full["sanitize"]["retrace_count"] == 0
        snap = el["snapshots"][0]
        fresh = train_global(_cfg(**kw), progress=False,
                             simulated_durations=probe,
                             simulated_round_durations=walls,
                             elastic_snapshot=snap)
        for k in TAIL_KEYS:
            assert full[k][2:] == fresh[k], f"results[{k!r}] diverged"

    def test_double_fault_falls_back_to_checkpoint(self, tmp_path):
        """Worker AND its ring buddy crash in the same round: the spans
        exist nowhere in memory — the recovery degrades to the newest
        committed checkpoint, logged and counted."""
        kw = dict(chaos="crash@3:w1,crash@3:w2", checkpoint_dir=str(
            tmp_path), checkpoint_every=1, epochs_global=5)
        res = train_global(_cfg(**kw), progress=False,
                           simulated_durations=PROBE4,
                           simulated_round_durations=WALLS4)
        el = res["elastic"]
        assert el["crashes"] == 2 and el["recoveries"] == 1
        assert el["recovery_source"] == ["checkpoint"]
        assert sorted(e["worker"] for e in el["events"]) == [1, 2]
        assert el["final_worker_ids"] == [0, 3]
        assert np.isfinite(res["global_train_losses"]).all()

    def test_redundancy_off_uses_checkpoint(self, tmp_path):
        kw = dict(chaos="crash@3:w1", shard_redundancy="off",
                  checkpoint_dir=str(tmp_path), checkpoint_every=1,
                  epochs_global=5)
        res = train_global(_cfg(**kw), progress=False,
                           simulated_durations=PROBE4,
                           simulated_round_durations=WALLS4)
        assert res["elastic"]["recovery_source"] == ["checkpoint"]
        assert np.isfinite(res["global_train_losses"]).all()

    def test_unrecoverable_without_checkpoint_raises(self):
        kw = dict(chaos="crash@2:w1", shard_redundancy="off")
        with pytest.raises(RuntimeError, match="unrecoverable"):
            train_global(_cfg(**kw), progress=False,
                         simulated_durations=PROBE4,
                         simulated_round_durations=WALLS4)

    def test_crash_composes_with_kill_and_join(self):
        """A cooperative kill, a crash, and a join in one run: the
        rollback recovery and the boundary elastic path share the plan,
        so ids never recycle and every round completes."""
        # logical ids reach 5 (the joiner's fresh id): the wall vector
        # is logical-id-indexed, so it must cover every id ever live
        walls = lambda e: np.ones(6)
        probe = np.array([1.0, 1.5, 1.0, 2.0, 1.2])
        kw = dict(chaos="kill@1:w0,crash@2:w3,join@3", num_workers=5,
                  epochs_global=5)
        res = train_global(_cfg(**kw), progress=False,
                           simulated_durations=probe,
                           simulated_round_durations=walls)
        el = res["elastic"]
        kinds = [(e["kind"], e["round"]) for e in el["events"]]
        assert kinds == [("kill", 1), ("crash", 2), ("join", 3)]
        assert el["final_worker_ids"] == [1, 2, 4, 5]   # 5 = fresh id
        assert np.isfinite(res["global_train_losses"]).all()

    def test_gradients_tracker_buddy_recovery(self):
        """Gradients mode x sharded placement: the crashed worker's
        round_opt moment rows are the uniquely-held state — recovered
        from the tracker's buddy rows."""
        kw = dict(chaos="crash@2:w1", aggregation_by="gradients",
                  sanitize=True)
        full = train_global(_cfg(**kw), progress=False,
                            simulated_durations=PROBE4,
                            simulated_round_durations=WALLS4)
        el = full["elastic"]
        assert el["recovery_source"] == ["buddy"]
        assert full["sanitize"]["retrace_count"] == 0
        snap = el["snapshots"][0]
        fresh = train_global(_cfg(**kw), progress=False,
                             simulated_durations=PROBE4,
                             simulated_round_durations=WALLS4,
                             elastic_snapshot=snap)
        for k in TAIL_KEYS:
            assert full[k][2:] == fresh[k], f"results[{k!r}] diverged"

    def test_random_mode_with_crash_kinds_completes(self):
        res = train_global(
            _cfg(chaos="random", chaos_kinds="crash,nan", chaos_events=2,
                 chaos_seed=7, epochs_global=5),
            progress=False, simulated_durations=PROBE4,
            simulated_round_durations=WALLS4)
        assert np.isfinite(res["global_train_losses"]).all()

    def test_compressed_wire_crash_recovery_bitwise(self):
        """int8 wire + EF: the buddy copy decodes the permuted wire
        payload, so recovery is exact even on the compressed wire, and
        the twin gate holds."""
        kw = dict(chaos="crash@2:w1", sync_dtype="int8",
                  sync_compression="ef", sanitize=True)
        full = train_global(_cfg(**kw), progress=False,
                            simulated_durations=PROBE4,
                            simulated_round_durations=WALLS4)
        el = full["elastic"]
        assert el["recovery_source"] == ["buddy"]
        snap = el["snapshots"][0]
        fresh = train_global(_cfg(**kw), progress=False,
                             simulated_durations=PROBE4,
                             simulated_round_durations=WALLS4,
                             elastic_snapshot=snap)
        for k in TAIL_KEYS:
            assert full[k][2:] == fresh[k], f"results[{k!r}] diverged"
