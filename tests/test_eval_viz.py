"""Evaluator metric parity vs sklearn + viz file outputs + CLI smoke."""

import os

import numpy as np
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu.eval import _prf, evaluate
from learning_deep_neural_network_in_distributed_computing_environment_tpu import viz


class TestPRF:
    @pytest.mark.parametrize("average", ["macro", "weighted", "micro"])
    def test_matches_sklearn(self, average):
        sklearn = pytest.importorskip("sklearn.metrics")
        rng = np.random.default_rng(0)
        labels = rng.integers(0, 10, 500)
        preds = np.where(rng.random(500) < 0.6, labels,
                         rng.integers(0, 10, 500))
        p, r, f = _prf(labels, preds, 10, average)
        np.testing.assert_allclose(
            p, sklearn.precision_score(labels, preds, average=average),
            rtol=1e-9)
        np.testing.assert_allclose(
            r, sklearn.recall_score(labels, preds, average=average),
            rtol=1e-9)
        np.testing.assert_allclose(
            f, sklearn.f1_score(labels, preds, average=average), rtol=1e-9)

    def test_missing_class_zero_division(self):
        # class never predicted: sklearn zero_division=0 semantics
        labels = np.array([0, 0, 1, 1])
        preds = np.array([0, 0, 0, 0])
        p, r, f = _prf(labels, preds, 2, "macro")
        assert 0 <= p <= 1 and 0 <= f <= 1


class TestEvaluate:
    def test_full_pass_with_tail_padding(self):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
        import jax, jax.numpy as jnp
        model = get_model("mlp", num_classes=10, hidden=8)
        variables = model.init(jax.random.key(0), jnp.zeros((1, 28, 28, 1)),
                               train=False)
        rng = np.random.default_rng(0)
        images = rng.normal(size=(70, 28, 28, 1)).astype(np.float32)
        labels = rng.integers(0, 10, 70).astype(np.int32)
        loss, acc, preds, labs, metrics = evaluate(
            model, variables, images, labels, batch_size=32, verbose=False)
        assert len(preds) == 70  # tail batch unpadded
        assert 0 <= acc <= 100 and np.isfinite(loss)
        assert set(metrics) >= {"f1_macro", "f1_weighted", "f1_micro"}


class TestViz:
    def test_all_six_files_written(self, tmp_path):
        out = str(tmp_path / "Graphs")
        results = {
            "global_train_losses": [1.0, 0.5],
            "global_train_accuracies": [50.0, 80.0],
            "global_val_losses": [1.1, 0.6],
            "global_val_accuracies": [48.0, 75.0],
            "worker_specific_train_losses": [1.0, 0.8, 0.6, 0.5],
            "worker_specific_train_accuracies": [50, 60, 70, 80],
            "worker_specific_val_losses": [1.1, 0.9, 0.7, 0.6],
            "worker_specific_val_accuracies": [45, 55, 65, 75],
            "all_workers_losses": [[1.0, 0.5], [0.9, 0.4]] + [[0.8]] * 6,
            "all_epochs_losses": [[1.0, 0.9], [0.5, 0.4]],
            "global_epoch_losses": [[1.0, 0.9, 0.5, 0.4]],
            "global_epoch_accuracies": [[50.0, 60.0]],
        }
        viz.write_all(results, epochs_global=2, epochs_local=2,
                      output_folder=out)
        expected = [
            "loss_distribution_by_worker.png",
            "loss_distribution_per_epoch.png",
            "loss_distribution_per_epoch_global.png",
            "accuracy_distribution_per_epoch_global.png",
            "training_metrics.png",
            "training_metrics_0.png",
        ]
        for name in expected:  # reference filenames (vizualizator.py)
            assert os.path.exists(os.path.join(out, name)), name

    def test_empty_worker_losses_do_not_crash(self, tmp_path):
        viz.plot_loss_distribution_by_worker([[], [1.0]], str(tmp_path))
        assert os.path.exists(tmp_path / "loss_distribution_by_worker.png")
