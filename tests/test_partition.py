"""Partition-math parity tests (reference semantics cited per test)."""

import numpy as np
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu.data import (
    budget_from_time_limit,
    contiguous_partition,
    efficiency_ratios,
    fixed_classes_for_rank,
    PackBufferPool,
    pack_shard,
    pack_window,
    repartition,
    skew_partition,
    skew_repartition,
    step_budget,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.data.sources import (
    load_dataset,
    train_val_split,
)


class TestEfficiencyRatios:
    def test_direct_matches_reference_formula(self):
        # ref: ratio_i = duration_i / sum (dataloader.py:149-151)
        d = np.array([1.0, 2.0, 3.0, 4.0])
        r = efficiency_ratios(d, "direct")
        np.testing.assert_allclose(r, d / d.sum())

    def test_inverse_gives_fast_workers_more(self):
        r = efficiency_ratios(np.array([1.0, 2.0]), "inverse")
        assert r[0] > r[1]
        np.testing.assert_allclose(r.sum(), 1.0)

    def test_uniform(self):
        r = efficiency_ratios(np.array([5.0, 1.0, 3.0]), "uniform")
        np.testing.assert_allclose(r, [1 / 3] * 3)


class TestContiguousPartition:
    def test_slice_sizes_proportional(self):
        # ref: num = int(total * ratio), contiguous (dataloader.py:53-75)
        parts = contiguous_partition(100, np.array([0.1, 0.2, 0.3, 0.4]))
        assert [len(p) for p in parts] == [10, 20, 30, 40]
        assert parts[1][0] == 10 and parts[2][0] == 30

    def test_floor_leaves_tail_unassigned_like_reference(self):
        parts = contiguous_partition(10, np.array([0.33, 0.33, 0.34]))
        assert [len(p) for p in parts] == [3, 3, 3]  # int() floors; 1 unused

    def test_disjoint(self):
        parts = contiguous_partition(1000, np.array([0.25] * 4))
        allidx = np.concatenate(parts)
        assert len(np.unique(allidx)) == len(allidx)


class TestRepartition:
    def test_sizes_and_mix(self):
        # ref dataloader.py:77-104: size = int(total*ratio), prev/next split
        rng = np.random.default_rng(0)
        prev = np.arange(100)
        out = repartition(1000, prev, 0.1, 0.5, 0.5, rng)
        assert len(out) == 100
        n_from_prev = np.isin(out[:50], prev).sum()
        assert n_from_prev == 50  # first half drawn from prev indices

    def test_without_replacement_unique(self):
        rng = np.random.default_rng(1)
        out = repartition(500, np.arange(50), 0.1, 0.5, 0.5, rng, replace=False)
        assert len(np.unique(out)) == len(out)

    def test_with_replacement_allowed_duplicates(self):
        # disbalanced variants sample with replacement (ref :123,129)
        rng = np.random.default_rng(2)
        out = repartition(100, np.arange(10), 0.9, 0.5, 0.5, rng, replace=True)
        assert len(out) == 90  # duplicates permitted, size preserved


class TestDisbalanced:
    def test_fixed_classes_formula(self):
        # ref: [(rank*2)%10, (rank*2+1)%10] (Disbalanced .../dataloader.py:77-78)
        assert fixed_classes_for_rank(0) == [0, 1]
        assert fixed_classes_for_rank(4) == [8, 9]
        assert fixed_classes_for_rank(5) == [0, 1]  # wraps mod 10

    def test_skew_partition_reaches_ratio(self):
        rng = np.random.default_rng(0)
        labels = np.tile(np.arange(10), 100)  # 1000 samples, balanced
        base = np.arange(200)
        out = skew_partition(labels, base, [0, 1], 0.5, rng)
        assert len(out) == len(base)
        frac = np.isin(labels[out], [0, 1]).mean()
        assert frac == pytest.approx(0.5, abs=0.01)

    def test_skew_repartition_maintains_ratio(self):
        rng = np.random.default_rng(0)
        labels = np.tile(np.arange(10), 100)
        fresh = repartition(1000, np.arange(100), 0.2, 0.5, 0.5, rng,
                            replace=True)
        out = skew_repartition(labels, fresh, [2, 3], 0.5, rng)
        assert len(out) == len(fresh)
        frac = np.isin(labels[out], [2, 3]).mean()
        assert frac >= 0.49

    def test_skew_noop_when_already_skewed(self):
        rng = np.random.default_rng(0)
        labels = np.zeros(100, np.int64)  # everything class 0
        out = skew_repartition(labels, np.arange(50), [0, 1], 0.5, rng)
        assert sorted(out) == list(range(50))


class TestStepBudget:
    def test_max_over_workers(self):
        assert step_budget([100, 230, 64], 64) == 4  # ceil(230/64)

    def test_time_limit_caps_budget(self):
        # straggler protocol as a budget (SURVEY.md 2.5.4 redesign)
        assert budget_from_time_limit(100, probe_sec_per_batch=1.0,
                                      time_limit=60.0) == 60
        assert budget_from_time_limit(10, 1.0, 60.0) == 10

    def test_pack_shard_masks_padding(self):
        imgs = np.arange(20, dtype=np.float32).reshape(20, 1, 1, 1)
        labels = np.arange(20) % 3
        x, y, m = pack_shard(imgs, labels, np.arange(10), batch_size=4,
                             num_steps=3)
        assert x.shape == (3, 4, 1, 1, 1)
        assert m.sum() == 10  # 10 real examples, 2 masked pads
        assert m[2, 2] == 0 and m[2, 1] == 1


class TestPackBuffers:
    """Double-buffered host staging (ISSUE 2 satellite: np.take(out=))."""

    def test_pack_window_out_matches_fresh_alloc(self):
        rng = np.random.default_rng(0)
        imgs = rng.normal(size=(30, 2, 2, 3)).astype(np.float32)
        labels = rng.integers(0, 5, (30, 7)).astype(np.int64)  # token task
        idx = rng.permutation(30)[:10]
        ref = pack_window(imgs, labels, idx, batch_size=4, start_step=0,
                          num_steps=3)
        bufs = (np.empty((3, 4, 2, 2, 3), np.float32),
                np.empty((3, 4, 7), np.int64),
                np.empty((3, 4), np.float32))
        out = pack_window(imgs, labels, idx, batch_size=4, start_step=0,
                          num_steps=3, out=bufs)
        for o, b, r in zip(out, bufs, ref):
            assert o is b  # filled in place, no fresh allocation
            np.testing.assert_array_equal(o, r)

    def test_pack_window_out_into_stacked_worker_slice(self):
        # the driver packs each worker into a leading-axis slice of one
        # contiguous [N, S, B, ...] stack — the reshape inside must view
        imgs = np.arange(40, dtype=np.float32).reshape(40, 1)
        labels = np.arange(40)
        stack = np.zeros((2, 3, 4, 1), np.float32)
        ystack = np.zeros((2, 3, 4), np.int64)
        mstack = np.zeros((2, 3, 4), np.float32)
        for i, idx in enumerate((np.arange(10), np.arange(10, 22))):
            pack_window(imgs, labels, idx, 4, 0, 3,
                        out=(stack[i], ystack[i], mstack[i]))
        ref0 = pack_window(imgs, labels, np.arange(10), 4, 0, 3)
        np.testing.assert_array_equal(stack[0], ref0[0])
        np.testing.assert_array_equal(mstack[1], np.ones((3, 4)))

    def test_pool_rotates_two_buffers_per_key(self):
        pool = PackBufferPool()
        a = pool.take("x", (4, 2), np.float32)
        b = pool.take("x", (4, 2), np.float32)
        assert a is not b
        assert pool.take("x", (4, 2), np.float32) is a  # round r+2 reuses r
        assert pool.take("x", (4, 2), np.float32) is b
        # a shape change (step budget moved) retires the slot
        c = pool.take("x", (6, 2), np.float32)
        assert c.shape == (6, 2) and c is not a and c is not b
        # distinct keys never share buffers
        assert pool.take("y", (4, 2), np.float32) is not a


class TestSources:
    def test_synthetic_cifar_learnable_structure(self):
        train, test = load_dataset("cifar10", data_dir="/nonexistent",
                                   limit_train=2000, limit_test=400)
        assert train.images.shape == (2000, 32, 32, 3)
        assert test.num_classes == 10
        # normalized with train stats
        assert abs(train.images.mean()) < 0.05
        # class structure: per-class means differ (nearest-centroid beats chance)
        cents = np.stack([train.images[train.labels == c].mean(0)
                          for c in range(10)])
        d = ((test.images[:, None] - cents[None]) ** 2).sum((2, 3, 4))
        acc = (d.argmin(1) == test.labels).mean()
        assert acc > 0.5

    def test_train_val_split(self):
        train, _ = load_dataset("cifar10", data_dir="/nonexistent",
                                limit_train=1000, limit_test=10)
        tr, va = train_val_split(train, 0.2, seed=0)
        assert len(tr) == 800 and len(va) == 200


class TestMnistIdxLoader:
    def test_reads_idx_files_and_falls_back(self, tmp_path):
        """The real-MNIST backend parses standard IDX files (written here
        byte-for-byte per the spec) and load_dataset falls back to
        synthetic when they are absent."""
        import gzip
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.data.sources import (
            _mnist_real, load_dataset)

        raw = tmp_path / "MNIST" / "raw"
        raw.mkdir(parents=True)
        rng = np.random.default_rng(0)

        def write_idx(name, arr, gz=False):
            dims = b"".join(int(d).to_bytes(4, "big") for d in arr.shape)
            payload = (b"\x00\x00\x08" + bytes([arr.ndim]) + dims
                       + arr.astype(np.uint8).tobytes())
            p = raw / (name + (".gz" if gz else ""))
            with (gzip.open(p, "wb") if gz else open(p, "wb")) as f:
                f.write(payload)

        xtr = rng.integers(0, 256, (6, 28, 28))
        ytr = rng.integers(0, 10, (6,))
        xte = rng.integers(0, 256, (4, 28, 28))
        yte = rng.integers(0, 10, (4,))
        write_idx("train-images-idx3-ubyte", xtr)
        write_idx("train-labels-idx1-ubyte", ytr)
        write_idx("t10k-images-idx3-ubyte", xte, gz=True)  # mixed gz/raw
        write_idx("t10k-labels-idx1-ubyte", yte, gz=True)

        got = _mnist_real(str(tmp_path))
        assert got is not None
        gxtr, gytr, gxte, gyte = got
        np.testing.assert_allclose(gxtr[..., 0] * 255.0, xtr, atol=1e-4)
        np.testing.assert_array_equal(gytr, ytr)
        np.testing.assert_allclose(gxte[..., 0] * 255.0, xte, atol=1e-4)
        np.testing.assert_array_equal(gyte, yte)

        train, test = load_dataset("mnist", data_dir=str(tmp_path))
        assert len(train) == 6 and len(test) == 4

        # absent files -> synthetic fallback with the requested limits
        train, test = load_dataset("mnist", data_dir=str(tmp_path / "nope"),
                                   limit_train=32, limit_test=8)
        assert len(train) == 32 and len(test) == 8


class TestCifarPickleLoader:
    def test_reads_pickle_batches_end_to_end(self, tmp_path):
        """The real-CIFAR-10 backend parses the standard python pickle
        batches (fabricated here in the exact on-disk format: bytes keys,
        [N, 3072] uint8 rows in CHW order) — VERDICT r3 weak #4: this was
        the flagship dataset's only untested code path."""
        import pickle
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.data.sources import (
            _cifar10_real, load_dataset)

        base = tmp_path / "cifar-10-batches-py"
        base.mkdir(parents=True)
        rng = np.random.default_rng(0)

        def write_batch(name, n):
            imgs = rng.integers(0, 256, (n, 32, 32, 3)).astype(np.uint8)
            rows = imgs.transpose(0, 3, 1, 2).reshape(n, 3072)  # CHW rows
            labels = rng.integers(0, 10, n).astype(int).tolist()
            with open(base / name, "wb") as f:
                pickle.dump({b"data": rows, b"labels": labels,
                             b"batch_label": name.encode()}, f)
            return imgs, labels

        per = 6
        train_imgs, train_labels = [], []
        for i in range(1, 6):
            imgs, labels = write_batch(f"data_batch_{i}", per)
            train_imgs.append(imgs)
            train_labels.extend(labels)
        test_imgs, test_labels = write_batch("test_batch", 4)

        got = _cifar10_real(str(tmp_path))
        assert got is not None
        xtr, ytr, xte, yte = got
        # HWC layout, [0,1] floats, batches concatenated in order
        assert xtr.shape == (5 * per, 32, 32, 3) and xtr.dtype == np.float32
        np.testing.assert_allclose(
            xtr * 255.0, np.concatenate(train_imgs), atol=1e-4)
        np.testing.assert_array_equal(ytr, train_labels)
        np.testing.assert_allclose(xte * 255.0, test_imgs, atol=1e-4)
        np.testing.assert_array_equal(yte, test_labels)

        # load_dataset prefers the real binaries and normalizes with
        # train-set stats
        train, test = load_dataset("cifar10", data_dir=str(tmp_path))
        assert len(train) == 5 * per and len(test) == 4
        assert abs(float(train.images.mean())) < 1e-5
        assert train.num_classes == 10

    def test_real_cifar_end_to_end_round(self, tmp_path, mesh8):
        """One full train_global round on fabricated real-CIFAR binaries:
        the real-data path drives the same engine the synthetic path
        does."""
        import pickle
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global

        base = tmp_path / "cifar-10-batches-py"
        base.mkdir(parents=True)
        rng = np.random.default_rng(1)
        for name, n in [(f"data_batch_{i}", 32) for i in range(1, 6)] + [
                ("test_batch", 16)]:
            rows = rng.integers(0, 256, (n, 3072)).astype(np.uint8)
            with open(base / name, "wb") as f:
                pickle.dump({b"data": rows,
                             b"labels": rng.integers(0, 10, n).tolist()}, f)

        cfg = Config(model="mlp", dataset="cifar10",
                     data_dir=str(tmp_path), epochs_global=1, epochs_local=1,
                     batch_size=8, num_workers=8, augment=False,
                     compute_dtype="float32")
        out = train_global(cfg, mesh=mesh8, progress=False)
        assert len(out["global_train_losses"]) == 1
        assert np.isfinite(out["global_train_losses"][0])
