"""Streamed input pipeline (VERDICT r1 'Next' #7).

The streamed round must be numerically EQUIVALENT to the whole-round
program (same step bodies, same RNG stream), while only ever materializing
one fixed-shape window per worker on the host.
"""

import numpy as np
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.data.partition import (
    pack_shard,
    pack_window,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global


class TestPackWindow:
    def test_windows_tile_the_shard(self):
        rng = np.random.default_rng(0)
        images = rng.normal(size=(50, 4, 4, 1)).astype(np.float32)
        labels = rng.integers(0, 10, 50).astype(np.int32)
        idx = rng.permutation(50)[:37]
        whole = pack_shard(images, labels, idx, batch_size=5, num_steps=10)
        w1 = pack_window(images, labels, idx, 5, 0, 4)
        w2 = pack_window(images, labels, idx, 5, 4, 4)
        w3 = pack_window(images, labels, idx, 5, 8, 2)
        for k in range(3):
            np.testing.assert_array_equal(
                whole[k], np.concatenate([w1[k], w2[k], w3[k]]))

    def test_empty_shard(self):
        images = np.zeros((10, 2, 2, 1), np.float32)
        labels = np.zeros(10, np.int32)
        x, y, m = pack_window(images, labels, np.array([], np.int64), 2, 3, 2)
        assert x.shape == (2, 2, 2, 2, 1) and (m == 0).all()


class TestStreamedRound:
    def _cfg(self, **kw):
        base = dict(model="mlp", dataset="mnist", epochs_global=2,
                    epochs_local=2, batch_size=16, limit_train_samples=800,
                    limit_eval_samples=100, compute_dtype="float32",
                    augment=False, aggregation_by="weights", seed=1)
        base.update(kw)
        return Config(**base)

    def test_matches_whole_round_exactly(self, mesh8):
        # pin the measured-wall straggler feedback so both runs see the
        # same per-round durations (wall clocks differ run to run)
        walls = lambda e: np.ones(8)
        dense = train_global(self._cfg(), mesh=mesh8, progress=False,
                             simulated_round_durations=walls)
        streamed = train_global(self._cfg(stream_chunk_steps=2), mesh=mesh8,
                                progress=False,
                                simulated_round_durations=walls)
        # identical step bodies + identical RNG stream => same numbers
        np.testing.assert_allclose(streamed["global_train_losses"],
                                   dense["global_train_losses"], rtol=1e-5)
        np.testing.assert_allclose(streamed["global_val_accuracies"],
                                   dense["global_val_accuracies"], rtol=1e-5)
        for i in range(8):
            np.testing.assert_allclose(streamed["all_workers_losses"][i],
                                       dense["all_workers_losses"][i],
                                       rtol=1e-5)

    def test_streamed_with_augment_learns(self, mesh8):
        res = train_global(self._cfg(augment=True, stream_chunk_steps=4),
                           mesh=mesh8, progress=False)
        assert res["global_train_losses"][-1] < res["global_train_losses"][0]

    def test_streamed_disbalanced_runs(self, mesh8):
        res = train_global(
            self._cfg(data_mode="disbalanced", stream_chunk_steps=3),
            mesh=mesh8, progress=False)
        assert np.isfinite(res["global_train_losses"]).all()

    @pytest.mark.slow
    def test_streamed_with_tensor_parallel(self, devices):
        """The streamed round must compose with TP param specs (the inner
        carry uses the sharded state specs) and match the packed TP round."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        kw = dict(model="bert_tiny", dataset="synthetic_mlm",
                  epochs_global=2, epochs_local=1, batch_size=8,
                  limit_train_samples=128, limit_eval_samples=32,
                  compute_dtype="float32", augment=False,
                  aggregation_by="weights", seed=11)
        mesh = build_mesh({"data": 2, "model": 2}, devices[:4])
        packed = train_global(Config(**kw), mesh=mesh, progress=False)
        streamed = train_global(Config(stream_chunk_steps=2, **kw),
                                mesh=mesh, progress=False)
        np.testing.assert_allclose(streamed["global_train_losses"],
                                   packed["global_train_losses"], rtol=1e-5)

    @pytest.mark.slow
    def test_streamed_with_fsdp(self, devices):
        """The streamed round must compose with ZeRO-3 shards (the inner
        carry and chunk programs use the fsdp specs, params gathered
        per step) and match the packed FSDP round."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        kw = dict(epochs_local=1, batch_size=8, limit_train_samples=160,
                  limit_eval_samples=32, seed=12)
        mesh = build_mesh({"data": 2, "fsdp": 2}, devices[:4])
        walls = lambda e: np.ones(2)
        packed = train_global(self._cfg(**kw), mesh=mesh, progress=False,
                              simulated_round_durations=walls)
        streamed = train_global(self._cfg(stream_chunk_steps=2, **kw),
                                mesh=mesh, progress=False,
                                simulated_round_durations=walls)
        np.testing.assert_allclose(streamed["global_train_losses"],
                                   packed["global_train_losses"], rtol=1e-5)
