"""Memory-tier engine tests (ISSUE 15).

Three surfaces:

1. **Named activations** — every scanned family's block emits the
   ``checkpoint_name`` labels in ``models.REMAT_NAMES`` (visible in the
   jaxpr), and the ``save_names:``/``offload_names:`` policy spellings
   resolve/validate/demote correctly;
2. **Bitwise gate** — remat policy NEVER changes math: fp32 training
   trajectories are bitwise-identical across ALL policies at engine
   level (tier-1) and through the sanitized driver (slow-marked, the
   tier-1 wall hygiene rule for new e2e cases);
3. **Compiled-memory observability** — ``memory_analysis`` temp bytes
   order monotonically down the policy ladder, ``TrackedProgram``
   retains executables without double-compiling, and the uniform
   ``results["memory"]`` row is emitted on every run with exact
   resident-state accounting.

Honors ``JAX_GRAFT_TEST_COMPILE_CACHE`` (conftest arms it; nothing here
disables the session cache).
"""

from __future__ import annotations

import functools as ft
import logging
import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu import (
    compat,
    probe,
    train as train_lib,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import (
    REMAT_NAMES,
    get_model,
    remat_name_vocab,
)

VOCAB, B, L_SEQ = 97, 4, 16

ALL_POLICIES = ("none", "dots_saveable", "save_names:attn_out",
                "save_names:attn_out,block_out", "offload_names:attn_out",
                "everything")


def _token_fixture(seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.integers(0, VOCAB, (B, L_SEQ)), jnp.int32)
    y = jnp.asarray(rng.integers(0, VOCAB, (B, L_SEQ)), jnp.int32)
    return x, y


def _grad_jaxpr(model, x):
    def loss(p):
        out = model.apply({"params": p}, x, train=True)
        if isinstance(out, tuple):
            out = out[0]
        return jnp.sum(out.astype(jnp.float32))
    params = jax.eval_shape(
        lambda k: model.init(k, x, train=False), jax.random.key(0))
    params = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), params)["params"]
    return str(jax.make_jaxpr(jax.grad(loss))(params))


class TestNamedActivations:
    """The vocabulary contract: names present in the jaxpr for every
    scanned family, exactly as ``remat_name_vocab`` promises."""

    @pytest.mark.parametrize("name,shape,extra", [
        ("bert_tiny", (L_SEQ,), {}),
        ("gpt_tiny", (L_SEQ,), {}),
        ("llama_tiny", (L_SEQ,), {}),
        ("vit_tiny", (32, 32, 3), {}),
        ("gpt_tiny", (L_SEQ,), {"num_experts": 2}),
    ])
    def test_names_present_in_jaxpr(self, name, shape, extra):
        kw = dict(num_classes=VOCAB, scan_layers=True, **extra)
        if len(shape) == 1:
            if not name.startswith("llama"):   # RoPE: no position table
                kw["max_len"] = L_SEQ
            x = jnp.zeros((B, *shape), jnp.int32)
        else:
            kw.pop("num_classes")
            kw["num_classes"] = 10
            x = jnp.zeros((B, *shape), jnp.float32)
        model = get_model(name, **kw)
        jpr = _grad_jaxpr(model, x)
        # the name primitive prints as ``name[name=<label>]`` — pjit's
        # unrelated ``pjit[name=...]`` params must not match
        emitted = set(re.findall(r"name\[name=(\w+)\]", jpr))
        vocab = set(remat_name_vocab(name, extra.get("num_experts", 0)))
        assert vocab <= emitted, (name, vocab - emitted)
        # and nothing outside the closed vocabulary (the R6 contract)
        assert emitted <= set(REMAT_NAMES), emitted - set(REMAT_NAMES)

    def test_vocab_registry(self):
        assert remat_name_vocab("gpt_tiny") == (
            "attn_out", "mlp_out", "block_out")
        assert remat_name_vocab("llama_tiny", 4)[-1] == "moe_dispatch"
        assert remat_name_vocab("mlp") == ()
        assert remat_name_vocab("enhanced_cnn", 2) == ()


class TestPolicyResolution:
    def test_split_spellings(self):
        assert compat.split_remat_policy("none") == ("none", ())
        assert compat.split_remat_policy("save_names:a,b,a") == (
            "save_names", ("a", "b"))
        with pytest.raises(ValueError, match="at least one"):
            compat.split_remat_policy("offload_names:")
        with pytest.raises(ValueError, match="must start with"):
            compat.split_remat_policy("keep_names:a")
        with pytest.raises(ValueError, match="must be one of"):
            compat.split_remat_policy("sometimes")

    def test_config_validates_names_eagerly(self):
        # valid spellings construct
        Config(model="gpt_tiny", remat_policy="save_names:attn_out")
        Config(model="gpt_tiny", num_experts=2,
               remat_policy="offload_names:moe_dispatch")
        # unknown name: the error lists the family's emitted vocabulary
        with pytest.raises(ValueError,
                           match=r"attn_typo.*attn_out.*block_out"):
            Config(model="gpt_tiny", remat_policy="save_names:attn_typo")
        # moe_dispatch without experts is not emitted
        with pytest.raises(ValueError, match="moe_dispatch"):
            Config(model="gpt_tiny",
                   remat_policy="save_names:moe_dispatch")
        # non-attention family has no scanned block path at all
        with pytest.raises(ValueError, match="no scanned block"):
            Config(model="mlp", remat_policy="save_names:attn_out")

    def test_named_policy_without_layer_scan_keeps_rejection(self):
        cfg = Config(model="gpt_tiny", dataset="synthetic_lm",
                     layer_scan="off",
                     remat_policy="save_names:attn_out",
                     epochs_global=1, epochs_local=1, batch_size=4,
                     limit_train_samples=16, limit_eval_samples=8,
                     compute_dtype="float32", augment=False)
        with pytest.raises(ValueError, match="scanned layer"):
            train_global(cfg, progress=False)

    def test_save_names_policy_resolves(self):
        pol = compat.checkpoint_policy("save_names:attn_out,mlp_out")
        assert callable(pol)

    def test_offload_demotes_with_logged_reason(self, caplog):
        if compat.host_offload_supported():
            pytest.skip("backend has pinned_host — no demotion here")
        names = ("block_out", "mlp_out")   # unique set => fresh log
        compat._OFFLOAD_DEMOTIONS_LOGGED.discard(names)
        with caplog.at_level(logging.INFO):
            pol = compat.checkpoint_policy("offload_names:block_out,mlp_out")
        assert callable(pol)
        assert any("demoted to save_names" in r.message
                   and "pinned_host" in r.message
                   for r in caplog.records), caplog.text

    def test_base_spellings_unchanged(self):
        for name in ("dots_saveable", "everything"):
            compat.checkpoint_policy(name)
        with pytest.raises(ValueError):
            compat.checkpoint_policy("none")


def _make_step(policy, depth=2):
    model = get_model("gpt_tiny", num_classes=VOCAB, num_layers=depth,
                      max_len=L_SEQ, scan_layers=True,
                      remat_policy=None if policy == "none" else policy)
    x, y = _token_fixture()
    tx = optax.adam(1e-3)

    def loss_fn(p):
        out = model.apply({"params": p}, x, train=True)
        return train_lib.softmax_cross_entropy(out, y).mean()

    @ft.partial(jax.jit, donate_argnums=0)
    def step(state):
        params, opt_state = state
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, new_opt = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), new_opt), loss

    def init():
        params = jax.jit(
            lambda k: model.init(k, x, train=False))(
                jax.random.key(3))["params"]
        return (params, jax.jit(tx.init)(params))

    return step, init


class TestBitwiseAcrossPolicies:
    """The tentpole gate at engine level: remat policy never changes
    math — 3 fp32 Adam steps land bit-identical params and losses on
    every policy arm, including the demoted offload arm."""

    def test_fp32_trajectory_bitwise_all_policies(self):
        finals = {}
        for policy in ALL_POLICIES:
            step, init = _make_step(policy)
            state = init()
            losses = []
            for _ in range(3):
                state, loss = step(state)
                losses.append(np.asarray(loss).copy())
            finals[policy] = (jax.tree_util.tree_leaves(
                jax.device_get(state[0])), losses)
        base_leaves, base_losses = finals["none"]
        for policy, (leaves, losses) in finals.items():
            assert all(np.array_equal(a, b)
                       for a, b in zip(base_leaves, leaves)), policy
            assert all(np.array_equal(a, b)
                       for a, b in zip(base_losses, losses)), policy


# sanitized driver-level matrix: new e2e driver cases ride the slow tier
# up front (ROADMAP tier-1 wall hygiene)
@pytest.mark.slow
class TestDriverBitwiseSanitized:
    DRIVER_KW = dict(
        model="gpt_tiny", dataset="synthetic_lm", epochs_global=2,
        epochs_local=1, batch_size=4, limit_train_samples=64,
        limit_eval_samples=16, compute_dtype="float32", augment=False,
        aggregation_by="weights", sanitize=True, seed=11)

    def _run(self, policy):
        mesh = build_mesh({"data": 2}, devices=jax.devices()[:2])
        res = train_global(Config(remat_policy=policy, **self.DRIVER_KW),
                           mesh=mesh, progress=False)
        leaves = jax.tree_util.tree_leaves(
            jax.device_get(res["variables"]["params"]))
        return res, leaves

    def test_sanitized_driver_bitwise_across_policies(self):
        base, base_leaves = self._run("none")
        assert base["sanitize"]["retrace_count"] == 0
        for policy in ("dots_saveable", "save_names:attn_out",
                       "offload_names:attn_out,mlp_out", "everything"):
            res, leaves = self._run(policy)
            assert res["sanitize"] == base["sanitize"], policy
            assert res["global_train_losses"] == \
                base["global_train_losses"], policy
            assert all(np.array_equal(a, b)
                       for a, b in zip(base_leaves, leaves)), policy
            assert res["memory"]["available"] is True


class TestMemoryAnalysisOrdering:
    def test_temp_bytes_monotone_down_the_ladder(self):
        temps = {}
        for policy in ("none", "dots_saveable", "save_names:attn_out",
                       "everything"):
            step, init = _make_step(policy, depth=4)
            comp = step.lower(init()).compile()
            temps[policy] = int(comp.memory_analysis().temp_size_in_bytes)
        assert temps["none"] >= temps["dots_saveable"] \
            >= temps["save_names:attn_out"] >= temps["everything"]
        assert temps["none"] > temps["everything"]

    def test_offload_arm_matches_save_arm_bytes(self):
        # demoted offload is the SAME executable residency-wise
        if compat.host_offload_supported():
            pytest.skip("backend has pinned_host — bytes may differ")
        vals = []
        for policy in ("save_names:attn_out", "offload_names:attn_out"):
            step, init = _make_step(policy, depth=4)
            comp = step.lower(init()).compile()
            vals.append(int(comp.memory_analysis().temp_size_in_bytes))
        assert vals[0] == vals[1]


class TestTrackedProgram:
    def test_single_shape_compiles_once_and_tracks(self):
        calls = []
        inner = jax.jit(lambda a: a * 2)
        orig_lower = inner.lower

        def counting_lower(*a, **k):
            calls.append(1)
            return orig_lower(*a, **k)
        inner.lower = counting_lower
        tp = probe.TrackedProgram("p", inner)
        x = jnp.arange(4.0)
        assert np.array_equal(np.asarray(tp(x)), np.asarray(x) * 2)
        tp(x)
        tp(x)
        assert len(calls) == 1          # one AOT lower+compile total
        rows = tp.memory_rows()
        assert len(rows) == 1
        for key in ("temp_bytes", "argument_bytes", "output_bytes",
                    "alias_bytes"):
            assert isinstance(rows[0][key], int)

    def test_multi_shape_keeps_one_executable_per_shape(self):
        tp = probe.TrackedProgram("p", jax.jit(lambda a: a.sum()),
                                  multi_shape=True)
        tp(jnp.ones(3))
        tp(jnp.ones(5))
        tp(jnp.ones(3))
        assert len(tp.executables()) == 2
        assert len(tp.memory_rows()) == 2

    def test_fallback_never_kills_the_call(self):
        tp = probe.TrackedProgram("p", lambda a: a + 1)  # no .lower
        assert tp(1) == 2
        assert tp.memory_rows() == []

    def test_memory_report_schema(self):
        tp = probe.TrackedProgram("round", jax.jit(lambda a: a + 1))
        tp(jnp.ones(3))
        bad = probe.TrackedProgram("broken", lambda a: a)
        bad(1)
        rep = probe.memory_report(
            {"round": tp, "broken": bad},
            state_bytes={"params": 100, "opt_state": 200,
                         "params_gathered_peak": 800},
            n_workers=8)
        assert rep["available"] is False     # one program missing
        assert rep["programs_unavailable"] == ["broken"]
        assert rep["per_worker_resident_bytes"] == 300
        assert rep["per_worker_peak_bytes"] == 1100
        assert rep["state_bytes_total"] == 2400
        assert rep["temp_bytes_total"] == sum(
            r["temp_bytes"] for r in rep["programs"]["round"])


class TestMemoryRowOnEveryRun:
    """results["memory"] is emitted unconditionally, like sync_engine /
    sanitize — including on unarmed (no remat, no sanitize) runs."""

    KW = dict(model="mlp", dataset="mnist", epochs_local=1, batch_size=16,
              limit_train_samples=128, limit_eval_samples=32,
              compute_dtype="float32", augment=False,
              aggregation_by="weights", seed=5)

    def test_unarmed_run_emits_schema(self):
        mesh = build_mesh({"data": 2}, devices=jax.devices()[:2])
        res = train_global(Config(epochs_global=1, **self.KW),
                           mesh=mesh, progress=False)
        m = res["memory"]
        assert m["available"] is True
        assert m["simulated"] is False and m["workers"] == 2
        assert list(m["programs"]) == ["round"]
        row = m["programs"]["round"][0]
        assert row["temp_bytes"] > 0 and row["argument_bytes"] > 0
        pw = m["per_worker_state_bytes"]
        assert set(pw) >= {"params", "opt_state", "params_gathered_peak",
                           "batch_stats", "bookkeeping"}
        assert m["per_worker_resident_bytes"] == sum(
            v for k, v in pw.items() if k != "params_gathered_peak")
        assert m["state_bytes_total"] == 2 * m["per_worker_resident_bytes"]

    def test_zero_round_run_still_emits(self, tmp_path):
        # resuming a finished run dispatches nothing — the row must
        # still be there (empty program map, analytic model populated)
        kw = dict(self.KW, checkpoint_dir=str(tmp_path),
                  checkpoint_every=1)
        mesh = build_mesh({"data": 2}, devices=jax.devices()[:2])
        train_global(Config(epochs_global=1, **kw), mesh=mesh,
                     progress=False)
        res = train_global(Config(epochs_global=1, resume=True, **kw),
                           mesh=mesh, progress=False)
        m = res["memory"]
        assert m["programs"] == {} and m["available"] is False
        assert m["per_worker_resident_bytes"] > 0

    def test_exact_accounting_vs_actual_state_bytes(self):
        mesh = build_mesh({"data": 2}, devices=jax.devices()[:2])
        res = train_global(Config(epochs_global=1, **self.KW),
                           mesh=mesh, progress=False)
        actual = sum(l.nbytes
                     for l in jax.tree_util.tree_leaves(res["state"])
                     if hasattr(l, "nbytes"))
        assert res["memory"]["state_bytes_total"] == actual

    def test_sim_run_stacked_total_is_n_times_per_worker(self):
        res = train_global(Config(epochs_global=1, sim_workers=8,
                                  **self.KW), progress=False)
        m = res["memory"]
        assert m["simulated"] is True and m["workers"] == 8
        assert list(m["programs"]) == ["sim_round"]
        assert m["state_bytes_total"] == 8 * m["per_worker_resident_bytes"]
        actual = sum(l.nbytes
                     for l in jax.tree_util.tree_leaves(res["state"])
                     if hasattr(l, "nbytes"))
        assert m["state_bytes_total"] == actual


@pytest.mark.slow
class TestMemoryRowResidentAndStreamed:
    """Driver e2e coverage of the resident / streamed program maps
    (slow tier: new e2e driver cases up front)."""

    KW = dict(model="mlp", dataset="mnist", epochs_global=2,
              epochs_local=1, batch_size=16, limit_train_samples=256,
              limit_eval_samples=64, compute_dtype="float32",
              augment=False, aggregation_by="weights", seed=5)

    def test_resident_run_reports_gathered_peak(self, mesh8):
        res = train_global(Config(sync_mode="sharded",
                                  param_residency="resident", **self.KW),
                           mesh=mesh8, progress=False)
        m = res["memory"]
        pw = m["per_worker_state_bytes"]
        # the acceptance identity: resident params are EXACTLY 1/N of
        # the transient gathered peak
        assert pw["params"] * 8 == pw["params_gathered_peak"]
        assert m["per_worker_peak_bytes"] == \
            m["per_worker_resident_bytes"] + pw["params_gathered_peak"]
        assert m["available"] is True

    def test_streamed_resident_run_tracks_all_programs(self, mesh8):
        res = train_global(Config(sync_mode="sharded",
                                  param_residency="resident",
                                  stream_chunk_steps=2, **self.KW),
                           mesh=mesh8, progress=False)
        labels = set(res["memory"]["programs"])
        assert {"sync", "resident_enter", "stream_zeros", "chunk_train",
                "chunk_eval", "bump_epoch"} <= labels
        assert res["memory"]["available"] is True
