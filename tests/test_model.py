"""EnhancedCNNModel parity tests vs the reference architecture
(``Balanced All-Reduce/model.py:52-111``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model

# Trainable parameters of the torch reference (convs bias-free, BN affine,
# final Linear 1024->10 with bias), computed layer-by-layer from
# model.py:52-111.
REFERENCE_PARAM_COUNT = 44_595_786


@pytest.fixture(scope="module")
def cnn_vars():
    model = get_model("enhanced_cnn")
    variables = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)),
                           train=False)
    return model, variables


def _count(tree):
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(tree))


def test_param_count_matches_reference(cnn_vars):
    _, variables = cnn_vars
    assert _count(variables["params"]) == REFERENCE_PARAM_COUNT


def test_batch_stats_present_and_not_trainable(cnn_vars):
    _, variables = cnn_vars
    # BN running stats live outside 'params' => excluded from aggregation,
    # matching torch model.parameters() semantics (communication.py:5,22).
    assert "batch_stats" in variables
    # one (mean, var) pair per BN: prep + 8 blocks * (2 or 3 BNs)
    n_bn = len(jax.tree_util.tree_leaves(variables["batch_stats"])) // 2
    assert n_bn == 1 + 4 * (3 + 2)  # stride-2 blocks have a shortcut BN


def test_forward_shape_and_dtype(cnn_vars):
    model, variables = cnn_vars
    x = jax.random.normal(jax.random.key(1), (4, 32, 32, 3))
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32


def test_train_mode_updates_batch_stats(cnn_vars):
    model, variables = cnn_vars
    x = jax.random.normal(jax.random.key(2), (4, 32, 32, 3))
    logits, mutated = model.apply(variables, x, train=True,
                                  mutable=["batch_stats"])
    assert logits.shape == (4, 10)
    old = variables["batch_stats"]["prep_bn"]["mean"]
    new = mutated["batch_stats"]["prep_bn"]["mean"]
    assert not np.allclose(old, new)


def test_downsampling_path():
    # 32 -> 16 -> 8 -> 4 -> 2 spatial; check an intermediate via capture
    model = get_model("enhanced_cnn")
    variables = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)),
                           train=False)
    _, state = model.apply(variables, jnp.zeros((2, 32, 32, 3)), train=False,
                           capture_intermediates=True, mutable=["intermediates"])
    inter = state["intermediates"]
    last_block_out = inter["layer4_block1"]["__call__"][0]
    assert last_block_out.shape == (2, 2, 2, 1024)


def test_bfloat16_compute():
    model = get_model("enhanced_cnn", dtype=jnp.bfloat16)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)),
                           train=False)
    # params stay fp32 (flax keeps param dtype fp32 unless param_dtype set)
    leaf = variables["params"]["prep_conv"]["kernel"]
    assert leaf.dtype == jnp.float32
    logits = model.apply(variables, jnp.zeros((2, 32, 32, 3)), train=False)
    assert logits.dtype == jnp.float32  # head forced to fp32


def test_xavier_init_statistics(cnn_vars):
    _, variables = cnn_vars
    k = variables["params"]["layer1_block0"]["conv1"]["kernel"]
    # xavier-uniform bound for 3x3 conv, fan_in=64*9, fan_out=128*9
    bound = np.sqrt(6.0 / (64 * 9 + 128 * 9))
    assert float(jnp.max(jnp.abs(k))) <= bound + 1e-6
    assert float(jnp.std(k)) == pytest.approx(bound / np.sqrt(3), rel=0.1)
