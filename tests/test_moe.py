"""Mixture-of-Experts FFN + expert parallelism (``models/moe.py``).

Correctness ladder: routing invariants (top-1, capacity, load-balance
loss); expert-sharded execution on a 4-device ``expert`` mesh vs the
dense twin (forward AND gradients); and end-to-end through the driver on
a (data=2, expert=2) mesh against the unsharded MoE data=2 run.
Beyond-reference capability (the reference is data-parallel only,
SURVEY.md 2.3).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from learning_deep_neural_network_in_distributed_computing_environment_tpu.models.moe import (
    MoEFFN,
    ep_param_specs,
)


@pytest.fixture(scope="module")
def expert_mesh(devices):
    return Mesh(np.array(devices[:4]), ("expert",))


def _x(b=2, t=16, h=32, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(b, t, h)), jnp.float32)


class TestMoEFFN:
    def test_output_shape_and_aux_loss(self):
        m = MoEFFN(num_experts=4, ffn_dim=64)
        x = _x()
        out, aux = m.init_with_output(jax.random.key(0), x,
                                      mutable=["params", "aux"])
        y, col = out, aux
        assert y.shape == x.shape
        lb = jax.tree_util.tree_leaves(col["aux"])[0]
        # Switch LB loss is E * sum(f_e * P_e) >= 1 with equality at
        # perfect balance; a random gate sits near 1
        assert 0.9 < float(lb) < 4.0

    def test_capacity_drops_overflow(self):
        """With capacity_factor tiny, most tokens drop -> output mostly 0
        (the caller's residual carries them)."""
        m = MoEFFN(num_experts=2, ffn_dim=16, capacity_factor=0.05)
        x = _x(b=1, t=64, h=8)
        variables = m.init(jax.random.key(0), x)
        y = m.apply(variables, x)
        # capacity = ceil(0.05 * 64 / 2) = 2 tokens per expert at most
        nonzero_rows = (np.abs(np.asarray(y[0])).sum(-1) > 1e-6).sum()
        assert nonzero_rows <= 4

    def test_sharded_matches_dense(self, expert_mesh):
        dense = MoEFFN(num_experts=4, ffn_dim=64)
        sharded_mod = MoEFFN(num_experts=4, ffn_dim=64,
                             expert_axis="expert", ep_size=4)
        x = _x(seed=1)
        params = dense.init(jax.random.key(1), x)["params"]
        specs = ep_param_specs({"moe": params}, axis="expert")["moe"]
        f = jax.jit(jax.shard_map(
            lambda p, x: sharded_mod.apply({"params": p}, x),
            mesh=expert_mesh, in_specs=(specs, P()), out_specs=P()))
        np.testing.assert_allclose(f(params, x),
                                   dense.apply({"params": params}, x),
                                   atol=1e-5)

    def test_sharded_grads_match_dense(self, expert_mesh):
        dense = MoEFFN(num_experts=4, ffn_dim=64)
        sharded_mod = MoEFFN(num_experts=4, ffn_dim=64,
                             expert_axis="expert", ep_size=4)
        x = _x(seed=2)
        params = dense.init(jax.random.key(2), x)["params"]
        specs = ep_param_specs({"moe": params}, axis="expert")["moe"]

        def loss(mod):
            def f(p, x):
                return (mod.apply({"params": p}, x) ** 2).sum()
            return f

        sh = jax.jit(jax.shard_map(loss(sharded_mod), mesh=expert_mesh,
                                   in_specs=(specs, P()), out_specs=P()))
        g = jax.grad(sh)(params, x)
        gr = jax.grad(loss(dense))(params, x)
        flat = jax.tree_util.tree_leaves_with_path(g)
        ref = dict(jax.tree_util.tree_leaves_with_path(gr))
        for path, leaf in flat:
            np.testing.assert_allclose(leaf, ref[path], atol=1e-4,
                                       err_msg=jax.tree_util.keystr(path))


@pytest.mark.slow
class TestMoETensorParallel:
    """MoE x TP (VERDICT r3 'next' #4): per-expert Megatron sharding of
    the F dim over a 'model' mesh axis, routing replicated — the sharded
    module computes EXACTLY the unsharded MoE function."""

    @pytest.fixture(scope="class")
    def model_mesh(self, devices):
        return Mesh(np.array(devices[:4]), ("model",))

    def _specs(self, params):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models.bert import tp_param_specs
        return tp_param_specs({"moe": params}, axis="model")["moe"]

    def test_tp_sharded_matches_dense(self, model_mesh):
        dense = MoEFFN(num_experts=4, ffn_dim=64)
        sharded_mod = MoEFFN(num_experts=4, ffn_dim=64,
                             model_axis="model", tp_size=4)
        x = _x(seed=3)
        params = dense.init(jax.random.key(3), x)["params"]
        specs = self._specs(params)
        f = jax.jit(jax.shard_map(
            lambda p, x: sharded_mod.apply({"params": p}, x),
            mesh=model_mesh, in_specs=(specs, P()), out_specs=P()))
        np.testing.assert_allclose(f(params, x),
                                   dense.apply({"params": params}, x),
                                   atol=1e-5)

    def test_tp_sharded_grads_match_dense(self, model_mesh):
        dense = MoEFFN(num_experts=4, ffn_dim=64)
        sharded_mod = MoEFFN(num_experts=4, ffn_dim=64,
                             model_axis="model", tp_size=4)
        x = _x(seed=4)
        params = dense.init(jax.random.key(4), x)["params"]
        specs = self._specs(params)

        def loss(mod):
            def f(p, x):
                return (mod.apply({"params": p}, x) ** 2).sum()
            return f

        sh = jax.jit(jax.shard_map(loss(sharded_mod), mesh=model_mesh,
                                   in_specs=(specs, P()), out_specs=P()))
        g = jax.grad(sh)(params, x)
        gr = jax.grad(loss(dense))(params, x)
        flat = jax.tree_util.tree_leaves_with_path(g)
        ref = dict(jax.tree_util.tree_leaves_with_path(gr))
        for path, leaf in flat:
            np.testing.assert_allclose(leaf, ref[path], atol=1e-4,
                                       err_msg=jax.tree_util.keystr(path))

    def _run(self, devices, mesh_axes):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        mesh = build_mesh(mesh_axes, devices)
        cfg = Config(model="bert_tiny", dataset="synthetic_mlm",
                     epochs_global=2, epochs_local=1, batch_size=8,
                     limit_train_samples=128, limit_eval_samples=32,
                     compute_dtype="float32", augment=False,
                     aggregation_by="weights", seed=7, num_experts=4)
        return train_global(cfg, mesh=mesh, progress=False)

    def test_driver_moe_tp_matches_unsharded(self, devices):
        base = self._run(devices[:2], {"data": 2})
        tp = self._run(devices[:4], {"data": 2, "model": 2})
        np.testing.assert_allclose(tp["global_train_losses"],
                                   base["global_train_losses"], rtol=2e-3)
        assert tp["global_train_losses"][-1] < tp["global_train_losses"][0]

    def test_driver_moe_tp_ep_matches_unsharded(self, devices):
        """3-D (data=2, model=2, expert=2): Megatron F dims over 'model'
        PLUS the expert overlay on the expert dim — still exactly the
        unsharded MoE function (routing replicated in both)."""
        base = self._run(devices[:2], {"data": 2})
        tpep = self._run(devices[:8], {"data": 2, "model": 2, "expert": 2})
        np.testing.assert_allclose(tpep["global_train_losses"],
                                   base["global_train_losses"], rtol=2e-3)
        res = tpep
        specs = [str(l.sharding.spec) for l in
                 jax.tree_util.tree_leaves(res["state"].params)]
        assert any("model" in s and "expert" in s for s in specs)


@pytest.mark.slow
class TestDriverExpertParallel:
    """MoE-BERT training expert-sharded over (data=2, expert=2) must match
    the unsharded MoE data=2 run."""

    def _run(self, devices, mesh_axes):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        mesh = build_mesh(mesh_axes, devices)
        cfg = Config(model="bert_tiny", dataset="synthetic_mlm",
                     epochs_global=2, epochs_local=1, batch_size=8,
                     limit_train_samples=128, limit_eval_samples=32,
                     compute_dtype="float32", augment=False,
                     aggregation_by="weights", seed=7, num_experts=4)
        return train_global(cfg, mesh=mesh, progress=False)

    def test_matches_unsharded_run(self, devices):
        base = self._run(devices[:2], {"data": 2})
        ep = self._run(devices[:4], {"data": 2, "expert": 2})
        np.testing.assert_allclose(ep["global_train_losses"],
                                   base["global_train_losses"], rtol=2e-3)
        assert ep["global_train_losses"][-1] < ep["global_train_losses"][0]

    def test_expert_axis_requires_experts(self, devices):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        mesh = build_mesh({"data": 2, "expert": 2}, devices[:4])
        cfg = Config(model="bert_tiny", dataset="synthetic_mlm",
                     limit_train_samples=64, limit_eval_samples=16,
                     augment=False)
        with pytest.raises(ValueError, match="expert"):
            train_global(cfg, mesh=mesh, progress=False)


@pytest.mark.slow
class TestMoEScanAndPipeline:
    """MoE x scan_layers (the sown aux lifts through ``nn.scan`` stacked)
    and MoE x pipeline parallelism (bubble-masked aux through the GPipe
    schedule, round-2 verdict item 7)."""

    def test_scanned_forward_matches_unrolled(self):
        """Same per-layer MoE params => identical logits for the two
        layouts (pattern of test_pp.TestScannedBert)."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
        loop = get_model("bert_tiny", num_classes=97, num_experts=4)
        scan = get_model("bert_tiny", num_classes=97, num_experts=4,
                         scan_layers=True)
        x = jnp.asarray(
            np.random.default_rng(0).integers(0, 97, (2, 16)), jnp.int32)
        pl_ = loop.init(jax.random.key(1), x, train=False)["params"]
        ps = {k: v for k, v in pl_.items() if not k.startswith("layer")}
        ps["layers"] = {"layer": jax.tree.map(
            lambda *ls: jnp.stack(ls), pl_["layer0"], pl_["layer1"])}
        np.testing.assert_allclose(
            scan.apply({"params": ps}, x, train=False),
            loop.apply({"params": pl_}, x, train=False), atol=1e-5)

    def test_scanned_aux_is_stacked_and_sums_match(self):
        """The scanned model's sown aux carries a leading layer axis and
        its total equals the unrolled model's per-layer scalar sum."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
        loop = get_model("bert_tiny", num_classes=97, num_experts=4)
        scan = get_model("bert_tiny", num_classes=97, num_experts=4,
                         scan_layers=True)
        x = jnp.asarray(
            np.random.default_rng(1).integers(0, 97, (2, 16)), jnp.int32)
        pl_ = loop.init(jax.random.key(2), x, train=False)["params"]
        ps = {k: v for k, v in pl_.items() if not k.startswith("layer")}
        ps["layers"] = {"layer": jax.tree.map(
            lambda *ls: jnp.stack(ls), pl_["layer0"], pl_["layer1"])}
        _, mut_s = scan.apply({"params": ps}, x, train=True,
                              mutable=["aux"])
        _, mut_l = loop.apply({"params": pl_}, x, train=True,
                              mutable=["aux"])
        leaves_s = jax.tree_util.tree_leaves(mut_s["aux"])
        assert any(l.ndim >= 1 and l.shape[0] == 2 for l in leaves_s)
        tot_s = sum(float(jnp.sum(l)) for l in leaves_s)
        tot_l = sum(float(jnp.sum(l))
                    for l in jax.tree_util.tree_leaves(mut_l["aux"]))
        np.testing.assert_allclose(tot_s, tot_l, rtol=1e-5)

    def _run(self, devices, mesh_axes, **kw):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        mesh = build_mesh(mesh_axes, devices)
        # generous capacity so no token drops either way: per-microbatch
        # routing then dispatches identically to full-batch routing and
        # only the aux-loss batching differs (microbatch mean vs full-
        # batch value), kept out of the trajectory with aux weight 0
        cfg = Config(model="bert_tiny", dataset="synthetic_mlm",
                     epochs_global=2, epochs_local=1, batch_size=8,
                     limit_train_samples=128, limit_eval_samples=32,
                     compute_dtype="float32", augment=False,
                     aggregation_by="weights", seed=7, num_experts=4,
                     expert_capacity_factor=2.0, moe_aux_weight=0.0, **kw)
        return train_global(cfg, mesh=mesh, progress=False)

    def test_driver_moe_pp_matches_unsharded(self, devices):
        base = self._run(devices[:2], {"data": 2})
        pp = self._run(devices[:4], {"data": 2, "pipe": 2})
        np.testing.assert_allclose(pp["global_train_losses"],
                                   base["global_train_losses"], rtol=2e-3)
        assert pp["global_train_losses"][-1] < pp["global_train_losses"][0]

    def test_driver_moe_pp_ep_trains(self, devices):
        """3-D: (data=2, pipe=2, expert=2) — stacked layer axis over
        'pipe', expert stacks over 'expert' (pp_ep_param_specs), with the
        default aux weight active."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        mesh = build_mesh({"data": 2, "pipe": 2, "expert": 2}, devices[:8])
        cfg = Config(model="bert_tiny", dataset="synthetic_mlm",
                     epochs_global=2, epochs_local=1, batch_size=8,
                     limit_train_samples=128, limit_eval_samples=32,
                     compute_dtype="float32", augment=False,
                     aggregation_by="weights", seed=7, num_experts=4)
        res = train_global(cfg, mesh=mesh, progress=False)
        assert np.isfinite(res["global_train_losses"]).all()
        assert res["global_train_losses"][-1] < res["global_train_losses"][0]
        specs = [str(l.sharding.spec) for l in
                 jax.tree_util.tree_leaves(res["state"].params)]
        assert any("pipe" in s and "expert" in s for s in specs)


@pytest.mark.slow
class TestDriverMoESequenceParallel:
    """MoE x SP (r5, guard lifted): each seq-parallel device routes its
    own chunk of every sequence — a declared semantics shift vs the
    unchunked run (per-chunk capacity), proven the same two-sided way as
    FSDP x MoE: the SP run itself must learn, and the EP-sharded triple
    composition must reproduce it EXACTLY (expert sharding touches no
    routing)."""

    def _run(self, devices, mesh_axes, **kw):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        mesh = build_mesh(mesh_axes, devices)
        cfg = Config(model="bert_tiny", dataset="synthetic_mlm",
                     epochs_global=2, epochs_local=1, batch_size=8,
                     limit_train_samples=128, limit_eval_samples=32,
                     compute_dtype="float32", augment=False,
                     aggregation_by="weights", seed=7, num_experts=4, **kw)
        return train_global(cfg, mesh=mesh, progress=False)

    @pytest.fixture(scope="class")
    def moe_sp_run(self, devices):
        return self._run(devices[:4], {"data": 2, "seq": 2},
                         sequence_parallel="ring")

    def test_moe_sp_runs_and_learns(self, moe_sp_run):
        losses = moe_sp_run["global_train_losses"]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    def test_moe_sp_ep_matches_moe_sp_twin(self, devices, moe_sp_run):
        ep = self._run(devices[:8], {"data": 2, "seq": 2, "expert": 2},
                       sequence_parallel="ring")
        np.testing.assert_allclose(ep["global_train_losses"],
                                   moe_sp_run["global_train_losses"],
                                   rtol=2e-3)


def _assert_params_close(res, ref, rtol=2e-3, atol=2e-4):
    """Final-parameter comparison between two driver runs with identical
    parameter structure (shared by the 1F1B MoE tests below)."""
    for a, b in zip(jax.tree_util.tree_leaves(res["state"].params),
                    jax.tree_util.tree_leaves(ref["state"].params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=rtol, atol=atol)


@pytest.mark.slow
class TestDriverMoEOneF1B:
    """1F1B x MoE (r5, the final 1F1B exclusion lifted): the stage
    applies with mutable aux so the sown load-balance losses are
    captured, the schedule adds them to its loss carry per valid fwd
    slot, and the backward seeds the aux output's cotangent with the
    (scaled) aux weight — differentiated through the schedule.  GPipe
    under the same microbatching routes identically, so the 1F1B run
    must reproduce the GPipe moe x pp run."""

    def _run(self, devices, mesh_axes, **kw):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        mesh = build_mesh(mesh_axes, devices)
        cfg = Config(model="bert_tiny", dataset="synthetic_mlm",
                     epochs_global=2, epochs_local=1, batch_size=8,
                     limit_train_samples=128, limit_eval_samples=32,
                     compute_dtype="float32", augment=False,
                     aggregation_by="weights", seed=7, num_experts=4,
                     pp_microbatches=2, **kw)
        return train_global(cfg, mesh=mesh, progress=False)

    def test_1f1b_moe_matches_gpipe(self, devices):
        """Default aux weight ACTIVE: the trajectory only matches the
        GPipe twin if the aux loss is both captured and differentiated
        correctly through the schedule."""
        gpipe = self._run(devices[:4], {"data": 2, "pipe": 2})
        onef = self._run(devices[:4], {"data": 2, "pipe": 2},
                         pp_schedule="1f1b")
        np.testing.assert_allclose(onef["global_train_losses"],
                                   gpipe["global_train_losses"], rtol=2e-3)
        _assert_params_close(onef, gpipe)

    def test_1f1b_moe_ep_matches_gpipe_ep(self, devices):
        """The EP triple: expert stacks sharded over 'expert' behind the
        'pipe' layer dim, under the 1F1B schedule.  Params compared too
        (same structure): an EP-specific aux-cotangent bug below loss
        visibility would otherwise pass (code-review r5)."""
        gpipe = self._run(devices[:8], {"data": 2, "pipe": 2, "expert": 2})
        onef = self._run(devices[:8], {"data": 2, "pipe": 2, "expert": 2},
                         pp_schedule="1f1b")
        np.testing.assert_allclose(onef["global_train_losses"],
                                   gpipe["global_train_losses"], rtol=2e-3)
        _assert_params_close(onef, gpipe)

    def test_1f1b_moe_sp_matches_gpipe(self, devices):
        """The deepest composition in the framework: 1F1B x MoE x SP on
        a (data, pipe, seq) mesh — masked schedule slots (SP ring), aux
        capture + weight-valued cotangent (MoE), per-microbatch head
        loss, all at once.  GPipe with the identical chunking and
        microbatching computes the same function, so the trajectories
        must agree."""
        gpipe = self._run(devices[:8], {"data": 2, "pipe": 2, "seq": 2},
                          sequence_parallel="ring")
        onef = self._run(devices[:8], {"data": 2, "pipe": 2, "seq": 2},
                         sequence_parallel="ring", pp_schedule="1f1b")
        np.testing.assert_allclose(onef["global_train_losses"],
                                   gpipe["global_train_losses"], rtol=2e-3)
        # looser atol than the pure-MoE twins: under SP the 1F1B bwd
        # remats the ring attention (a different fp32 path than GPipe's
        # stored residuals) and Adam amplifies the noise, worst on
        # sparsely-updated embedding rows (see test_pp.py's 1f1b_sp
        # leaf-aware bounds)
        _assert_params_close(onef, gpipe, atol=5e-3)
