"""ZeRO-3 / FSDP sharded data parallelism (``parallel/fsdp.py``).

Beyond-reference capability (SURVEY.md 2.3 lists the ZeRO/FSDP row as
absent — the reference keeps a full replica + per-worker Adam,
``Balanced All-Reduce/main.py:53``).  Correctness is asserted three ways:
spec/gather unit math, physical sharding of params AND Adam moments in the
initialized TrainState, and end-to-end numerics on a (data=2, fsdp=2) mesh
against the plain data=2 run with identical seed/config.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
from learning_deep_neural_network_in_distributed_computing_environment_tpu.parallel.fsdp import (
    MIN_SHARD_ELEMS,
    fsdp_param_specs,
    gather_params,
)


class TestSpecsAndGather:
    def _params(self):
        model = get_model("mlp", num_classes=10)
        x = jnp.zeros((2, 28, 28, 1), jnp.float32)
        return model.init(jax.random.key(0), x, train=False)["params"]

    def test_large_leaves_shard_small_replicate(self):
        params = self._params()
        specs = fsdp_param_specs(params, axis="fsdp", axis_size=2)
        for (path, leaf), (_, spec) in zip(
                jax.tree_util.tree_leaves_with_path(params),
                jax.tree_util.tree_leaves_with_path(
                    specs, is_leaf=lambda s: isinstance(s, P))):
            if leaf.size >= MIN_SHARD_ELEMS and any(
                    s % 2 == 0 for s in leaf.shape):
                assert "fsdp" in spec, jax.tree_util.keystr(path)
                d = spec.index("fsdp")
                assert leaf.shape[d] % 2 == 0
            else:
                assert "fsdp" not in spec, jax.tree_util.keystr(path)
        # the MLP's big input kernel must actually be sharded (the point)
        flat = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda s: "fsdp" in s, specs,
                                   is_leaf=lambda s: isinstance(s, P)))
        assert sum(flat) >= 1

    def test_gather_roundtrip(self, devices):
        params = self._params()
        specs = fsdp_param_specs(params, axis="fsdp", axis_size=2)
        mesh = Mesh(np.array(devices[:2]), ("fsdp",))
        # all_gather output is TYPED varying (shard_map can't statically
        # prove replication), so the replication check is off for this
        # out_specs=P() roundtrip; numerics below prove actual equality
        f = jax.jit(jax.shard_map(
            lambda p: gather_params(p, specs, "fsdp"),
            mesh=mesh, in_specs=(specs,),
            out_specs=jax.tree_util.tree_map(
                lambda _: P(), specs, is_leaf=lambda s: isinstance(s, P)),
            check_vma=False))
        out = f(params)
        jax.tree_util.tree_map(np.testing.assert_array_equal, out, params)


def _run(devices, mesh_axes, model="mlp", dataset="mnist", **kw):
    mesh = build_mesh(mesh_axes, devices)
    cfg = Config(model=model, dataset=dataset, epochs_global=2,
                 epochs_local=1, batch_size=8, limit_train_samples=128,
                 limit_eval_samples=32, compute_dtype="float32",
                 augment=False, aggregation_by="weights", seed=11, **kw)
    return train_global(cfg, mesh=mesh, progress=False)


@pytest.mark.slow
class TestDriverFSDP:
    def test_matches_plain_dp_mlp(self, devices):
        plain = _run(devices[:2], {"data": 2})
        fsdp = _run(devices[:4], {"data": 2, "fsdp": 2})
        np.testing.assert_allclose(fsdp["global_train_losses"],
                                   plain["global_train_losses"], rtol=2e-4)
        np.testing.assert_allclose(fsdp["global_val_losses"],
                                   plain["global_val_losses"], rtol=2e-4)
        assert fsdp["global_train_losses"][-1] < fsdp["global_train_losses"][0]

    def test_matches_plain_dp_bert(self, devices):
        plain = _run(devices[:2], {"data": 2}, model="bert_tiny",
                     dataset="synthetic_mlm")
        fsdp = _run(devices[:4], {"data": 2, "fsdp": 2}, model="bert_tiny",
                    dataset="synthetic_mlm")
        np.testing.assert_allclose(fsdp["global_train_losses"],
                                   plain["global_train_losses"], rtol=2e-3)

    def test_batchnorm_model_runs(self, devices):
        """BN under FSDP: per-device sub-batch statistics, pmean'd running
        stats (engine-level, width-8 CNN so CPU stays fast)."""
        from functools import partial
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import LocalSGDEngine
        mesh = build_mesh({"data": 2, "fsdp": 2}, devices[:4])
        cfg = Config(epochs_local=1, batch_size=4, compute_dtype="float32",
                     augment=False, aggregation_by="weights")
        model = get_model("enhanced_cnn", num_classes=10, width=8)
        eng = LocalSGDEngine(
            model, mesh, cfg,
            param_specs_fn=partial(fsdp_param_specs, axis="fsdp",
                                   axis_size=2))
        rng = np.random.default_rng(0)
        n, steps, b = 2, 2, cfg.batch_size
        x = rng.normal(size=(n, steps, b, 32, 32, 3)).astype(np.float32)
        y = rng.integers(0, 10, (n, steps, b)).astype(np.int32)
        m = np.ones((n, steps, b), np.float32)
        state = eng.init_state(jax.random.key(0), x[0, 0])
        state, mx = eng.round(state, (x, y, m), (x, y, m))
        assert np.isfinite(mx["train_loss"]).all()
        # running stats stayed replicated along fsdp (pmean'd)
        bs_leaf = jax.tree_util.tree_leaves(state.batch_stats)[0]
        assert "fsdp" not in str(bs_leaf.sharding.spec)

    def test_state_is_physically_sharded(self, devices):
        """Params AND Adam moments shard over fsdp — the ZeRO-3 memory
        claim — while small leaves stay replicated."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import LocalSGDEngine
        from functools import partial
        mesh = build_mesh({"data": 2, "fsdp": 2}, devices[:4])
        model = get_model("mlp", num_classes=10)
        cfg = Config(model="mlp", batch_size=8, compute_dtype="float32",
                     augment=False)
        eng = LocalSGDEngine(
            model, mesh, cfg,
            param_specs_fn=partial(fsdp_param_specs, axis="fsdp",
                                   axis_size=2))
        state = eng.init_state(jax.random.key(0),
                               np.zeros((8, 28, 28, 1), np.float32))

        def sharded_axes(tree):
            return {
                jax.tree_util.keystr(path): leaf.sharding.spec
                for path, leaf in jax.tree_util.tree_leaves_with_path(tree)}

        pspecs = sharded_axes(state.params)
        assert any("fsdp" in s for s in pspecs.values())
        # Adam mu/nu mirror the param sharding
        mspecs = sharded_axes(state.opt_state)
        assert any("fsdp" in s for s in mspecs.values())

    def test_augment_runs_decorrelated(self, devices):
        """augment=True under FSDP: the per-worker key is folded with the
        fsdp axis index (code-review r2 finding: replicated key + split
        batch = duplicated per-image draws across devices)."""
        mesh = build_mesh({"data": 2, "fsdp": 2}, devices[:4])
        cfg = Config(model="lenet5", dataset="mnist", epochs_global=1,
                     epochs_local=1, batch_size=8, limit_train_samples=64,
                     limit_eval_samples=16, compute_dtype="float32",
                     augment=True, aggregation_by="weights", seed=12)
        res = train_global(cfg, mesh=mesh, progress=False)
        assert np.isfinite(res["global_train_losses"]).all()

    def test_batch_divisibility_error(self, devices):
        mesh = build_mesh({"data": 2, "fsdp": 2}, devices[:4])
        cfg = Config(model="mlp", dataset="mnist", batch_size=7,
                     limit_train_samples=64, limit_eval_samples=16,
                     augment=False)
        with pytest.raises(ValueError, match="divisible"):
            train_global(cfg, mesh=mesh, progress=False)

    def test_composes_with_tp(self, devices):
        """2-D (fsdp, model) sharding inside each worker: ZeRO-3 claims a
        free dim of every large TP-sharded leaf; numerics must match the
        plain data=2 run."""
        plain = _run(devices[:2], {"data": 2}, model="bert_tiny",
                     dataset="synthetic_mlm")
        both = _run(devices[:8], {"data": 2, "fsdp": 2, "model": 2},
                    model="bert_tiny", dataset="synthetic_mlm")
        np.testing.assert_allclose(both["global_train_losses"],
                                   plain["global_train_losses"], rtol=2e-3)
        specs = [str(l.sharding.spec) for l in
                 jax.tree_util.tree_leaves(both["state"].params)]
        assert any("fsdp" in s and "model" in s for s in specs)

    @pytest.fixture(scope="class")
    def fsdp_moe_run(self, devices):
        """One (data=2, fsdp=2) MoE training run shared by the two MoE
        tests below (learning check + EP golden twin)."""
        return _run(devices[:4], {"data": 2, "fsdp": 2}, model="bert_tiny",
                    dataset="synthetic_mlm", num_experts=4)

    def test_moe_runs_and_learns(self, fsdp_moe_run):
        """FSDP x MoE (r5, guard lifted): each fsdp slice routes its own
        sub-batch — a semantics shift vs the unsharded run (per-slice
        capacity), so the contract is finite declining loss; exact
        numerics are proven by the EP twin test below, which shares the
        slicing."""
        losses = fsdp_moe_run["global_train_losses"]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    def test_moe_ep_matches_fsdp_moe_twin(self, devices, fsdp_moe_run):
        """FSDP x EP == FSDP x unsharded-MoE EXACTLY: the expert axis
        shards only the expert stacks (routing, capacity, and the
        fsdp-sliced batches are identical), so the 3-D (data, fsdp,
        expert) run must reproduce the (data, fsdp) MoE run's loss
        trajectory to float tolerance."""
        twin = fsdp_moe_run
        ep = _run(devices[:8], {"data": 2, "fsdp": 2, "expert": 2},
                  model="bert_tiny", dataset="synthetic_mlm", num_experts=4)
        np.testing.assert_allclose(ep["global_train_losses"],
                                   twin["global_train_losses"], rtol=2e-3)
        # the expert stacks must be PHYSICALLY sharded over 'expert' and
        # ZeRO-3 must still claim a free dim of large non-expert leaves
        specs = {jax.tree_util.keystr(p): str(l.sharding.spec)
                 for p, l in jax.tree_util.tree_leaves_with_path(
                     ep["state"].params)}
        assert any("expert" in s for k, s in specs.items() if "moe" in k)
        assert any("fsdp" in s for k, s in specs.items()
                   if "moe" not in k)
