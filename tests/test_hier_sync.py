"""Hierarchical two-level sync engine (ISSUE 13 tentpole).

The gate, per the framework's standing bar: the bucketed hierarchical
program (inner sharded psum_scatter/all_gather over the ICI-shaped
``data`` axis x outer per-bucket ppermute gossip over the DCN-shaped
``slice`` axis, one program) is BITWISE-identical in fp32 to the flat
gossip-of-means reference — ``comms.aggregate_hier``, the same
expressions evaluated per leaf from the flat primitives (lax.pmean +
the dense gossip blends) — across 2x2 / 2x4 / 4x2 layouts x
ring/double-ring x equal/weighted; at ``--num_slices 1`` the config
resolves the UNCHANGED flat engine (whose dense-twin bitwise gates are
tests/test_sync.py's).  Outer (DCN) wire bytes are exactly
``hops x shard_row x outer_wire_itemsize`` per bucket — 1/N_inner of
the flat gossip payload — with bf16/int8 outer wire at exactly 1/2 and
1/4 of that.  Per-level EF, scatter-resident composition (PR 11),
cross-slice checkpoint re-layouts, and the eager v1 rejections ride
along.  Driver-level S x W sweeps are slow-marked per the ROADMAP
tier-1 wall-headroom rule; the tier-1 subset stays well under ~30 s.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from learning_deep_neural_network_in_distributed_computing_environment_tpu import (
    checkpoint as ckpt_lib,
    comms,
    mesh as mesh_lib,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import LocalSGDEngine

# uneven leaf sizes (nothing divisible by the worker counts) so every
# bucket needs padding and the pack/pad/unpack plumbing is exercised
SHAPES = {"a": (13, 7), "b": (257,), "c": (31, 5), "d": (3,)}
TINY_BUCKET = 1024
LAYOUTS = [(2, 2), (2, 4), (4, 2)]   # (slices, workers-per-slice)


def stacked_tree(n, seed=0):
    rng = np.random.default_rng(seed)
    return {k: jnp.asarray(rng.normal(size=(n, *s)), jnp.float32)
            for k, s in SHAPES.items()}


def slice_mesh(s, w):
    return mesh_lib.build_mesh({"slice": s, "data": w},
                               devices=jax.devices()[:s * w])


def per_worker_struct(tree):
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape[1:], x.dtype), tree)


def trees_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert la and len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


def hier_cfg(**kw):
    base = dict(model="mlp", dataset="mnist", epochs_local=1,
                epochs_global=2, batch_size=8, compute_dtype="float32",
                augment=False, aggregation_by="weights", topology="ring",
                num_slices=2, sync_bucket_mb=0.001)
    base.update(kw)
    return Config(**base)


# --------------------------------------------------------------------------
# Config resolution + eager v1 validation (ISSUE 13 satellite)
# --------------------------------------------------------------------------
class TestConfigResolution:
    def test_hier_mode_and_levels(self):
        for topo in ("ring", "double_ring"):
            cfg = hier_cfg(topology=topo)
            assert cfg.resolve_sync_mode("cpu") == "hier"
            assert cfg.resolve_sync_mode("tpu") == "hier"
            assert cfg.resolve_sync_levels("cpu") == {
                "inner": "sharded", "outer": "gossip"}
            # the apply necessarily runs on the inner shard
            assert cfg.resolve_opt_placement("cpu") == "sharded"

    def test_one_slice_resolves_the_flat_engine_unchanged(self):
        # the 1-slice limit of the bitwise gate: no hier program exists —
        # the resolution is EXACTLY the pre-ISSUE-13 flat one (whose
        # dense-twin bitwise gates live in tests/test_sync.py)
        cfg = hier_cfg(num_slices=1)
        assert cfg.resolve_sync_mode("cpu") == "dense"       # ring, CPU
        assert cfg.resolve_sync_levels("cpu") == {
            "inner": "dense", "outer": None}
        flat = hier_cfg(num_slices=1, topology="allreduce",
                        sync_mode="sharded")
        assert flat.resolve_sync_mode("cpu") == "sharded"

    def test_mesh_axes_lead_with_slice(self):
        axes = hier_cfg().mesh_axes()
        assert list(axes)[0] == "slice" and axes["slice"] == 2

    def test_wire_dtypes_outer_inherits(self):
        assert hier_cfg(sync_dtype="bfloat16",
                        ).resolve_sync_wire_dtypes() == ("bfloat16",
                                                         "bfloat16")
        assert hier_cfg(sync_dtype_outer="int8",
                        ).resolve_sync_wire_dtypes() == ("float32", "int8")

    def test_residency_auto_resolves_resident(self):
        assert hier_cfg().resolve_param_residency("cpu") == "resident"
        assert hier_cfg(aggregation_type="weighted",
                        ).resolve_param_residency("cpu") == "replicated"
        assert hier_cfg(aggregation_by="gradients",
                        ).resolve_param_residency("cpu") == "replicated"


class TestEagerValidation:
    def test_allreduce_outer_rejected(self):
        with pytest.raises(ValueError, match="flat sharded allreduce"):
            hier_cfg(topology="allreduce")

    def test_dense_inner_rejected(self):
        with pytest.raises(ValueError, match="dense inner level has no"):
            hier_cfg(sync_mode="dense")

    def test_chaos_rejected(self):
        with pytest.raises(ValueError, match="chaos cannot combine"):
            hier_cfg(chaos="kill@1:w0")
        with pytest.raises(ValueError, match="chaos cannot combine"):
            hier_cfg(chaos="random")

    def test_explicit_buddy_rejected(self):
        with pytest.raises(ValueError, match="buddy cannot combine"):
            hier_cfg(shard_redundancy="buddy")
        # auto resolves off: nothing raises, the engine disarms it
        eng = LocalSGDEngine(get_model("mlp", num_classes=10, hidden=8),
                             slice_mesh(2, 2), hier_cfg())
        assert not eng.buddy_on

    def test_replicated_opt_placement_rejected(self):
        with pytest.raises(ValueError, match="opt_placement replicated"):
            hier_cfg(opt_placement="replicated")

    def test_outer_wire_needs_slices(self):
        with pytest.raises(ValueError, match="requires --num_slices"):
            hier_cfg(num_slices=1, sync_dtype_outer="int8")

    def test_inner_model_axes_rejected(self):
        with pytest.raises(ValueError, match="inner mesh axes"):
            hier_cfg(mesh_shape="data=2,model=2").mesh_axes()

    def test_slice_in_mesh_shape_rejected(self):
        with pytest.raises(ValueError, match="driven by --num_slices"):
            hier_cfg(mesh_shape="slice=2,data=2").mesh_axes()

    def test_one_worker_per_slice_rejected_by_engine(self):
        with pytest.raises(ValueError, match="workers per"):
            LocalSGDEngine(get_model("mlp", num_classes=10, hidden=8),
                           slice_mesh(4, 1), hier_cfg(num_slices=4))

    def test_elastic_snapshot_rejected_by_driver(self):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        with pytest.raises(ValueError, match="elastic_snapshot cannot"):
            train_global(hier_cfg(), elastic_snapshot=object(),
                         progress=False)

    def test_hierarchical_sync_rejects_allreduce_topology(self):
        with pytest.raises(ValueError, match="outer topology"):
            comms.hierarchical_sync({"x": jnp.zeros(4)},
                                    topology="allreduce")


# --------------------------------------------------------------------------
# The tentpole bitwise gate (comms level, full S x W matrix)
# --------------------------------------------------------------------------
class TestHierBitwise:
    @pytest.mark.parametrize("layout", LAYOUTS)
    @pytest.mark.parametrize("topo", ["ring", "double_ring"])
    @pytest.mark.parametrize("how", ["equal", "weighted"])
    def test_fp32_bucketed_equals_gossip_of_means_reference(
            self, layout, topo, how):
        s, w = layout
        mesh = slice_mesh(s, w)
        tree = stacked_tree(s * w)
        ref = comms.make_hier_host_aggregator(
            mesh, topology=topo, how=how, local_weight=0.3)(tree)
        out = comms.make_hier_host_sync(
            mesh, topology=topo, how=how, local_weight=0.3,
            bucket_bytes=TINY_BUCKET)(tree)[0]
        for key in SHAPES:
            assert np.array_equal(np.asarray(ref[key]),
                                  np.asarray(out[key])), (layout, topo,
                                                          how, key)

    def test_semantics_match_numpy_gossip_of_means(self):
        # the reference itself is pinned against plain numpy: slice
        # means then the ring blend, per element (fp32 tolerance — the
        # np summation order is not the XLA reduction's)
        s, w = 2, 4
        mesh = slice_mesh(s, w)
        tree = stacked_tree(s * w)
        out = comms.make_hier_host_sync(
            mesh, topology="ring", bucket_bytes=TINY_BUCKET)(tree)[0]
        for key in SHAPES:
            x = np.asarray(tree[key], np.float64).reshape(
                s, w, *SHAPES[key])
            m = x.mean(1)
            ref = np.stack([(m[i] + m[(i - 1) % s]) / 2.0
                            for i in range(s)])
            got = np.asarray(out[key], np.float64).reshape(
                s, w, *SHAPES[key])
            assert np.allclose(got, ref[:, None], atol=1e-5), key

    def test_resident_rows_gather_to_the_replicated_output(self):
        # PR 11 composition: the resident program ends at the inner
        # scatter; gathering its rows over the data axis reproduces the
        # replicated program's output bit-for-bit, per slice
        mesh = slice_mesh(2, 4)
        tree = stacked_tree(8)
        rep = comms.make_hier_host_sync(
            mesh, topology="ring", bucket_bytes=TINY_BUCKET)(tree)[0]
        res = comms.make_hier_host_sync(
            mesh, topology="ring", bucket_bytes=TINY_BUCKET,
            residency="resident")(tree)[0]
        gathered = comms.make_resident_gather(
            mesh, per_worker_struct(tree), bucket_bytes=TINY_BUCKET)(res)
        assert trees_equal(rep, gathered)
        # and the resident state is exactly 1/W per worker: each row is
        # padded/W elements of the padded consensus vector
        plan = comms.bucket_plan(
            list(per_worker_struct(tree).values()), 4, TINY_BUCKET)
        for i, b in enumerate(plan):
            rows = np.asarray(res[comms._bucket_name(i)])
            assert rows.shape == (8, b.padded // 4)

    def test_weighted_one_slice_limit_form(self):
        # the weighted blend's 1-slice limit IS the flat weighted
        # allreduce: w*own + (1-w)*(total-own)/(n-1) — checked against
        # the flat engine on the same worker count
        mesh_flat = mesh_lib.build_mesh({"data": 4},
                                        devices=jax.devices()[:4])
        tree = stacked_tree(4)
        flat = comms.make_host_sync(
            mesh_flat, mode="sharded", how="weighted",
            local_weight=0.3, bucket_bytes=TINY_BUCKET)(tree)[0]
        # hierarchical weighted with S=1 is not a built engine path
        # (config resolves flat at 1 slice); evaluate the REFERENCE
        # expression instead: g == m at S=1, so out = w*x +
        # (1-w)*(W*m - x)/(W-1)
        m = {k: np.asarray(tree[k], np.float64).mean(0) for k in SHAPES}
        for key in SHAPES:
            x = np.asarray(tree[key], np.float64)
            want = 0.3 * x + 0.7 * (4 * m[key][None] - x) / 3
            assert np.allclose(np.asarray(flat[key], np.float64), want,
                               atol=1e-5), key


# --------------------------------------------------------------------------
# Wire-byte accounting (ISSUE 13 satellite — the exactness twin also
# rides tests/test_sync.py's accounting class)
# --------------------------------------------------------------------------
class TestHierWireBytes:
    def tree(self):
        return {k: jax.ShapeDtypeStruct(v, jnp.float32)
                for k, v in SHAPES.items()}

    @pytest.mark.parametrize("topo,hops", [("ring", 1),
                                           ("double_ring", 2)])
    def test_dcn_bytes_exactly_shard_rows_per_hop(self, topo, hops):
        w = 4
        split = comms.hier_wire_bytes(self.tree(), w, topology=topo,
                                      bucket_bytes=TINY_BUCKET)
        plan = comms.bucket_plan(list(self.tree().values()), w,
                                 TINY_BUCKET)
        assert split["dcn"] == hops * sum(
            (b.padded // w) * 4 for b in plan)
        # inner bytes: unchanged from the flat sharded engine at W
        assert split["ici"] == comms.sync_wire_bytes(
            self.tree(), w, mode="sharded", wire_dtype=jnp.float32,
            bucket_bytes=TINY_BUCKET)

    def test_dcn_is_one_over_n_inner_of_flat_gossip(self):
        # W-divisible leaves => no padding => the ratio is EXACT
        tree = {"a": jax.ShapeDtypeStruct((64, 4), jnp.float32),
                "b": jax.ShapeDtypeStruct((128,), jnp.float32)}
        for w in (2, 4):
            for topo in ("ring", "double_ring"):
                split = comms.hier_wire_bytes(
                    tree, w, topology=topo, bucket_bytes=TINY_BUCKET)
                flat = comms.sync_wire_bytes(
                    tree, 8, mode="gossip", wire_dtype=jnp.float32,
                    bucket_bytes=TINY_BUCKET, topology=topo)
                assert split["dcn"] * w == flat, (w, topo)

    def test_compressed_outer_wire_halves_and_quarters(self):
        fp32 = comms.hier_wire_bytes(self.tree(), 4, topology="ring",
                                     bucket_bytes=TINY_BUCKET)
        bf16 = comms.hier_wire_bytes(self.tree(), 4, topology="ring",
                                     outer_wire_dtype=jnp.bfloat16,
                                     bucket_bytes=TINY_BUCKET)
        int8 = comms.hier_wire_bytes(self.tree(), 4, topology="ring",
                                     outer_wire_dtype=jnp.int8,
                                     bucket_bytes=TINY_BUCKET)
        assert bf16["dcn"] * 2 == fp32["dcn"]
        assert int8["dcn"] * 4 == fp32["dcn"]
        # outer wire leaves the inner level untouched
        assert bf16["ici"] == fp32["ici"] == int8["ici"]


# --------------------------------------------------------------------------
# Per-level error feedback
# --------------------------------------------------------------------------
class TestHierEF:
    def test_engine_arms_ef_per_level(self):
        model = get_model("mlp", num_classes=10, hidden=8)
        mesh = slice_mesh(2, 2)
        e = LocalSGDEngine(model, mesh, hier_cfg(
            sync_dtype_outer="int8", sync_compression="ef"))
        assert not e.sync_ef and e.sync_ef_outer
        e = LocalSGDEngine(model, mesh, hier_cfg(
            sync_dtype="bfloat16", sync_compression="ef"))
        assert e.sync_ef and e.sync_ef_outer   # outer inherits bf16
        e = LocalSGDEngine(model, mesh, hier_cfg(
            sync_dtype="bfloat16", sync_dtype_outer="float32",
            sync_compression="ef"))
        assert e.sync_ef and not e.sync_ef_outer

    def test_outer_ef_single_sync_drift_and_residual(self):
        s, w = 2, 4
        mesh = slice_mesh(s, w)
        tree = stacked_tree(s * w)
        ref = comms.make_hier_host_aggregator(mesh, topology="ring")(tree)
        ores = comms.hier_outer_residual_init(
            per_worker_struct(tree), w, s * w, bucket_bytes=TINY_BUCKET)
        out, _res, nores = comms.make_hier_host_sync(
            mesh, topology="ring", outer_wire_dtype=jnp.bfloat16,
            bucket_bytes=TINY_BUCKET)(tree, None, ores)
        err = max(float(np.abs(np.asarray(out[k], np.float32)
                               - np.asarray(ref[k], np.float32)).max())
                  for k in SHAPES)
        assert 0 < err < 0.05   # one bf16 rounding of the neighbor term
        assert any(float(np.abs(np.asarray(v)).max()) > 0
                   for v in jax.tree_util.tree_leaves(nores))

    def test_outer_ef_time_average_tracks_fp32(self):
        # drifting-consensus regime: with EF the int8-outer iterate's
        # time average stays near the fp32 path where the uncompensated
        # wire's rounding bias accumulates
        s, w = 2, 2
        mesh = slice_mesh(s, w)
        rng = np.random.default_rng(0)
        base = jnp.asarray(rng.normal(size=(4, 256)) * 50, jnp.float32)
        step = jnp.asarray(rng.uniform(0.01, 0.03, (4, 256)), jnp.float32)
        ref_fn = comms.make_hier_host_aggregator(mesh, topology="ring")
        comp_fn = comms.make_hier_host_sync(
            mesh, topology="ring", outer_wire_dtype=jnp.int8,
            bucket_bytes=TINY_BUCKET)
        tmpl = per_worker_struct({"p": base})
        add = jax.jit(lambda t: {"p": t["p"] + step})
        p_ref = p_ef = p_raw = {"p": base}
        r_ef = comms.hier_outer_residual_init({"p": tmpl["p"]}, w, s * w,
                                              bucket_bytes=TINY_BUCKET)
        err_ef = err_raw = 0.0
        rounds = 30
        for _ in range(rounds):
            p_ref = jax.block_until_ready(ref_fn(add(p_ref)))
            out, _r, r_ef = comp_fn(add(p_ef), None, r_ef)
            p_ef = jax.block_until_ready(out)
            p_raw = jax.block_until_ready(
                comp_fn(add(p_raw))[0])
            err_ef += float(np.abs(np.asarray(p_ef["p"])
                                   - np.asarray(p_ref["p"])).mean())
            err_raw += float(np.abs(np.asarray(p_raw["p"])
                                    - np.asarray(p_ref["p"])).mean())
        assert err_ef < err_raw, (err_ef, err_raw)


# --------------------------------------------------------------------------
# Engine-level rounds on the hierarchical mesh
# --------------------------------------------------------------------------
def make_packs(n, steps=4, b=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, steps, b, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, (n, steps, b)).astype(np.int32)
    m = np.ones((n, steps, b), np.float32)
    return x, y, m


class TestHierEngineRound:
    def _round(self, mesh, cfg, n):
        model = get_model("mlp", num_classes=10, hidden=16)
        eng = LocalSGDEngine(model, mesh, cfg)
        x, y, m = make_packs(n)
        st = eng.init_state(jax.random.key(0), x[0, 0])
        st, mx = eng.round(st, (x, y, m), (x, y, m))
        return eng, st, mx

    def test_weights_round_is_gossip_of_means_of_presync_params(self):
        # gradients mode leaves params untouched by the sync (reference
        # aggregate-and-discard semantics), so its post-round params ARE
        # the pre-sync per-worker params of the identically-seeded
        # weights-mode round — the engine-level bitwise gate applies the
        # dense gossip-of-means reference to them
        mesh = slice_mesh(2, 2)
        _, st_pre, mx_g = self._round(
            mesh, hier_cfg(aggregation_by="gradients"), 4)
        assert float(np.asarray(mx_g["agg_grad_norm"]).ravel()[0]) > 0
        eng, st_w, _ = self._round(
            mesh, hier_cfg(param_residency="replicated"), 4)
        assert eng.sync_mode == "hier"
        assert st_w.params is not None
        ref = comms.make_hier_host_aggregator(
            mesh, topology="ring")(st_pre.params)
        assert trees_equal(ref, st_w.params)

    def test_round_telemetry_carries_per_level_split(self):
        mesh = slice_mesh(2, 2)
        eng, _st, _mx = self._round(mesh, hier_cfg(), 4)
        stats = eng.last_sync_stats
        assert stats["sync_mode"] == "hier"
        split = comms.hier_wire_bytes(
            eng.params_template, 2, topology="ring",
            wire_dtype=jnp.float32, outer_wire_dtype=jnp.float32,
            bucket_bytes=eng.sync_bucket_bytes)
        assert stats["sync_bytes_ici"] == split["ici"]
        assert stats["sync_bytes_dcn"] == split["dcn"]
        assert stats["sync_bytes"] == split["ici"] + split["dcn"]

    def test_resident_round_matches_replicated_twin(self):
        mesh = slice_mesh(2, 2)
        eng_r, st_r, _ = self._round(
            mesh, hier_cfg(param_residency="resident"), 4)
        assert eng_r.resident_on and st_r.params is None
        eng_w, st_w, _ = self._round(
            mesh, hier_cfg(param_residency="replicated"), 4)
        vr = eng_r.rank0_variables(st_r)
        vw = eng_w.rank0_variables(st_w)
        assert trees_equal(vr["params"], vw["params"])
        # per-worker resident params are exactly 1/W of the padded
        # gathered peak (the ISSUE 13 composition contract: 1/N_inner)
        by = eng_r.state_resident_bytes(st_r)
        assert by["params"] * 2 == by["params_gathered_peak"]


# --------------------------------------------------------------------------
# Cross-slice checkpoint re-layouts (MANIFEST records slice topology)
# --------------------------------------------------------------------------
class TestHierCheckpoint:
    def _engine_state(self, s, w, tmp, **cfg_kw):
        cfg = hier_cfg(num_slices=s, checkpoint_dir=str(tmp), **cfg_kw)
        model = get_model("mlp", num_classes=10, hidden=8)
        eng = LocalSGDEngine(model, slice_mesh(s, w), cfg)
        x, _y, _m = make_packs(s * w, steps=1, b=4)
        st = eng.init_state(jax.random.key(0), x[0, 0])
        return cfg, eng, st

    def _save(self, tmp, eng, st, num_slices):
        e = ckpt_lib.CheckpointEngine(
            str(tmp), async_write=False,
            metadata={"sync_bucket_mb": eng.cfg.sync_bucket_mb,
                      "num_slices": num_slices,
                      "param_residency": eng.param_residency})
        e.save(eng.checkpoint_fence(st), 1)
        e.close()
        return e.latest_checkpoint()

    def test_manifest_records_slice_topology(self, tmp_path):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import checkpoint_metadata
        meta = checkpoint_metadata(hier_cfg(), 10, False)
        assert meta["num_slices"] == 2

    def test_same_topology_roundtrip_bitwise(self, tmp_path):
        _cfg, eng, st = self._engine_state(2, 2, tmp_path)
        path = self._save(tmp_path, eng, st, 2)
        assert ckpt_lib.manifest_metadata(path)["num_slices"] == 2
        restored, ep = ckpt_lib.restore_checkpoint(
            path, st, params_template=eng.params_template,
            bucket_bytes=eng.sync_bucket_bytes, num_slices=2)
        assert ep == 1
        assert trees_equal(st.params_resident, restored.params_resident)

    def test_flat_resident_restores_into_hier_layout(self, tmp_path):
        # a flat checkpoint is a GLOBAL consensus: every slice adopts it
        flat_cfg = hier_cfg(num_slices=1, topology="allreduce",
                            sync_mode="sharded")
        model = get_model("mlp", num_classes=10, hidden=8)
        mesh_flat = mesh_lib.build_mesh({"data": 4},
                                        devices=jax.devices()[:4])
        eng_f = LocalSGDEngine(model, mesh_flat, flat_cfg)
        x, _y, _m = make_packs(4, steps=1, b=4)
        st_f = eng_f.init_state(jax.random.key(0), x[0, 0])
        assert eng_f.resident_on
        path = self._save(tmp_path, eng_f, st_f, 1)
        _cfg, eng_h, st_h = self._engine_state(2, 2, tmp_path / "h")
        restored, _ep = ckpt_lib.restore_checkpoint(
            path, st_h, params_template=eng_h.params_template,
            bucket_bytes=eng_h.sync_bucket_bytes, num_slices=2)
        # both slices carry the flat consensus: the hier engine's rank0
        # reconstruction equals the flat one's
        v_f = eng_f.rank0_variables(st_f)
        v_h = eng_h.rank0_variables(eng_h.stage_state(restored))
        assert trees_equal(v_f["params"], v_h["params"])

    def test_distinct_per_slice_consensus_refuses_recount(self, tmp_path):
        _cfg, eng, st = self._engine_state(2, 2, tmp_path)
        # make the two slices' consensuses DIFFER (post-gossip reality):
        # perturb slice 1's rows in every resident bucket
        pr = {k: np.asarray(v).copy()
              for k, v in jax.device_get(st.params_resident).items()}
        for k in pr:
            pr[k][2:] += 1.0
        st = st.replace(params_resident=jax.device_put(pr))
        st = eng.stage_state(jax.device_get(st))
        path = self._save(tmp_path, eng, st, 2)
        flat_cfg = hier_cfg(num_slices=1, topology="allreduce",
                            sync_mode="sharded")
        mesh_flat = mesh_lib.build_mesh({"data": 4},
                                        devices=jax.devices()[:4])
        model = get_model("mlp", num_classes=10, hidden=8)
        eng_f = LocalSGDEngine(model, mesh_flat, flat_cfg)
        x, _y, _m = make_packs(4, steps=1, b=4)
        st_f = eng_f.init_state(jax.random.key(0), x[0, 0])
        with pytest.raises(ValueError, match="cannot re-shard"):
            ckpt_lib.restore_checkpoint(
                path, st_f, params_template=eng_f.params_template,
                bucket_bytes=eng_f.sync_bucket_bytes, num_slices=1)

    def test_hier_resident_restores_into_replicated_per_slice(
            self, tmp_path):
        _cfg, eng, st = self._engine_state(2, 2, tmp_path)
        pr = {k: np.asarray(v).copy()
              for k, v in jax.device_get(st.params_resident).items()}
        for k in pr:
            pr[k][2:] += 1.0
        st = eng.stage_state(
            jax.device_get(st).replace(params_resident=pr))
        path = self._save(tmp_path, eng, st, 2)
        # replicated template on the same hier mesh: every worker row
        # must carry ITS OWN slice's consensus
        _c2, eng_rep, st_rep = self._engine_state(
            2, 2, tmp_path / "r", param_residency="replicated")
        restored, _ep = ckpt_lib.restore_checkpoint(
            path, st_rep, params_template=eng_rep.params_template,
            bucket_bytes=eng_rep.sync_bucket_bytes, num_slices=2)
        for leaf in jax.tree_util.tree_leaves(restored.params):
            arr = np.asarray(leaf)
            # rows agree within each slice group...
            assert np.array_equal(arr[0], arr[1])
            assert np.array_equal(arr[2], arr[3])
            # ...and differ across the groups (the +1 perturbation)
            assert not np.array_equal(arr[0], arr[2])

    def test_serve_loads_slice0_consensus_from_hier_resident(
            self, tmp_path):
        # the serve consumer's rank-0 convention on a hierarchical
        # resident checkpoint: slice 0's consensus, template-free from
        # the manifest metadata (ISSUE 13 x the PR 12 serve satellite)
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import checkpoint_metadata
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.serve import engine as serve_engine
        cfg, eng, st = self._engine_state(2, 2, tmp_path)
        meta = checkpoint_metadata(cfg, 10, False,
                                   param_residency=eng.param_residency,
                                   params_template=eng.params_template)
        e = ckpt_lib.CheckpointEngine(str(tmp_path), async_write=False,
                                      metadata=meta)
        e.save(eng.checkpoint_fence(st), 1)
        e.close()
        path = e.latest_checkpoint()
        got = serve_engine.load_params_resident(
            path, ckpt_lib.manifest_metadata(path))
        assert trees_equal(got, eng.rank0_variables(st)["params"])

    def test_missing_outer_residual_restores_zeros(self, tmp_path):
        # pre-ISSUE-13 checkpoint (no outer residual) into an
        # outer-EF-armed run: fresh zero rows, like absent round_opt
        _cfg, eng, st = self._engine_state(2, 2, tmp_path)
        path = self._save(tmp_path, eng, st, 2)
        _c2, eng_ef, st_ef = self._engine_state(
            2, 2, tmp_path / "ef", sync_dtype_outer="int8",
            sync_compression="ef")
        assert st_ef.sync_residual_outer is not None
        restored, _ep = ckpt_lib.restore_checkpoint(
            path, st_ef, params_template=eng_ef.params_template,
            bucket_bytes=eng_ef.sync_bucket_bytes, num_slices=2)
        for leaf in jax.tree_util.tree_leaves(restored.sync_residual_outer):
            assert float(np.abs(np.asarray(leaf)).max()) == 0.0


# --------------------------------------------------------------------------
# Driver-level S x W sweeps — slow-marked up front per the ROADMAP
# tier-1 wall-headroom rule (the sanitized 2x2 CLI smoke lives in
# tools/verify.sh)
# --------------------------------------------------------------------------
@pytest.mark.slow
class TestHierDriverMatrix:
    def _run(self, **kw):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        base = dict(epochs_local=1, epochs_global=3, num_workers=2,
                    limit_train_samples=256, limit_eval_samples=64,
                    sanitize=True)
        base.update(kw)
        return train_global(hier_cfg(**base), progress=False)

    @pytest.mark.parametrize("layout", LAYOUTS)
    @pytest.mark.parametrize("topo", ["ring", "double_ring"])
    def test_sanitized_driver_layout_matrix(self, layout, topo):
        s, w = layout
        res = self._run(num_slices=s, num_workers=w, topology=topo)
        san = res["sanitize"]
        assert san["retrace_count"] == 0
        assert san["recompile_count"] == 0
        assert san["transfer_guard_violations"] == 0
        assert res["sync_engine"]["mode"] == "hier"
        assert res["sync_engine"]["num_slices"] == s
        assert res["round_timings"][1]["sync_bytes_dcn"] > 0

    def test_streamed_round_matches_packed(self):
        # the streamed path shares the standalone donated sync program
        # (and, resident, the slice-aware enter gather) — its hier
        # trajectory must match the packed round's exactly
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        kw = dict(epochs_local=1, epochs_global=2, num_workers=2,
                  limit_train_samples=256, limit_eval_samples=64,
                  batch_size=8)
        packed = train_global(hier_cfg(**kw), progress=False)
        streamed = train_global(hier_cfg(stream_chunk_steps=2, **kw),
                                progress=False)
        np.testing.assert_allclose(streamed["global_train_losses"],
                                   packed["global_train_losses"],
                                   rtol=1e-5)
        assert streamed["sync_engine"]["mode"] == "hier"

    @pytest.mark.parametrize("how", ["equal", "weighted"])
    def test_driver_equal_weighted_consensus(self, how):
        res = self._run(aggregation_type=how,
                        local_weight=0.4 if how == "weighted" else 0.5)
        assert res["sanitize"]["retrace_count"] == 0
        assert np.isfinite(res["global_val_losses"]).all()

    def test_driver_compressed_dcn_wire_with_ef(self):
        res = self._run(sync_dtype_outer="int8", sync_compression="ef")
        rt = res["round_timings"][1]
        fp = self._run()
        assert rt["sync_bytes_dcn"] * 4 == \
            fp["round_timings"][1]["sync_bytes_dcn"]
        assert rt["sync_bytes_ici"] == \
            fp["round_timings"][1]["sync_bytes_ici"]
