"""REAL multi-host execution: two OS processes, one global 8-worker mesh.

The reference validates its multi-node path by actually launching N
processes (``torchrun``/``mpirun`` with ``MASTER_ADDR=localhost``,
``Balanced All-Reduce/main.py:14``); this is the JAX twin — two processes
join a coordination-service rendezvous on CPU (4 virtual devices each) and
run the full driver: probe ``process_allgather``, cross-process data feed
(``make_array_from_process_local_data``), the compiled round with its
cross-host collectives, replicated metric fetch, measured-wall exchange,
and the collective multi-host checkpoint save.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_driver_run(tmp_path):
    port = _free_port()
    env_base = {k: v for k, v in os.environ.items()
                if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = []
    for pid in range(2):
        env = dict(
            env_base,
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
            MH_CKPT_DIR=str(tmp_path / f"ckpt{pid}"),
        )
        procs.append(subprocess.Popen(
            [sys.executable, WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-host worker timed out")
        assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
        outs.append(out)

    results = {}
    for out in outs:
        line = [ln for ln in out.splitlines() if ln.startswith("MHRESULT ")]
        assert line, out[-2000:]
        r = json.loads(line[-1][len("MHRESULT "):])
        results[r["process"]] = r

    assert set(results) == {0, 1}
    # every process must observe the SAME global metrics (the reference's
    # all-reduced epoch means), and training must make progress
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)
    np.testing.assert_allclose(results[0]["val_losses"],
                               results[1]["val_losses"], rtol=1e-6)
    losses = results[0]["losses"]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
    # both hosts wrote the collective checkpoint
    for pid in range(2):
        files = os.listdir(tmp_path / f"ckpt{pid}")
        assert any(f.startswith("ckpt_") for f in files), files
