"""Layer-scan compile engine (ISSUE 3 tentpole).

Covers the contract end to end: the scanned stack TRACES its block once
(not once per layer — the compile-count regression the engine exists
for), compiles to exactly one cached executable (asserted via the PR 2
persistent-cache counter), and computes the bit-identical forward to the
unrolled twin on transplanted parameters; the named remat policies
shrink the autodiff residuals monotonically while preserving numerics;
microbatch gradient accumulation matches the full-batch step within fp32
summation tolerance at K in {2, 4} and IS the unmodified step at K=1;
and the driver wires/validates the --layer_scan / --remat_policy /
--grad_accum surface.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import LocalSGDEngine

VOCAB, L_SEQ, DEPTH = 97, 16, 4


def tokens(b=2, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, VOCAB, (b, L_SEQ)), jnp.int32)


def build(scan, depth=DEPTH, **kw):
    return get_model("gpt_tiny", num_classes=VOCAB, num_layers=depth,
                     max_len=L_SEQ, scan_layers=scan, **kw)


def transplant(unrolled_params, depth=DEPTH):
    """Unrolled ``layer{i}`` subtrees -> the scanned stacked layout."""
    stacked = jax.tree_util.tree_map(
        lambda *ls: jnp.stack(ls),
        *[unrolled_params[f"layer{i}"] for i in range(depth)])
    out = {k: v for k, v in unrolled_params.items()
           if not k.startswith("layer")}
    out["layers"] = {"layer": stacked}
    return out


class TestTraceCount:
    """The compile-cost mechanism itself: under ``nn.scan`` the block
    body is traced ONCE regardless of depth; unrolled, once per layer."""

    def _count_block_traces(self, scan, depth):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import gpt

        calls = {"n": 0}
        orig = gpt.GPTBlock.__call__

        def counting(self, *a, **kw):
            calls["n"] += 1
            return orig(self, *a, **kw)

        gpt.GPTBlock.__call__ = counting
        try:
            m = build(scan, depth)
            x = tokens()
            params = m.init(jax.random.key(0), x, train=False)["params"]
            calls["n"] = 0
            jax.make_jaxpr(
                lambda p: m.apply({"params": p}, x, train=True))(params)
        finally:
            gpt.GPTBlock.__call__ = orig
        return calls["n"]

    def test_scanned_trace_count_is_depth_independent(self):
        # nn.scan traces the block a small CONSTANT number of times
        # (once to lift variables, once for the jaxpr); unrolled, the
        # count is the layer count — the linear-in-depth compile cost
        # the engine removes
        scan4 = self._count_block_traces(scan=True, depth=DEPTH)
        scan8 = self._count_block_traces(scan=True, depth=2 * DEPTH)
        assert scan4 == scan8 <= 2, (scan4, scan8)
        assert self._count_block_traces(scan=False, depth=DEPTH) == DEPTH
        assert self._count_block_traces(
            scan=False, depth=2 * DEPTH) == 2 * DEPTH

    def test_one_cached_executable_for_the_stack(self, tmp_path):
        """ONE jit entry for the whole scanned stack, via the PR 2
        persistent-cache counter: compiling the scanned train forward
        registers exactly one cache miss (one executable), and an
        identical fresh jit is served as one hit."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.xla_flags import (
            compile_cache_counts,
            setup_compile_cache,
        )
        if not setup_compile_cache(str(tmp_path), min_compile_secs=0.0):
            pytest.skip("persistent compile cache unavailable")
        try:
            m = build(True, depth=8)
            x = tokens()
            params = jax.jit(
                lambda k: m.init(k, x, train=False))(jax.random.key(0))
            before = compile_cache_counts()
            jax.jit(lambda p: m.apply(p, x, train=True)).lower(
                params).compile()
            mid = compile_cache_counts()
            assert mid["misses"] - before["misses"] == 1
            # a DISTINCT function object with the identical HLO: jax's
            # in-memory executable dedupe cannot serve it, so the compile
            # goes to the persistent cache and must HIT
            jax.jit(lambda p: m.apply(p, x, train=True)).lower(
                params).compile()
            after = compile_cache_counts()
            assert after["hits"] - mid["hits"] == 1
            assert after["misses"] == mid["misses"]
        finally:
            # un-latch the tmp cache (jax initializes the cache object
            # once — clearing the config dir alone would leave every
            # later compile in this process hitting the tmp cache:
            # phantom hit/miss deltas in the driver-telemetry tests
            # downstream), then RESTORE the session cache if the suite
            # opted into one via JAX_GRAFT_TEST_COMPILE_CACHE
            import os

            from learning_deep_neural_network_in_distributed_computing_environment_tpu.xla_flags import (
                reset_cache_latch,
            )
            session_dir = os.environ.get("JAX_GRAFT_TEST_COMPILE_CACHE", "")
            if session_dir:
                setup_compile_cache(session_dir, min_compile_secs=0.5)
            else:
                jax.config.update("jax_compilation_cache_dir", None)
                reset_cache_latch()


class TestScanVsUnrolled:
    def test_forward_bitwise_on_transplanted_params(self):
        mu, ms = build(False), build(True)
        x = tokens()
        pu = mu.init(jax.random.key(1), x, train=False)["params"]
        pt = transplant(pu)
        # compare the COMPILED programs (what training runs): eager
        # op-by-op dispatch fuses differently and drifts ~1e-7
        ou = jax.jit(lambda p: mu.apply({"params": p}, x, train=True))(pu)
        os_ = jax.jit(lambda p: ms.apply({"params": p}, x, train=True))(pt)
        assert np.array_equal(np.asarray(ou), np.asarray(os_))

    def test_grads_match_within_float_rounding(self):
        mu, ms = build(False), build(True)
        x = tokens()
        pu = mu.init(jax.random.key(1), x, train=False)["params"]
        pt = transplant(pu)

        def loss(m, p):
            return (m.apply({"params": p}, x,
                            train=True).astype(jnp.float32) ** 2).sum()

        gu = jax.grad(lambda p: loss(mu, p))(pu)
        gs = jax.grad(lambda p: loss(ms, p))(pt)
        gus = transplant(gu)
        for a, b in zip(jax.tree_util.tree_leaves(gus),
                        jax.tree_util.tree_leaves(gs)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=1e-5)


class TestRematPolicies:
    def test_residuals_shrink_monotonically_numerics_hold(self):
        """dots_saveable keeps matmul outputs (fewer residual bytes than
        no-remat), everything keeps only block boundaries (fewest);
        all three compute the same function."""
        x = tokens(b=4)
        outs, sizes = {}, {}
        params = None
        for policy in ("none", "dots_saveable", "everything"):
            m = build(True, remat_policy=policy)
            if params is None:
                params = m.init(jax.random.key(0), x,
                                train=False)["params"]
            out, vjp_fn = jax.vjp(
                lambda p: m.apply({"params": p}, x, train=True), params)
            outs[policy] = out
            sizes[policy] = sum(l.nbytes for l in
                                jax.tree_util.tree_leaves(vjp_fn))
        np.testing.assert_allclose(outs["dots_saveable"], outs["none"],
                                   atol=1e-6)
        np.testing.assert_allclose(outs["everything"], outs["none"],
                                   atol=1e-6)
        assert sizes["everything"] < sizes["dots_saveable"] < sizes["none"], \
            sizes

    def test_legacy_remat_bool_is_everything_alias(self):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models.bert import (
            resolve_remat_policy,
        )
        assert resolve_remat_policy(True, None) == "everything"
        assert resolve_remat_policy(False, None) is None
        assert resolve_remat_policy(False, "none") is None
        assert resolve_remat_policy(True, "dots_saveable") == "dots_saveable"


# The two grad-accum equivalence cases and the auto-scan driver-surface
# case below are the tier-1 suite's heaviest engine-compile cases (~30 s,
# ~18 s and ~11 s of fresh K-variant round-program compiles on the CI
# host — ISSUE 11 satellite measurement); they ride the slow tier, whose
# runs also reuse the JAX_GRAFT_TEST_COMPILE_CACHE verify.sh now arms.
class TestGradAccum:
    """--grad_accum K: scan K microbatches with an fp32 grad carry.
    K in {2, 4} matches the full-batch round within fp32 summation
    tolerance; K=1 takes the UNMODIFIED step path (bit-identical by
    construction, asserted through the round program)."""

    def _round(self, mesh, grad_accum):
        cfg = Config(model="gpt_tiny", dataset="synthetic_lm",
                     epochs_local=1, batch_size=8,
                     compute_dtype="float32", augment=False,
                     aggregation_by="weights", grad_accum=grad_accum)
        model = get_model("gpt_tiny", num_classes=VOCAB, max_len=L_SEQ)
        engine = LocalSGDEngine(model, mesh, cfg)
        rng = np.random.default_rng(0)
        n, s, b = 2, 2, 8
        x = rng.integers(0, VOCAB, (n, s, b, L_SEQ)).astype(np.int32)
        y = rng.integers(0, VOCAB, (n, s, b, L_SEQ)).astype(np.int32)
        m = np.ones((n, s, b), np.float32)
        state = engine.init_state(jax.random.key(0), x[0, 0])
        state, mx = engine.round(state, (x, y, m),
                                 (x[:, :1], y[:, :1], m[:, :1]))
        return state, mx

    @pytest.fixture(scope="class")
    def mesh2(self, devices):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu import (
            mesh as mesh_lib,
        )
        return mesh_lib.build_mesh({"data": 2}, devices=devices[:2])

    @pytest.mark.slow
    def test_accumulation_matches_full_batch(self, mesh2):
        base_state, base_mx = self._round(mesh2, grad_accum=1)
        for k in (2, 4):
            state, mx = self._round(mesh2, grad_accum=k)
            np.testing.assert_allclose(
                np.asarray(mx["train_loss"]),
                np.asarray(base_mx["train_loss"]), rtol=0, atol=5e-6,
                err_msg=f"grad_accum={k}")
            for a, b in zip(jax.tree_util.tree_leaves(base_state.params),
                            jax.tree_util.tree_leaves(state.params)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-4, atol=2e-5,
                                           err_msg=f"grad_accum={k}")

    @pytest.mark.slow
    def test_masked_batches_keep_denominator_semantics(self, mesh2):
        """Partially-masked steps: the accumulation denominator is the
        FULL-step masked weight, so uneven per-slice masses still sum to
        the full-batch masked mean."""
        cfg1 = Config(model="gpt_tiny", dataset="synthetic_lm",
                      epochs_local=1, batch_size=8,
                      compute_dtype="float32", augment=False,
                      aggregation_by="weights", grad_accum=1)
        cfg2 = cfg1.replace(grad_accum=2)
        model = get_model("gpt_tiny", num_classes=VOCAB, max_len=L_SEQ)
        rng = np.random.default_rng(1)
        n, s, b = 2, 1, 8
        x = rng.integers(0, VOCAB, (n, s, b, L_SEQ)).astype(np.int32)
        y = rng.integers(0, VOCAB, (n, s, b, L_SEQ)).astype(np.int32)
        m = np.ones((n, s, b), np.float32)
        m[:, :, 5:] = 0.0  # slice 2 of K=2 is 3/4 padding
        outs = {}
        for cfg in (cfg1, cfg2):
            engine = LocalSGDEngine(model, mesh2, cfg)
            state = engine.init_state(jax.random.key(0), x[0, 0])
            _, mx = engine.round(state, (x, y, m), (x, y, m))
            outs[cfg.grad_accum] = np.asarray(mx["train_loss"])
        np.testing.assert_allclose(outs[2], outs[1], rtol=0, atol=5e-6)


class TestDriverSurface:
    def _cfg(self, **kw):
        base = dict(model="gpt_tiny", dataset="synthetic_lm",
                    limit_train_samples=64, limit_eval_samples=16,
                    augment=False)
        base.update(kw)
        return Config(**base)

    def _expect_raises(self, mesh_axes, match, **kw):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu import (
            mesh as mesh_lib,
        )
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import (
            train_global,
        )
        mesh = mesh_lib.build_mesh(mesh_axes)
        with pytest.raises(ValueError, match=match):
            train_global(self._cfg(**kw), mesh=mesh, progress=False)

    def test_layer_scan_on_rejects_heterogeneous_models(self):
        self._expect_raises({"data": 2}, "homogeneous",
                            model="mlp", dataset="mnist", layer_scan="on")

    def test_layer_scan_off_rejects_pipe_axis(self):
        self._expect_raises({"data": 2, "pipe": 2}, "layer_scan off",
                            layer_scan="off")

    def test_remat_policy_requires_scanned_stack(self):
        self._expect_raises({"data": 2}, "remat_policy",
                            model="mlp", dataset="mnist",
                            remat_policy="dots_saveable")

    def test_grad_accum_rejects_batchnorm_models(self):
        self._expect_raises({"data": 2}, "grad_accum",
                            model="enhanced_cnn", dataset="cifar10",
                            batch_size=8, grad_accum=2)

    def test_grad_accum_must_divide_batch(self):
        with pytest.raises(ValueError, match="grad_accum"):
            Config(batch_size=8, grad_accum=3)

    def test_pp_remat_without_pipe_axis_points_at_remat_policy(self):
        self._expect_raises({"data": 2}, "remat_policy", pp_remat=True)

    @pytest.mark.slow
    def test_auto_scan_stacks_attention_models(self, mesh8):
        """The auto default: a driver-built attention model carries the
        stacked ``layers`` collection (and the engine state mirrors it)."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import (
            train_global,
        )
        res = train_global(
            self._cfg(epochs_global=1, epochs_local=1, batch_size=8,
                      compute_dtype="float32",
                      aggregation_by="weights"),
            mesh=mesh8, progress=False,
            simulated_durations=np.ones(8))
        assert "layers" in res["state"].params
        assert not any(k.startswith("layer0")
                       for k in res["state"].params)
