"""Scenario lab (ISSUE 14): the vmap'd many-worker simulator.

The tentpole gate — fp32 N=8 simulated rounds BITWISE-identical to N=8
real-mesh rounds across all three topologies x equal/weighted, under
--sanitize with zero post-warmup retraces — plus:

- comms level: ``aggregate_sim`` (stacked math, no mesh) vs the dense
  reference path inside shard_map, unmasked bitwise + the participation
  mask vs the poison screen;
- engine level: a whole SimEngine round vs a whole LocalSGDEngine round
  on the 8-device mesh, weights AND gradients aggregation;
- driver level: sanitized e2e parity (tier-1 keeps one combo per
  topology; the full 6-combo matrix and the paper's 2x3 grid are
  slow-marked);
- the scenario surface: sampling/dropout/byzantine/lr-jitter semantics,
  and the guarantee that scenario knobs at their DEFAULTS never perturb
  the parity gate (all-ones masks select the unscreened arithmetic);
- scale: N >> device count in one jit on one chip.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from learning_deep_neural_network_in_distributed_computing_environment_tpu import (
    comms,
    mesh as mesh_lib,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.compat import (
    shard_map,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
from learning_deep_neural_network_in_distributed_computing_environment_tpu.sim import SimEngine
from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import (
    LocalSGDEngine,
)

N = 8
TOPOS = ("allreduce", "ring", "double_ring")
HOWS = ("equal", "weighted")


def stacked_tree(n=N, seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    shapes = {"a": (13, 7), "b": (257,), "c": (3,)}
    return {k: jnp.asarray(rng.normal(size=(n, *s)) * scale, jnp.float32)
            for k, s in shapes.items()}


def trees_equal(a, b):
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree_util.tree_leaves(a),
                               jax.tree_util.tree_leaves(b)))


def assert_trees_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)), a, b)


def mesh1():
    return mesh_lib.build_mesh({"data": 1}, devices=jax.devices()[:1])


def base_kw(**kw):
    base = dict(model="mlp", dataset="mnist", epochs_global=2,
                epochs_local=1, batch_size=16, limit_train_samples=400,
                limit_eval_samples=100, compute_dtype="float32",
                augment=False, aggregation_by="weights", seed=1,
                compile_cache_dir="")
    base.update(kw)
    return base


def run_pair(mesh8, *, rounds=2, **kw):
    """(real N=8 on the 8-device mesh, simulated N=8 on one device) —
    identical config, deterministic probe/walls, sanitized."""
    kw = base_kw(epochs_global=rounds, sanitize=True, **kw)
    sims = np.full(N, 1.0)
    walls = lambda e: np.full(N, 0.1)
    real = train_global(Config(**kw), mesh=mesh8, progress=False,
                        simulated_durations=sims,
                        simulated_round_durations=walls)
    sim = train_global(Config(**kw, sim_workers=N), progress=False,
                       simulated_durations=sims,
                       simulated_round_durations=walls)
    return real, sim


# ---------------------------------------------------------------------
# comms level: aggregate_sim vs the flat-primitives reference path
# ---------------------------------------------------------------------
class TestAggregateSim:
    def _real(self, mesh8, tree, how, topo, poison=None):
        def pw(t, *rest):
            sq = jax.tree_util.tree_map(lambda a: a[0], t)
            if rest:
                out, _okf = comms.aggregate(sq, how=how, topology=topo,
                                            local_weight=0.3,
                                            poison=rest[0][0])
            else:
                out = comms.aggregate(sq, how=how, topology=topo,
                                      local_weight=0.3)
            return jax.tree_util.tree_map(lambda a: a[None], out)
        specs = (P("data"),) * (2 if poison is not None else 1)
        f = jax.jit(shard_map(pw, mesh=mesh8, in_specs=specs,
                              out_specs=P("data")))
        return f(tree, poison) if poison is not None else f(tree)

    @pytest.mark.parametrize("topo", TOPOS)
    @pytest.mark.parametrize("how", HOWS)
    def test_bitwise_vs_dense_reference(self, mesh8, topo, how):
        # the simulator's sync IS the dense path's arithmetic: stacked
        # fp32 blends bitwise == the shard_map collectives (rank-order
        # fold == psum, roll == ppermute).  One cell — weighted x
        # double_ring — is ulp-tight instead of bitwise in THIS
        # standalone harness: its three-term blend gives LLVM an FMA
        # contraction choice that can differ between the tiny
        # standalone programs (<= 1 ulp).  The acceptance gate lives at
        # round level, where TestEngineParity/TestDriverParity assert
        # the same cell BITWISE inside the real round programs.
        tree = stacked_tree(scale=100.0)
        real = self._real(mesh8, tree, how, topo)
        sim, res = jax.jit(functools.partial(
            comms.aggregate_sim, how=how, topology=topo,
            local_weight=0.3))(tree)
        assert res is None
        if (topo, how) == ("double_ring", "weighted"):
            for k in tree:
                np.testing.assert_allclose(np.asarray(real[k]),
                                           np.asarray(sim[k]),
                                           rtol=3e-7, atol=0)
        else:
            assert_trees_equal(real, sim)

    def test_fold_matches_psum_and_roll_matches_ppermute(self, mesh8):
        # the two primitives the whole bitwise argument rests on
        x = stacked_tree()["a"]
        def pw(a):
            return (lax.psum(a[0], "data")[None],
                    lax.ppermute(a[0], "data",
                                 comms.ring_neighbors(N, 2))[None])
        f = jax.jit(shard_map(pw, mesh=mesh8, in_specs=P("data"),
                              out_specs=(P("data"), P("data"))))
        ps, perm = f(x)
        fold = jax.jit(comms.sim_fold)(x)
        np.testing.assert_array_equal(np.asarray(ps)[0], np.asarray(fold))
        np.testing.assert_array_equal(np.asarray(perm),
                                      np.asarray(jnp.roll(x, 2, axis=0)))

    @pytest.mark.parametrize("topo", TOPOS)
    @pytest.mark.parametrize("how", HOWS)
    def test_participation_mask_mirrors_poison_screen(self, mesh8, topo,
                                                      how):
        # the scenario masks reuse the dense poison path's renormalized
        # blends; fp32 values agree to <= 1 ulp (the select-heavy masked
        # programs fuse slightly differently across program shapes, so
        # this twin is semantic-exact, ulp-tight — the UNMASKED gate
        # above stays bitwise)
        tree = stacked_tree()
        ok = np.array([1, 1, 0, 1, 1, 1, 0, 1], np.float32)
        real = self._real(mesh8, tree, how, topo,
                          poison=jnp.asarray(ok < 1))
        sim, _ = jax.jit(functools.partial(
            comms.aggregate_sim, how=how, topology=topo,
            local_weight=0.3, ok=jnp.asarray(ok)))(tree)
        for k in tree:
            np.testing.assert_allclose(np.asarray(real[k]),
                                       np.asarray(sim[k]), rtol=2e-6,
                                       atol=1.3e-7)

    @pytest.mark.parametrize("topo", TOPOS)
    @pytest.mark.parametrize("how", HOWS)
    def test_all_ones_mask_selects_the_unscreened_values(self, topo,
                                                         how):
        # scenario knobs at their defaults compile NO mask machinery at
        # all (SimEngine.scenario_on) — the parity gate's program is the
        # unmasked one.  This case pins the adjacent property: an armed
        # scenario whose draw happens to be full participation selects
        # the unscreened VALUES via the all_ok construction — bitwise
        # for the equal blends (a pure select); the weighted blends are
        # ulp-tight (the masked program's extra branches give LLVM a
        # different FMA contraction context).
        tree = stacked_tree()
        f0 = jax.jit(functools.partial(comms.aggregate_sim, how=how,
                                       topology=topo, local_weight=0.3))
        f1 = jax.jit(functools.partial(comms.aggregate_sim, how=how,
                                       topology=topo, local_weight=0.3,
                                       ok=jnp.ones((N,))))
        a, b = f0(tree)[0], f1(tree)[0]
        if how == "equal":
            assert_trees_equal(a, b)
        else:
            for k in tree:
                np.testing.assert_allclose(np.asarray(a[k]),
                                           np.asarray(b[k]),
                                           rtol=2e-6, atol=1.3e-7)

    def test_mask_semantics_adoption_and_renormalization(self):
        # hand-checkable n=4 vector: worker 2 masked out
        x = jnp.asarray(np.array([[0.0], [4.0], [100.0], [8.0]],
                                 np.float32))
        ok = jnp.asarray(np.array([1, 1, 0, 1], np.float32))
        # allreduce equal: every row (incl. the masked) adopts the
        # survivors' mean (0+4+8)/3
        out, _ = comms.aggregate_sim({"p": x}, how="equal",
                                     topology="allreduce", ok=ok)
        np.testing.assert_allclose(np.asarray(out["p"]),
                                   np.full((4, 1), 4.0), rtol=1e-6)
        # ring equal: row 3's predecessor (2) is masked -> keeps own/1;
        # row 2 (masked) adopts its participating predecessor's payload
        out, _ = comms.aggregate_sim({"p": x}, how="equal",
                                     topology="ring", ok=ok)
        got = np.asarray(out["p"]).ravel()
        np.testing.assert_allclose(got[3], 8.0, rtol=1e-6)   # (8+0)/1? no: (8)/1
        np.testing.assert_allclose(got[2], 4.0, rtol=1e-6)   # adopts w1
        np.testing.assert_allclose(got[1], 2.0, rtol=1e-6)   # (4+0)/2

    def test_compressed_wire_ef_discriminates(self):
        # single-stage EF: the time-averaged consensus of repeated
        # syncs tracks the fp32 fixed point closer than plain bf16
        # (the gossip engine's EF argument, on the simulated wire)
        rng = np.random.default_rng(3)
        base = jnp.asarray(rng.normal(size=(N, 64)) * 1e-3, jnp.float32)
        tgt, _ = comms.aggregate_sim({"p": base}, how="equal",
                                     topology="allreduce")

        def run(ef):
            res = {"p": jnp.zeros_like(base)} if ef else None
            x = {"p": base}
            outs = []
            for _ in range(24):
                x, res = comms.aggregate_sim(
                    x, how="equal", topology="allreduce",
                    wire_dtype=jnp.bfloat16,
                    residual=res)
                if not ef:
                    res = None
                outs.append(np.asarray(x["p"]))
            return np.mean(outs[8:], axis=0)

        err_plain = np.abs(run(False) - np.asarray(tgt["p"])).mean()
        err_ef = np.abs(run(True) - np.asarray(tgt["p"])).mean()
        assert err_ef < err_plain / 2.0, (err_ef, err_plain)

    def test_sim_wire_bytes_accounting(self):
        tree = stacked_tree()
        shapes = {k: jax.ShapeDtypeStruct(v.shape[1:], v.dtype)
                  for k, v in tree.items()}
        fp32 = comms.sim_wire_bytes(shapes, N, topology="allreduce")
        # fp32 == the dense accounting exactly
        assert fp32 == comms.sync_wire_bytes(shapes, N, mode="dense",
                                             topology="allreduce")
        assert comms.sim_wire_bytes(
            shapes, N, topology="allreduce",
            wire_dtype=jnp.bfloat16) == fp32 // 2
        assert comms.sim_wire_bytes(
            shapes, N, topology="allreduce",
            wire_dtype=jnp.int8) == fp32 // 4
        # double_ring sends every leaf twice per round
        assert comms.sim_wire_bytes(
            shapes, N, topology="double_ring") == 2 * fp32
        assert comms.sim_wire_bytes(shapes, 1, topology="ring") == 0


# ---------------------------------------------------------------------
# engine level: whole SimEngine rounds vs whole real-mesh rounds
# ---------------------------------------------------------------------
def make_packs(n=N, steps=4, b=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, steps, b, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, (n, steps, b)).astype(np.int32)
    m = np.ones((n, steps, b), np.float32)
    return x, y, m


def engine_pair(mesh8, **kw):
    cfg_kw = base_kw(**kw)
    cfg_kw.pop("epochs_global")
    model = get_model("mlp", num_classes=10, hidden=16)
    real = LocalSGDEngine(model, mesh8, Config(**cfg_kw))
    sim = SimEngine(model, mesh1(), Config(**cfg_kw, sim_workers=N))
    return real, sim


class TestEngineParity:
    @pytest.mark.parametrize("topo,how", [("allreduce", "weighted"),
                                          ("ring", "equal"),
                                          ("double_ring", "weighted")])
    def test_round_bitwise_weights_mode(self, mesh8, topo, how):
        real_e, sim_e = engine_pair(mesh8, topology=topo,
                                    aggregation_type=how, epochs_local=2)
        sample = np.zeros((8, 28, 28, 1), np.float32)
        rs = real_e.init_state(jax.random.key(0), sample)
        ss = sim_e.init_state(jax.random.key(0), sample)
        assert_trees_equal(jax.device_get(rs), jax.device_get(ss))
        tp, vp = make_packs(), make_packs(seed=1)
        for _ in range(2):
            rs, rmx = real_e.round(rs, tp, vp)
            ss, smx = sim_e.round(ss, tp, vp)
        assert_trees_equal(jax.device_get(rs.params),
                           jax.device_get(ss.params))
        assert_trees_equal(jax.device_get(rs.opt_state),
                           jax.device_get(ss.opt_state))
        np.testing.assert_array_equal(np.asarray(rs.rng),
                                      np.asarray(ss.rng))
        for k in rmx:
            np.testing.assert_array_equal(
                np.asarray(rmx[k]), np.asarray(smx[k]), err_msg=k)

    def test_round_bitwise_gradients_mode(self, mesh8):
        # reference default: collectives on the stale last-batch grads,
        # params untouched, only the aggregated norm observable
        real_e, sim_e = engine_pair(mesh8, aggregation_by="gradients")
        sample = np.zeros((8, 28, 28, 1), np.float32)
        rs = real_e.init_state(jax.random.key(0), sample)
        ss = sim_e.init_state(jax.random.key(0), sample)
        tp, vp = make_packs(), make_packs(seed=1)
        rs, rmx = real_e.round(rs, tp, vp)
        ss, smx = sim_e.round(ss, tp, vp)
        assert_trees_equal(jax.device_get(rs.params),
                           jax.device_get(ss.params))
        np.testing.assert_array_equal(np.asarray(rmx["agg_grad_norm"]),
                                      np.asarray(smx["agg_grad_norm"]))

    def test_sync_stats_schema_and_sim_accounting(self, mesh8):
        _, sim_e = engine_pair(mesh8)
        sample = np.zeros((8, 28, 28, 1), np.float32)
        ss = sim_e.init_state(jax.random.key(0), sample)
        ss, _ = sim_e.round(ss, make_packs(), make_packs(seed=1))
        stats = sim_e.last_sync_stats
        # identical schema to every real engine's row (ISSUE 16 added
        # sync_hidden_ms, zero-filled everywhere but staleness runs)
        assert set(stats) == {"sync_bytes", "sync_mode", "sync_ms",
                              "sync_hidden_ms",
                              "sync_bytes_ici", "sync_bytes_dcn",
                              "sync_ms_ici", "sync_ms_dcn"}
        assert stats["sync_hidden_ms"] == 0.0
        assert stats["sync_mode"] == "sim"
        assert stats["sync_bytes"] == comms.sim_wire_bytes(
            sim_e.params_template, N, topology="allreduce")
        # per-worker state bytes: each simulated worker owns 1/N of the
        # stacked rows even though all rows live on one chip
        bts = sim_e.state_resident_bytes(ss)
        total_params = sum(
            int(np.prod(np.shape(x))) * 4
            for x in jax.tree_util.tree_leaves(ss.params))
        assert bts["params"] == total_params // N


# ---------------------------------------------------------------------
# driver level: the sanitized e2e gate
# ---------------------------------------------------------------------
class TestDriverParity:
    # one combo per topology stays tier-1; the full 6-combo matrix is
    # the slow-marked case below (tier-1 wall hygiene, ISSUE 14)
    @pytest.mark.parametrize("topo,how", [("allreduce", "equal"),
                                          ("ring", "weighted"),
                                          ("double_ring", "equal")])
    def test_sim_bitwise_vs_real_mesh_sanitized(self, mesh8, topo, how):
        real, sim = run_pair(mesh8, topology=topo, aggregation_type=how)
        assert real["global_train_losses"] == sim["global_train_losses"]
        assert real["global_val_accuracies"] == \
            sim["global_val_accuracies"]
        assert real["all_epochs_losses"] == sim["all_epochs_losses"]
        assert_trees_equal(jax.device_get(real["state"].params),
                           jax.device_get(sim["state"].params))
        assert_trees_equal(real["variables"], sim["variables"])
        # zero post-warmup retraces on BOTH paths (--sanitize raised
        # otherwise; the rows record it)
        for res in (real, sim):
            assert res["sanitize"]["enabled"] is True
            assert res["sanitize"]["retrace_count"] == 0
            assert res["sanitize"]["donation_failures"] == 0

    @pytest.mark.slow
    @pytest.mark.parametrize("topo", TOPOS)
    @pytest.mark.parametrize("how", HOWS)
    def test_full_matrix_sim_bitwise_vs_real_mesh(self, mesh8, topo,
                                                  how):
        real, sim = run_pair(mesh8, topology=topo, aggregation_type=how)
        assert real["global_train_losses"] == sim["global_train_losses"]
        assert_trees_equal(jax.device_get(real["state"].params),
                           jax.device_get(sim["state"].params))

    def test_sim_telemetry_and_provenance(self, mesh8):
        _, sim = run_pair(mesh8)
        s = sim["sim"]
        assert s["workers"] == N and s["rounds"] == 2
        assert s["rounds_per_s"] is None or s["rounds_per_s"] > 0
        assert s["per_worker_sync_bytes"] > 0
        assert s["per_worker_state_bytes"]["params"] > 0
        assert s["scenario"] == {"sample_frac": 1.0, "dropout": 0.0,
                                 "byzantine": None, "lr_jitter": 0.0}
        assert "rounds_scenario" not in s   # nothing armed, no draws
        assert sim["sync_engine"]["mode"] == "sim"
        assert sim["sync_engine"]["levels"] == {"inner": "sim",
                                                "outer": None}
        # per-round rows keep the uniform telemetry schema
        for t in sim["round_timings"]:
            assert t["sync_mode"] == "sim"
            assert t["sync_bytes"] == s["per_worker_sync_bytes"]

    def test_more_workers_than_devices_one_chip(self):
        # the point of the lab: N=32 workers where the mesh caps at 8
        n = 32
        cfg = Config(**base_kw(), sim_workers=n)
        res = train_global(cfg, progress=False,
                           simulated_durations=np.full(n, 1.0),
                           simulated_round_durations=lambda e: np.full(
                               n, 0.1))
        assert res["sim"]["workers"] == n
        assert len(res["all_workers_losses"]) == n
        assert all(len(w) > 0 for w in res["all_workers_losses"])
        losses = res["global_train_losses"]
        assert losses[-1] < losses[0]
        # every worker-stacked state leaf carries the full simulated axis
        assert all(x.shape[0] == n for x in
                   jax.tree_util.tree_leaves(res["state"].params))

    @pytest.mark.slow
    def test_paper_matrix_2x3_sim_vs_real(self, mesh8):
        """The paper's full 2x3 grid (balanced/disbalanced x allreduce/
        ring/double_ring) at simulated N=8: per-topology consensus
        bitwise-matches the real-mesh twin, and the non-IID ordering the
        paper reports (skewed shards hurt accuracy) holds on the
        aggregate."""
        acc = {"balanced": [], "disbalanced": []}
        for mode in ("balanced", "disbalanced"):
            for topo in TOPOS:
                real, sim = run_pair(mesh8, rounds=3, topology=topo,
                                     data_mode=mode, fixed_ratio=0.8,
                                     epochs_local=2)
                assert real["global_train_losses"] == \
                    sim["global_train_losses"], (mode, topo)
                assert_trees_equal(
                    jax.device_get(real["state"].params),
                    jax.device_get(sim["state"].params))
                acc[mode].append(sim["global_val_accuracies"][-1])
        assert np.mean(acc["balanced"]) > np.mean(acc["disbalanced"]), acc


# ---------------------------------------------------------------------
# the scenario surface
# ---------------------------------------------------------------------
def sim_run(n=8, rounds=3, **kw):
    cfg = Config(**base_kw(epochs_global=rounds, **kw), sim_workers=n)
    return train_global(cfg, progress=False,
                        simulated_durations=np.full(n, 1.0),
                        simulated_round_durations=lambda e: np.full(
                            n, 0.1))


class TestScenarios:
    def test_sampling_draws_and_telemetry(self):
        res = sim_run(n=8, sim_sample_frac=0.5)
        draws = res["sim"]["rounds_scenario"]
        assert len(draws) == 3
        assert all(d["active"] == 4 for d in draws)  # ceil(0.5 * 8)
        assert res["sim"]["scenario"]["sample_frac"] == 0.5
        assert np.isfinite(res["global_train_losses"]).all()

    def test_sampling_is_seeded_deterministic(self):
        a = sim_run(n=8, sim_sample_frac=0.5)
        b = sim_run(n=8, sim_sample_frac=0.5)
        assert a["global_train_losses"] == b["global_train_losses"]
        assert a["sim"]["rounds_scenario"] == b["sim"]["rounds_scenario"]

    def test_dropout_freezes_the_dropped_worker(self):
        # dropout ~1 never drops EVERY worker (validated < 1), but a
        # high rate on a small grid exercises the freeze: a dropped
        # worker's whole round is a no-op — its lr_epoch clock must lag
        # the rounds it missed
        res = sim_run(n=4, rounds=4, sim_dropout=0.45)
        dropped_total = sum(d["dropped"]
                            for d in res["sim"]["rounds_scenario"])
        assert dropped_total > 0   # seeded: this config does drop
        clocks = np.asarray(res["state"].lr_epoch)
        full_clock = 4 * 1   # rounds x epochs_local
        assert clocks.min() < full_clock
        assert clocks.max() <= full_clock

    def test_sampled_out_worker_adopts_the_consensus(self):
        # allreduce x equal with sampling: after the sync EVERY
        # non-dropped worker holds the same consensus (sampled-out rows
        # adopt), so all params rows are identical each round
        res = sim_run(n=8, sim_sample_frac=0.5)
        p = jax.device_get(res["state"].params)
        for leaf in jax.tree_util.tree_leaves(p):
            assert np.all(leaf == leaf[:1]), "rows diverged"

    def test_byzantine_signflip_changes_consensus_and_hurts(self):
        clean = sim_run(n=8)
        byz = sim_run(n=8, sim_byzantine="signflip:3")
        assert clean["global_train_losses"] != byz["global_train_losses"]
        # three sign-flipped contributions out of eight slow convergence
        assert byz["global_train_losses"][-1] > \
            clean["global_train_losses"][-1]
        assert byz["sim"]["scenario"]["byzantine"] == "signflip:3"

    def test_byzantine_noise_is_seeded_and_bounded(self):
        a = sim_run(n=8, sim_byzantine="noise:2:0.01")
        b = sim_run(n=8, sim_byzantine="noise:2:0.01")
        assert a["global_train_losses"] == b["global_train_losses"]
        assert np.isfinite(a["global_train_losses"]).all()

    def test_lr_jitter_spreads_worker_trajectories(self):
        # gradients mode keeps params per-worker (no FedAvg overwrite),
        # so a per-worker LR spread must leave different rows
        flat = sim_run(n=4, aggregation_by="gradients")
        jit_ = sim_run(n=4, aggregation_by="gradients",
                       sim_lr_jitter=0.5)
        p = jax.device_get(jit_["state"].params)
        leaf = jax.tree_util.tree_leaves(p)[0]
        assert not np.all(leaf == leaf[:1]), "jitter had no effect"
        assert flat["global_train_losses"] != jit_["global_train_losses"]

    def test_defaults_compile_no_scenario_machinery(self, mesh8):
        # scenario_on is a compile-time arming: the default program has
        # no mask inputs at all (the parity gate's program)
        _, sim_e = engine_pair(mesh8)
        assert sim_e.scenario_on is False
        assert sim_e.lr_scale is None
        cfg = Config(**{**base_kw(), "epochs_global": 2},
                     sim_workers=N, sim_dropout=0.3)
        armed = SimEngine(get_model("mlp", num_classes=10, hidden=16),
                          mesh1(), cfg)
        assert armed.scenario_on is True

    def test_compressed_wire_runs_with_ef_state(self):
        res = sim_run(n=8, sync_dtype="bfloat16", sync_compression="ef",
                      topology="ring")
        assert res["sim"]["per_worker_state_bytes"]["ef_residual"] > 0
        assert res["sim"]["per_worker_sync_bytes"] == \
            res["sim"]["per_worker_state_bytes"]["params"] // 2
        assert np.isfinite(res["global_train_losses"]).all()


# ---------------------------------------------------------------------
# eager config validation (ISSUE 14 satellite)
# ---------------------------------------------------------------------
class TestSimConfigValidation:
    @pytest.mark.parametrize("kw,frag", [
        (dict(chaos="kill@1:w0"), "--chaos"),
        (dict(num_slices=2, topology="ring"), "--num_slices"),
        (dict(shard_redundancy="buddy"), "buddy"),
        (dict(opt_placement="sharded"), "--opt_placement"),
        (dict(param_residency="resident"), "resident"),
        (dict(sync_mode="sharded"), "--sync_mode"),
        (dict(stream_chunk_steps=4), "--stream_chunk_steps"),
        (dict(checkpoint_dir="/tmp/ck"), "--checkpoint_dir"),
        (dict(num_workers=4), "--num_workers"),
        (dict(mesh_shape="data=4,model=2"), "inner mesh axes"),
        (dict(sequence_parallel="ring"), "--sequence_parallel"),
    ])
    def test_real_mesh_only_features_rejected_eagerly(self, kw, frag):
        with pytest.raises(ValueError, match="sim_workers"):
            try:
                Config(**base_kw(), sim_workers=8, **kw)
            except ValueError as e:
                assert frag in str(e), (kw, str(e))
                raise

    @pytest.mark.parametrize("kw", [
        dict(sim_sample_frac=0.0), dict(sim_sample_frac=1.5),
        dict(sim_dropout=-0.1), dict(sim_dropout=1.0),
        dict(sim_lr_jitter=1.0), dict(sim_lr_jitter=-0.5),
    ])
    def test_scenario_ranges_checked(self, kw):
        with pytest.raises(ValueError):
            Config(**base_kw(), sim_workers=8, **kw)

    @pytest.mark.parametrize("spec", [
        "evil:2", "signflip", "signflip:0", "signflip:8",
        "signflip:2:0.5", "noise:2:-1", "noise:x",
    ])
    def test_byzantine_spec_validated(self, spec):
        with pytest.raises(ValueError):
            Config(**base_kw(), sim_workers=8, sim_byzantine=spec)

    def test_scenario_knobs_need_sim_workers(self):
        for kw in (dict(sim_dropout=0.5), dict(sim_sample_frac=0.5),
                   dict(sim_byzantine="signflip:2"),
                   dict(sim_lr_jitter=0.5)):
            with pytest.raises(ValueError, match="sim_workers"):
                Config(**base_kw(), **kw)

    def test_driver_rejects_snapshot_and_wide_mesh(self, mesh8):
        cfg = Config(**base_kw(), sim_workers=8)
        with pytest.raises(ValueError, match="ONE anchor device"):
            train_global(cfg, mesh=mesh8, progress=False)
        with pytest.raises(ValueError, match="elastic_snapshot"):
            train_global(cfg, elastic_snapshot=object(), progress=False)

    def test_valid_sim_config_accepted(self):
        cfg = Config(**base_kw(), sim_workers=256, sim_sample_frac=0.1,
                     sim_dropout=0.05, sim_byzantine="noise:8:0.5",
                     sim_lr_jitter=0.2)
        assert cfg.parse_sim_byzantine() == ("noise", 8, 0.5)
