"""Bucketed compressed gossip engine (ISSUE 4 tentpole).

Covers the gossip twin of the sharded-sync contract: the fp32 bucketed
ring/double-ring round is BIT-IDENTICAL to the legacy dense per-leaf path
across worker counts and blend modes; the weighted blend reproduces the
reference's ``local_weight`` semantics through the bucketed path;
compressed gossip (bf16/int8 permuted payload, fp32 local blend) is
wire-rounding bounded per round and, with error feedback, contracts
repeated-round consensus to the dense fixed point where the uncompensated
path plateaus at the wire quantum; the engine resolves ``--sync_mode
sharded``/auto per topology onto the gossip program; and the per-round
telemetry schema is identical across all three topologies.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu import (
    comms,
    mesh as mesh_lib,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import LocalSGDEngine

N = 8

# same uneven leaf sizes as test_sync.py: multiple buckets at the tiny
# target, with a mid-tree bucket boundary
SHAPES = {"a": (13, 7), "b": (257,), "c": (31, 5), "d": (3,)}
TINY_BUCKET = 1024


def stacked_tree(n=N, seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    return {k: jnp.asarray(rng.normal(size=(n, *s)) * scale, jnp.float32)
            for k, s in SHAPES.items()}


def sub_mesh(k):
    return mesh_lib.build_mesh({"data": k}, devices=jax.devices()[:k])


class TestGossipBitIdentity:
    @pytest.mark.parametrize("k", [4, 8])
    @pytest.mark.parametrize("topology", ["ring", "double_ring"])
    def test_fp32_bucketed_bitwise_equals_dense(self, k, topology):
        mesh = sub_mesh(k)
        tree = stacked_tree(n=k)
        dense = comms.make_host_sync(mesh, mode="dense",
                                     topology=topology)(tree)[0]
        buck = comms.make_host_sync(mesh, mode="gossip", topology=topology,
                                    bucket_bytes=TINY_BUCKET)(tree)[0]
        for key in SHAPES:
            assert np.array_equal(np.asarray(dense[key]),
                                  np.asarray(buck[key])), key


class TestWeightedBlend:
    """The Disbalanced variants' straggler weighting through the bucketed
    path: ``new = w*own + (1-w)*peer`` (peer mean for double-ring)."""

    @pytest.mark.parametrize("topology", ["ring", "double_ring"])
    def test_weighted_matches_dense_and_legacy_semantics(self, mesh8,
                                                         topology):
        w = 0.3
        tree = stacked_tree()
        dense = comms.make_host_sync(mesh8, mode="dense", topology=topology,
                                     how="weighted", local_weight=w)(tree)[0]
        buck = comms.make_host_sync(mesh8, mode="gossip", topology=topology,
                                    how="weighted", local_weight=w,
                                    bucket_bytes=TINY_BUCKET)(tree)[0]
        for key in SHAPES:
            a = np.asarray(tree[key], np.float64)
            r1 = np.roll(a, 1, axis=0)   # shift-1 predecessor's value
            if topology == "ring":
                expect = w * a + (1 - w) * r1
            else:
                r2 = np.roll(a, 2, axis=0)
                expect = w * a + ((1 - w) / 2) * (r1 + r2)
            # bucketed == dense bitwise; both == the reference's
            # local_weight blend to float rounding
            assert np.array_equal(np.asarray(dense[key]),
                                  np.asarray(buck[key])), key
            np.testing.assert_allclose(np.asarray(buck[key], np.float64),
                                       expect, rtol=1e-6, atol=1e-6)


class TestCompressedGossip:
    def test_single_round_error_is_wire_bounded(self, mesh8):
        tree = stacked_tree(scale=1.0)
        res0 = jax.tree_util.tree_map(jnp.zeros_like, tree)
        dense = comms.make_host_sync(mesh8, mode="dense",
                                     topology="ring")(tree)[0]
        for wdt, bound in ((jnp.bfloat16, 0.05), (jnp.int8, 0.1)):
            comp, new_res = comms.make_host_sync(
                mesh8, mode="gossip", topology="ring", wire_dtype=wdt,
                bucket_bytes=TINY_BUCKET)(tree, res0)
            # only the permuted neighbor term is compressed — one wire
            # rounding of an O(1) value per element
            err = max(float(np.abs(np.asarray(comp[k], np.float32)
                                   - np.asarray(dense[k], np.float32)).max())
                      for k in SHAPES)
            assert err < bound, (wdt, err)
            # the residual carries the own-transmission rounding error
            assert any(float(np.abs(np.asarray(l)).max()) > 0
                       for l in jax.tree_util.tree_leaves(new_res))

    @pytest.mark.parametrize("topology", ["ring", "double_ring"])
    def test_ef_consensus_contracts_to_dense_fixed_point(self, mesh8,
                                                         topology):
        # stall regime by construction: worker disagreement (~0.2) far
        # below the bf16 quantum at base magnitude ~100 (~0.5).  Plain
        # bf16 gossip rounds every transmission to the wire grid, so the
        # workers agree on GRID values — variance contracts, but the
        # consensus plateaus up to half a quantum off the dense fixed
        # point (the true fp32 mean) and stays there.  Error feedback
        # re-injects each round's rounding into the next transmission, so
        # the received values time-average to the true mean: the EF run's
        # time-averaged iterate lands several times closer (the EF-must-
        # win margin measured here is ~5x; asserted at 2x).
        rng = np.random.default_rng(1)
        base = rng.uniform(64, 128, 512) * rng.choice([-1.0, 1.0], 512)
        spread = rng.normal(size=(N, 512)) * 0.2
        x0 = jnp.asarray(base[None] + spread, jnp.float32)
        true_mean = np.asarray(x0).mean(0)
        var0 = float(((np.asarray(x0) - true_mean[None]) ** 2).mean())

        comp = comms.make_host_sync(mesh8, mode="gossip", topology=topology,
                                    wire_dtype=jnp.bfloat16)
        rounds, tail = 60, 20
        p_ef = p_raw = {"w": x0}
        r_ef = {"w": jnp.zeros((N, 512), jnp.float32)}
        ef_tail, raw_tail = [], []
        for t in range(rounds):
            # block each round: pipelined collectives can starve the
            # XLA:CPU rendezvous (test_comms gossip note)
            p_ef, r_ef = jax.block_until_ready(comp(p_ef, r_ef))
            p_raw = jax.block_until_ready(comp(p_raw)[0])
            if t >= rounds - tail:
                ef_tail.append(np.asarray(p_ef["w"]))
                raw_tail.append(np.asarray(p_raw["w"]))
        # consensus contraction: both compressed paths shrink the
        # cross-worker variance by well over 2x
        for tag, p in (("ef", p_ef), ("raw", p_raw)):
            a = np.asarray(p["w"])
            var = float(((a - a.mean(0)) ** 2).mean())
            assert var < 0.5 * var0, (topology, tag, var, var0)
        ef_dist = float(np.abs(np.mean(ef_tail, 0)
                               - true_mean[None]).mean())
        raw_dist = float(np.abs(np.mean(raw_tail, 0)
                                - true_mean[None]).mean())
        assert ef_dist < 0.5 * raw_dist, (topology, ef_dist, raw_dist)


class TestGossipWireBytes:
    def test_accounting_matches_hops_and_wire_dtype(self):
        tree = {k: jax.ShapeDtypeStruct(s, jnp.float32)
                for k, s in SHAPES.items()}
        total = sum(int(np.prod(s)) for s in SHAPES.values())
        for topo, hops in (("ring", 1), ("double_ring", 2)):
            dense = comms.sync_wire_bytes(tree, N, mode="dense",
                                          topology=topo)
            fp32 = comms.sync_wire_bytes(tree, N, mode="gossip",
                                         wire_dtype=jnp.float32,
                                         topology=topo)
            # bucketing changes the collective count, never the bytes:
            # each hop moves every element exactly once, unpadded
            assert dense == fp32 == hops * total * 4
            bf16 = comms.sync_wire_bytes(tree, N, mode="gossip",
                                         wire_dtype=jnp.bfloat16,
                                         topology=topo)
            int8 = comms.sync_wire_bytes(tree, N, mode="gossip",
                                         wire_dtype=jnp.int8,
                                         topology=topo)
            assert bf16 * 2 == fp32 and int8 * 4 == fp32
        assert comms.sync_wire_bytes(tree, 1, mode="gossip",
                                     topology="ring") == 0


def small_cfg(**kw):
    base = dict(model="mlp", dataset="mnist", epochs_local=2, epochs_global=2,
                batch_size=8, compute_dtype="float32", augment=False,
                aggregation_by="weights")
    base.update(kw)
    return Config(**base)


def make_engine(mesh8, cfg):
    model = get_model("mlp", num_classes=10, hidden=16)
    return LocalSGDEngine(model, mesh8, cfg)


def make_packs(n=8, steps=4, b=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, steps, b, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, (n, steps, b)).astype(np.int32)
    m = np.ones((n, steps, b), np.float32)
    return x, y, m


class TestEngineGossip:
    def test_ring_round_bitwise_identical_and_telemetry_parity(self, mesh8):
        x, y, m = make_packs()

        def run(cfg):
            engine = make_engine(mesh8, cfg)
            state = engine.init_state(jax.random.key(0), x[0, 0])
            state, _ = engine.round(state, (x, y, m), (x, y, m))
            return engine, state

        eng_d, s_d = run(small_cfg(topology="ring", sync_mode="dense"))
        eng_g, s_g = run(small_cfg(topology="ring", sync_mode="sharded",
                                   sync_bucket_mb=0.001))
        assert eng_d.sync_mode == "dense"
        assert eng_g.sync_mode == "gossip"
        for a, b in zip(jax.tree_util.tree_leaves(s_d.params),
                        jax.tree_util.tree_leaves(s_g.params)):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        # telemetry schema parity (ISSUE 4 satellite): identical keys on
        # every engine, sync_ms zero-filled where no standalone sync
        # program ran (CPU fuses the sync into the round program).
        # ISSUE 13 widened the schema with the per-LEVEL split — flat
        # engines report every byte as the intra-slice (ICI) level —
        # and ISSUE 16 with sync_hidden_ms (zero-filled on synchronous
        # runs)
        keys = {"sync_bytes", "sync_mode", "sync_ms", "sync_hidden_ms",
                "sync_bytes_ici", "sync_bytes_dcn",
                "sync_ms_ici", "sync_ms_dcn"}
        assert set(eng_d.last_sync_stats) == keys
        assert set(eng_g.last_sync_stats) == keys
        assert eng_g.last_sync_stats["sync_bytes"] > 0
        assert eng_g.last_sync_stats["sync_ms"] == 0.0
        assert eng_g.last_sync_stats["sync_bytes_ici"] == \
            eng_g.last_sync_stats["sync_bytes"]
        assert eng_g.last_sync_stats["sync_bytes_dcn"] == 0


class TestGossipConfigResolution:
    def test_sharded_ring_resolves_to_gossip_engine(self):
        # the old hard rejection is lifted (ISSUE 4): --sync_mode sharded
        # names the bucketed fast path, resolved per topology
        cfg = Config(sync_mode="sharded", topology="ring")
        assert cfg.resolve_sync_mode("cpu") == "gossip"
        assert cfg.resolve_sync_mode("tpu") == "gossip"
        assert Config(sync_mode="sharded").resolve_sync_mode("cpu") \
            == "sharded"

    def test_auto_resolves_per_topology_and_backend(self):
        for topo, fast in (("allreduce", "sharded"), ("ring", "gossip"),
                           ("double_ring", "gossip")):
            assert Config(topology=topo).resolve_sync_mode("cpu") == "dense"
            assert Config(topology=topo).resolve_sync_mode("tpu") == fast
            assert Config(topology=topo,
                          sync_dtype="bfloat16").resolve_sync_mode(
                              "cpu") == fast

    def test_compressed_gossip_flags_now_construct(self):
        # previously a hard ValueError; the engine now rides the
        # compressed wire for gossip topologies too
        cfg = Config(sync_dtype="int8", sync_compression="ef",
                     topology="double_ring", aggregation_by="weights")
        assert cfg.resolve_sync_mode("cpu") == "gossip"

    def test_dense_mode_still_rejects_compressed_wire(self):
        with pytest.raises(ValueError, match="sync_mode dense"):
            Config(sync_mode="dense", sync_dtype="bfloat16",
                   topology="ring")


class TestGossipDriverTelemetry:
    def test_ring_round_timings_schema_matches_allreduce(self, mesh8):
        res = train_global(
            Config(model="mlp", dataset="mnist", epochs_global=2,
                   epochs_local=1, batch_size=16, limit_train_samples=256,
                   limit_eval_samples=64, compute_dtype="float32",
                   augment=False, aggregation_by="weights",
                   topology="ring", sync_mode="sharded"),
            mesh=mesh8, progress=False)
        assert res["sync_engine"]["mode"] == "gossip"
        # gossip blends are worker-local; the optimizer-placement
        # resolution records that honestly (ISSUE 9)
        assert res["sync_engine"]["opt_placement"] == "local"
        assert len(res["round_timings"]) == 2
        for t in res["round_timings"]:
            # the exact keys the allreduce telemetry carries — downstream
            # viz/bench can key on them regardless of topology
            assert t["sync_mode"] == "gossip"
            assert t["sync_bytes"] > 0
            assert t["sync_ms"] >= 0.0


class TestBenchGossipEntry:
    def test_measure_gossip_reports_counts_bytes_and_identity(self):
        import bench

        out = bench.measure_gossip()
        assert out["n_workers"] == N
        for topo, hops in (("ring", 1), ("double_ring", 2)):
            row = out[topo]
            assert row["bitwise_bucketed_eq_dense"] is True
            # the bucketed engine moves per-bucket collectives, not
            # per-leaf ones (the bench tree has 6 leaves, ~1 bucket at
            # the default 4 MiB target)
            assert row["bucketed"]["collectives"] < row["dense"]["collectives"]
            assert row["dense"]["collectives"] == hops * 6
            assert row["bf16_vs_fp32_bytes"] == pytest.approx(0.5)
            assert row["int8_vs_fp32_bytes"] == pytest.approx(0.25)
            for mode in ("dense", "bucketed", "bf16", "int8"):
                assert row[mode]["ms"] > 0
                assert row[mode]["wire_mb"] > 0
            assert row["bf16_max_abs_err"] < 0.05
            assert row["int8_max_abs_err"] < 0.1
