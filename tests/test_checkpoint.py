"""Async sharded checkpoint engine (ISSUE 5; beyond-reference, SURVEY.md 5).

Covers the engine contract end to end: sharded save/restore round-trip,
async-vs-blocking bitwise identity, atomic manifest commit (crash debris
is never restorable and falls back to the previous committed epoch),
open-time sweep of mid-write leftovers, every-process prune, resharding
across meshes, the legacy single-file back-compat shim, and the driver's
resume + round-timing telemetry integration.
"""

import os

import numpy as np

import jax
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu import checkpoint as C
from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import LocalSGDEngine


def _mlp_state(mesh, seed=0):
    cfg = Config(model="mlp", epochs_local=1, batch_size=8,
                 compute_dtype="float32", augment=False)
    engine = LocalSGDEngine(get_model("mlp", num_classes=10, hidden=8),
                            mesh, cfg)
    x = np.zeros((8, 28, 28, 1), np.float32)
    return engine, engine.init_state(jax.random.key(seed), x)


def _assert_trees_equal(a, b):
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_save_restore_roundtrip(mesh8, tmp_path):
    engine, state = _mlp_state(mesh8, seed=0)
    path = C.save_checkpoint(str(tmp_path), state, global_epoch=3)
    assert os.path.isdir(path)                       # sharded layout
    assert os.path.isfile(os.path.join(path, C.MANIFEST))
    assert C.latest_checkpoint(str(tmp_path)) == path
    _, template = _mlp_state(mesh8, seed=1)
    restored, epoch = C.restore_checkpoint(path, template)
    assert epoch == 3
    _assert_trees_equal(state.params, restored.params)
    # restored leaves land on the TEMPLATE's shardings
    for t, r in zip(jax.tree_util.tree_leaves(template),
                    jax.tree_util.tree_leaves(restored)):
        assert r.sharding == t.sharding


def test_async_save_bitwise_equals_blocking(mesh8, tmp_path):
    """The async engine's committed bytes are the blocking engine's —
    the background thread changes WHEN the write happens, never what."""
    engine, state = _mlp_state(mesh8, seed=2)
    da, db = str(tmp_path / "async"), str(tmp_path / "blocking")
    ea = C.CheckpointEngine(da, async_write=True)
    eb = C.CheckpointEngine(db, async_write=False)
    timing = {}
    ea.save(state, 5, timing=timing)
    eb.save(state, 5)
    ea.wait()
    assert timing["ckpt_snapshot_ms"] > 0 and timing["ckpt_write_ms"] > 0
    _, template = _mlp_state(mesh8, seed=3)
    ra, _ = C.restore_checkpoint(C.latest_checkpoint(da), template)
    rb, _ = C.restore_checkpoint(C.latest_checkpoint(db), template)
    _assert_trees_equal(ra, rb)
    _assert_trees_equal(ra, state)
    # identical payloads -> identical shard bytes on disk
    raw = lambda d: open(os.path.join(d, "ckpt_5", "shard_0.msgpack"),
                         "rb").read()
    assert raw(da) == raw(db)
    assert ea.summary()["bytes_per_host"] == eb.summary()["bytes_per_host"]


def test_prune_keeps_newest_committed(mesh8, tmp_path):
    engine, state = _mlp_state(mesh8, seed=0)
    eng = C.CheckpointEngine(str(tmp_path), keep=2, async_write=False)
    for e in range(1, 6):
        eng.save(state, e)
    assert C.committed_epochs(str(tmp_path)) == [4, 5]
    # pruned epochs are gone from disk, not just from the listing
    assert sorted(n for n in os.listdir(tmp_path)
                  if n.startswith("ckpt_")) == ["ckpt_4", "ckpt_5"]


def test_crash_fallback_to_previous_committed(mesh8, tmp_path):
    """Mid-write debris (no manifest / truncated shard) must make
    ``latest_checkpoint`` fall back to the newest INTACT epoch."""
    engine, state = _mlp_state(mesh8, seed=0)
    eng = C.CheckpointEngine(str(tmp_path), async_write=False)
    eng.save(state, 1)
    eng.save(state, 2)
    # crash between shard write and manifest commit: dir, no MANIFEST
    os.makedirs(tmp_path / "ckpt_3")
    (tmp_path / "ckpt_3" / "shard_0.msgpack").write_bytes(b"partial")
    assert C.committed_epochs(str(tmp_path)) == [1, 2]
    # post-commit truncation of epoch 2's shard: size mismatch vs manifest
    sh = tmp_path / "ckpt_2" / "shard_0.msgpack"
    sh.write_bytes(sh.read_bytes()[:64])
    assert C.committed_epochs(str(tmp_path)) == [1]
    latest = C.latest_checkpoint(str(tmp_path))
    assert latest.endswith("ckpt_1")
    _, template = _mlp_state(mesh8, seed=1)
    restored, epoch = C.restore_checkpoint(latest, template)
    assert epoch == 1
    _assert_trees_equal(restored, state)


def test_corrupt_same_size_shard_falls_back(mesh8, tmp_path):
    """Bit rot / a torn overwrite that PRESERVES the byte size must drop
    the epoch exactly like truncation does (ISSUE 8 satellite): the
    manifest's crc32 is validated at listing time, so ``latest`` falls
    back to the previous committed epoch instead of crashing (or worse,
    restoring garbage) at restore."""
    engine, state = _mlp_state(mesh8, seed=0)
    eng = C.CheckpointEngine(str(tmp_path), async_write=False)
    eng.save(state, 1)
    eng.save(state, 2)
    sh = tmp_path / "ckpt_2" / "shard_0.msgpack"
    raw = bytearray(sh.read_bytes())
    raw[len(raw) // 2] ^= 0xFF           # flip bits, keep the size
    sh.write_bytes(bytes(raw))
    assert os.path.getsize(sh) == len(raw)
    assert C.committed_epochs(str(tmp_path)) == [1]
    latest = C.latest_checkpoint(str(tmp_path))
    assert latest.endswith("ckpt_1")
    _, template = _mlp_state(mesh8, seed=1)
    restored, epoch = C.restore_checkpoint(latest, template)
    assert epoch == 1
    _assert_trees_equal(restored, state)


def test_missing_shard_falls_back(mesh8, tmp_path):
    """A manifested epoch with a LOST (not just truncated) shard file is
    exactly as unrestorable — it must drop out of the committed listing
    so latest falls back, instead of surfacing as a restore crash."""
    engine, state = _mlp_state(mesh8, seed=0)
    eng = C.CheckpointEngine(str(tmp_path), async_write=False)
    eng.save(state, 1)
    eng.save(state, 2)
    os.remove(tmp_path / "ckpt_2" / "shard_0.msgpack")
    assert C.committed_epochs(str(tmp_path)) == [1]
    assert C.latest_checkpoint(str(tmp_path)).endswith("ckpt_1")


def test_dtype_mismatch_rejected(mesh8, tmp_path):
    """Restoring into a template with different leaf dtypes must fail
    loudly at restore time, not at the first engine dispatch."""
    import jax.numpy as jnp
    engine, state = _mlp_state(mesh8, seed=0)
    path = C.save_checkpoint(str(tmp_path), state, 1)
    bad = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16) if x.dtype == jnp.float32 else x,
        state)
    with pytest.raises(ValueError, match="dtype"):
        C.restore_checkpoint(path, bad)


def test_open_sweeps_stale_leftovers(mesh8, tmp_path):
    engine, state = _mlp_state(mesh8, seed=0)
    C.CheckpointEngine(str(tmp_path), async_write=False).save(state, 1)
    # plant every debris species a crash can leave
    os.makedirs(tmp_path / "ckpt_9")
    (tmp_path / "ckpt_9" / "shard_0.msgpack").write_bytes(b"junk")
    (tmp_path / "ckpt_4.msgpack.tmp.0").write_bytes(b"junk")
    (tmp_path / "ckpt_1" / "shard_0.msgpack.tmp.0").write_bytes(b"junk")
    C.CheckpointEngine(str(tmp_path), async_write=False)   # open -> sweep
    names = {n for root, _d, fs in os.walk(tmp_path)
             for n in fs + [os.path.basename(root)]}
    assert not any(".tmp." in n for n in names), names
    assert not (tmp_path / "ckpt_9").exists()
    assert C.committed_epochs(str(tmp_path)) == [1]   # committed untouched


def test_legacy_single_file_restores(mesh8, tmp_path):
    """v1 single-msgpack checkpoints (pre-engine layout) still restore,
    and a newer committed sharded epoch wins the listing over them."""
    engine, state = _mlp_state(mesh8, seed=0)
    C.save_checkpoint_legacy(str(tmp_path), state, 2)
    latest = C.latest_checkpoint(str(tmp_path))
    assert latest.endswith("ckpt_2.msgpack")
    _, template = _mlp_state(mesh8, seed=1)
    restored, epoch = C.restore_checkpoint(latest, template)
    assert epoch == 2
    _assert_trees_equal(restored, state)
    C.save_checkpoint(str(tmp_path), state, 5)
    assert C.committed_epochs(str(tmp_path)) == [2, 5]
    assert C.latest_checkpoint(str(tmp_path)).endswith("ckpt_5")


def test_reshard_restore_roundtrips_exact(devices, tmp_path):
    """Save at one addressable-shard layout, restore into a template with
    a DIFFERENT sharding (the single-process simulation of a host-count /
    mesh change): a ZeRO-3-sharded save restores bit-exactly onto a
    plain data-parallel template and vice versa."""
    from functools import partial
    from learning_deep_neural_network_in_distributed_computing_environment_tpu.parallel.fsdp import fsdp_param_specs
    cfg = Config(model="mlp", epochs_local=1, batch_size=8,
                 compute_dtype="float32", augment=False)
    x = np.zeros((8, 28, 28, 1), np.float32)
    mesh_f = build_mesh({"data": 2, "fsdp": 2}, devices[:4])
    mesh_p = build_mesh({"data": 2}, devices[:2])
    # hidden=32: the 784x32 kernel crosses fsdp's MIN_SHARD_ELEMS, so the
    # save really does happen at a sharded-parameter layout
    eng_f = LocalSGDEngine(get_model("mlp", num_classes=10, hidden=32),
                           mesh_f, cfg,
                           param_specs_fn=partial(fsdp_param_specs,
                                                  axis="fsdp", axis_size=2))
    eng_p = LocalSGDEngine(get_model("mlp", num_classes=10, hidden=32),
                           mesh_p, cfg)
    state_f = eng_f.init_state(jax.random.key(0), x)
    state_p = eng_p.init_state(jax.random.key(1), x)
    # fsdp-sharded save -> plain template
    p1 = C.save_checkpoint(str(tmp_path / "a"), state_f, 1)
    r1, _ = C.restore_checkpoint(p1, state_p)
    _assert_trees_equal(r1, state_f)
    for t, r in zip(jax.tree_util.tree_leaves(state_p),
                    jax.tree_util.tree_leaves(r1)):
        assert r.sharding == t.sharding
    # plain save -> fsdp-sharded template
    p2 = C.save_checkpoint(str(tmp_path / "b"), state_p, 1)
    r2, _ = C.restore_checkpoint(p2, state_f)
    _assert_trees_equal(r2, state_p)
    specs = [str(l.sharding.spec)
             for l in jax.tree_util.tree_leaves(r2.params)]
    assert any("fsdp" in s for s in specs)


def test_config_validation():
    with pytest.raises(ValueError, match="ckpt_keep"):
        Config(ckpt_keep=0)
    with pytest.raises(ValueError, match="checkpoint_dir"):
        Config(checkpoint_every=1)
    with pytest.raises(ValueError, match="resume"):
        Config(resume=True)


def test_driver_resume_continues(mesh8, tmp_path):
    kw = dict(model="mlp", dataset="mnist", epochs_local=1, batch_size=16,
              limit_train_samples=400, limit_eval_samples=50,
              compute_dtype="float32", augment=False,
              aggregation_by="weights", checkpoint_dir=str(tmp_path),
              checkpoint_every=1, seed=2)
    res1 = train_global(Config(epochs_global=2, **kw), mesh=mesh8,
                        progress=False)
    # round_timings carry the checkpoint walls every round (zero-filled
    # convention); checkpoint_every=1 means every round paid a snapshot
    # and its background write landed before results returned
    for t in res1["round_timings"]:
        assert t["ckpt_snapshot_ms"] > 0.0
        assert t["ckpt_write_ms"] > 0.0
    ck = res1["checkpoint"]
    assert ck["enabled"] and ck["async"] and ck["layout"] == "sharded"
    assert ck["saves"] == 2 and ck["bytes_per_host"] > 0
    # resume: run "4 epochs" but the first 2 come from the checkpoint
    res2 = train_global(Config(epochs_global=4, resume=True, **kw),
                        mesh=mesh8, progress=False)
    assert len(res2["global_train_losses"]) == 2  # only epochs 3 and 4 ran
    assert C.committed_epochs(str(tmp_path))[-1] == 4
