"""Checkpoint/resume round-trip (beyond-reference feature, SURVEY.md 5)."""

import numpy as np

import jax

from learning_deep_neural_network_in_distributed_computing_environment_tpu import checkpoint as C
from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import LocalSGDEngine


def test_save_restore_roundtrip(mesh8, tmp_path):
    cfg = Config(model="mlp", epochs_local=1, batch_size=8,
                 compute_dtype="float32", augment=False)
    engine = LocalSGDEngine(get_model("mlp", num_classes=10, hidden=8),
                            mesh8, cfg)
    x = np.zeros((8, 1, 8, 28, 28, 1), np.float32)
    state = engine.init_state(jax.random.key(0), x[0, 0])
    path = C.save_checkpoint(str(tmp_path), state, global_epoch=3)
    assert C.latest_checkpoint(str(tmp_path)) == path
    template = engine.init_state(jax.random.key(1), x[0, 0])
    restored, epoch = C.restore_checkpoint(path, template)
    assert epoch == 3
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_prune_keeps_newest(mesh8, tmp_path):
    cfg = Config(model="mlp", epochs_local=1, batch_size=8,
                 compute_dtype="float32", augment=False)
    engine = LocalSGDEngine(get_model("mlp", num_classes=10, hidden=8),
                            mesh8, cfg)
    x = np.zeros((8, 1, 8, 28, 28, 1), np.float32)
    state = engine.init_state(jax.random.key(0), x[0, 0])
    for e in range(1, 6):
        C.save_checkpoint(str(tmp_path), state, e, keep=2)
    assert C._list(str(tmp_path)) == [4, 5]


def test_driver_resume_continues(mesh8, tmp_path):
    kw = dict(model="mlp", dataset="mnist", epochs_local=1, batch_size=16,
              limit_train_samples=400, limit_eval_samples=50,
              compute_dtype="float32", augment=False,
              aggregation_by="weights", checkpoint_dir=str(tmp_path),
              checkpoint_every=1, seed=2)
    res1 = train_global(Config(epochs_global=2, **kw), mesh=mesh8,
                        progress=False)
    # resume: run "4 epochs" but the first 2 come from the checkpoint
    res2 = train_global(Config(epochs_global=4, resume=True, **kw),
                        mesh=mesh8, progress=False)
    assert len(res2["global_train_losses"]) == 2  # only epochs 3 and 4 ran
    assert C._list(str(tmp_path))[-1] == 4
