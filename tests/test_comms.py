"""Numerical goldens for the 12-mode sync matrix (SURVEY.md 2.3) on the
8-device CPU mesh, plus the gossip-convergence property."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu import comms

N = 8


def worker_values():
    """Distinct per-worker pytrees: worker i holds {a: i, b: [i, i+0.5]}."""
    return {
        "a": jnp.arange(N, dtype=jnp.float32).reshape(N, 1),
        "b": jnp.stack([jnp.arange(N, dtype=jnp.float32),
                        jnp.arange(N, dtype=jnp.float32) + 0.5], axis=1),
    }


def run(mesh8, how, topology, w=0.5):
    agg = comms.make_host_aggregator(mesh8, how=how, topology=topology,
                                     local_weight=w)
    out = agg(worker_values())
    return np.asarray(out["a"]).ravel(), np.asarray(out["b"])


class TestAllReduce:
    def test_equal_is_global_mean(self, mesh8):
        # ref: all_reduce SUM / world_size (communication.py:21-31)
        a, b = run(mesh8, "equal", "allreduce")
        np.testing.assert_allclose(a, np.full(N, 3.5), rtol=1e-6)
        np.testing.assert_allclose(b[:, 1], np.full(N, 4.0), rtol=1e-6)

    def test_weighted_self_exclusive_peer_mean(self, mesh8):
        # ref formula (communication.py:7-10): w*own + (1-w)*(sum-own)/(N-1)
        w = 0.3
        a, _ = run(mesh8, "weighted", "allreduce", w)
        own = np.arange(N, dtype=np.float64)
        expect = w * own + (1 - w) * (own.sum() - own) / (N - 1)
        np.testing.assert_allclose(a, expect, rtol=1e-6)


class TestRing:
    def test_equal_blends_with_predecessor(self, mesh8):
        # ref: recv from (rank-1+N)%N, new = (x + r)/2
        # (Balanced Ring/communication.py:5-30)
        a, _ = run(mesh8, "equal", "ring")
        own = np.arange(N, dtype=np.float64)
        pred = np.roll(own, 1)  # worker i receives worker i-1's value
        np.testing.assert_allclose(a, (own + pred) / 2, rtol=1e-6)

    def test_weighted(self, mesh8):
        # ref: w*x + (1-w)*r (Balanced Ring/communication.py:33-62)
        w = 0.25
        a, _ = run(mesh8, "weighted", "ring", w)
        own = np.arange(N, dtype=np.float64)
        np.testing.assert_allclose(a, w * own + (1 - w) * np.roll(own, 1),
                                   rtol=1e-6)


class TestDoubleRing:
    def test_equal_three_way_average(self, mesh8):
        # ref: (x + r1 + r2)/3 (Balanced Double-Ring/communication.py:5-40)
        a, _ = run(mesh8, "equal", "double_ring")
        own = np.arange(N, dtype=np.float64)
        expect = (own + np.roll(own, 1) + np.roll(own, 2)) / 3
        np.testing.assert_allclose(a, expect, rtol=1e-6)

    def test_weighted(self, mesh8):
        # ref: w*x + ((1-w)/2)*(r1+r2) (communication.py:43-77)
        w = 0.6
        a, _ = run(mesh8, "weighted", "double_ring", w)
        own = np.arange(N, dtype=np.float64)
        expect = w * own + ((1 - w) / 2) * (np.roll(own, 1) + np.roll(own, 2))
        np.testing.assert_allclose(a, expect, rtol=1e-6)


class TestProperties:
    @pytest.mark.parametrize("topology", ["ring", "double_ring"])
    def test_gossip_converges_to_consensus(self, mesh8, topology):
        """Repeated gossip averaging drives all workers to the global mean —
        the asymptotic behavior the reference's local-SGD relies on."""
        agg = comms.make_host_aggregator(mesh8, how="equal", topology=topology)
        x = worker_values()
        for _ in range(60):
            # block each round: on a 1-core host, pipelined executions of an
            # 8-thread collective can starve the XLA:CPU rendezvous past its
            # deadline and abort the process
            x = jax.block_until_ready(agg(x))
        a = np.asarray(x["a"]).ravel()
        # slowest gossip mode decays as cos(pi/8)^rounds ~ 0.924^60 ~ 0.009
        # of the initial spread (2.29) => ~0.02 residual for ring
        np.testing.assert_allclose(a, np.full(N, 3.5), atol=0.05)
        # mean is preserved by equal gossip (float32 accumulation slack)
        np.testing.assert_allclose(a.mean(), 3.5, rtol=1e-5)

    def test_gossip_preserves_mean_each_round(self, mesh8):
        agg = comms.make_host_aggregator(mesh8, how="equal", topology="ring")
        x = agg(worker_values())
        np.testing.assert_allclose(np.asarray(x["a"]).mean(), 3.5, rtol=1e-6)

    def test_all_modes_compile_and_preserve_structure(self, mesh8):
        for how in comms.HOWS:
            for topo in comms.TOPOLOGIES:
                a, b = run(mesh8, how, topo)
                assert a.shape == (N,) and b.shape == (N, 2)

    def test_invalid_args_raise(self, mesh8):
        with pytest.raises(ValueError, match="topology"):
            comms.make_host_aggregator(mesh8, how="equal", topology="star")(
                worker_values())
        with pytest.raises(ValueError, match="how"):
            comms.make_host_aggregator(mesh8, how="median", topology="ring")(
                worker_values())

    def test_single_worker_identity(self):
        """N==1: every mode is the identity (the reference's weighted
        all-reduce divides by zero here — deliberate fix, SURVEY.md 2.5.10)."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu import mesh as M
        mesh1 = M.build_mesh({"data": 1}, devices=jax.devices()[:1])
        x = {"a": jnp.ones((1, 3)) * 7}
        for how in comms.HOWS:
            for topo in comms.TOPOLOGIES:
                out = comms.make_host_aggregator(mesh1, how=how, topology=topo)(x)
                np.testing.assert_allclose(np.asarray(out["a"]), 7.0)
