"""Tests for the round-1-untested layer: ResNet-18/50, BERT, flash
attention (VERDICT r1 'Next' #5 — no source file with zero test references).

ResNet parameter counts are asserted against the canonical torchvision
values (resnet18 = 11,689,512; resnet50 = 25,557,032 at 1000 classes,
imagenet stem), pinning architectural parity for BASELINE ladder entries
3 and 4.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def _init(model, shape, dtype=jnp.float32):
    return jax.jit(functools.partial(model.init, train=False))(
        jax.random.key(0), jnp.zeros(shape, dtype))


def _param_count(variables):
    return int(sum(np.prod(p.shape)
                   for p in jax.tree.leaves(variables["params"])))


class TestResNet:
    def test_resnet18_imagenet_param_count_matches_torchvision(self):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models.resnet import ResNet18
        v = _init(ResNet18(num_classes=1000, stem="imagenet"), (1, 64, 64, 3))
        assert _param_count(v) == 11_689_512

    def test_resnet50_imagenet_param_count_matches_torchvision(self):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models.resnet import ResNet50
        v = _init(ResNet50(num_classes=1000, stem="imagenet"), (1, 64, 64, 3))
        assert _param_count(v) == 25_557_032

    def test_resnet18_cifar_forward_shape_and_grads(self):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models.resnet import ResNet18
        m = ResNet18(num_classes=10, stem="cifar")
        v = _init(m, (2, 32, 32, 3))
        assert _param_count(v) == 11_173_962

        @jax.jit
        def loss_fn(params):
            out, _ = m.apply({"params": params,
                              "batch_stats": v["batch_stats"]},
                             jnp.ones((2, 32, 32, 3)), train=True,
                             mutable=["batch_stats"])
            assert out.shape == (2, 10)
            return (out ** 2).mean()

        grads = jax.grad(loss_fn)(v["params"])
        assert all(np.isfinite(g).all() for g in jax.tree.leaves(grads))

    def test_resnet50_forward_shape(self):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models.resnet import ResNet50
        m = ResNet50(num_classes=1000, stem="imagenet")
        v = _init(m, (1, 64, 64, 3))
        out = jax.jit(functools.partial(m.apply, train=False))(
            v, jnp.ones((1, 64, 64, 3)))
        assert out.shape == (1, 1000)
        # imagenet stem: 64x64 -> /4 stem -> /8 stages = 2x2 pre-pool
        assert np.isfinite(out).all()


class TestLeNet:
    def test_avg_pool_2x2_matches_nn_avg_pool(self):
        """The reshape-mean pooling (TPU-backend compile-hang workaround)
        must be numerically identical to flax's nn.avg_pool."""
        import flax.linen as nn
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models.lenet import _avg_pool_2x2
        x = jnp.asarray(np.random.default_rng(0).normal(size=(3, 28, 28, 6)),
                        jnp.float32)
        np.testing.assert_allclose(_avg_pool_2x2(x),
                                   nn.avg_pool(x, (2, 2), strides=(2, 2)),
                                   atol=1e-6)

    @pytest.mark.parametrize("padding,cin,cout", [("SAME", 1, 6),
                                                  ("VALID", 6, 16)])
    def test_im2col_conv_matches_nn_conv(self, padding, cin, cout):
        """The im2col patch-matmul conv (TPU compile-hang workaround +
        MXU-utilization win for tiny channel counts) must match nn.Conv
        exactly, parameter-for-parameter."""
        import flax.linen as nn
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models.lenet import ConvIm2Col
        x = jnp.asarray(np.random.default_rng(1).normal(size=(4, 14, 14, cin)),
                        jnp.float32)
        m = ConvIm2Col(cout, (5, 5), padding=padding)
        v = m.init(jax.random.key(2), x)
        assert set(v["params"]) == {"kernel", "bias"}
        assert v["params"]["kernel"].shape == (5, 5, cin, cout)
        ref = nn.Conv(cout, (5, 5), padding=padding)
        out_ref = ref.apply(
            {"params": {"kernel": v["params"]["kernel"],
                        "bias": v["params"]["bias"]}}, x)
        np.testing.assert_allclose(m.apply(v, x), out_ref, atol=1e-5)

    def test_lenet5_param_count_forward_shape_and_grads(self):
        """LeNet-5 (SAME 5x5 stem on 28x28): 28->14->10->5 spatial,
        61,706 params (classic LeCun-98 count with the modern SAME stem)."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models.lenet import LeNet5
        m = LeNet5(num_classes=10)
        v = _init(m, (2, 28, 28, 1))
        assert _param_count(v) == 61_706

        @jax.jit
        def loss_fn(params):
            out = m.apply({"params": params}, jnp.ones((2, 28, 28, 1)),
                          train=True)
            assert out.shape == (2, 10)
            return (out ** 2).mean()

        grads = jax.grad(loss_fn)(v["params"])
        assert all(np.isfinite(g).all() for g in jax.tree.leaves(grads))


class TestBert:
    def _tiny(self, **kw):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
        return get_model("bert_tiny", num_classes=1000, **kw)

    def test_forward_shape(self):
        m = self._tiny()
        ids = jnp.ones((2, 32), jnp.int32)
        v = _init(m, (2, 32), jnp.int32)
        out = jax.jit(functools.partial(m.apply, train=False))(v, ids)
        assert out.shape == (2, 32, 1000)

    def test_param_count_formula(self):
        # tok_emb V*H + pos_emb 512*H + ln_emb 2H
        # + per layer: qkv 3(H*H+H) + out H*H+H + 2 LN 4H + ffn H*F+F+F*H+H
        # + head: H*H+H + 2H + H*V+V
        V, H, F, L, P = 1000, 64, 128, 2, 512
        per_layer = 3 * (H * H + H) + H * H + H + 4 * H + H * F + F + F * H + H
        expect = (V * H + P * H + 2 * H + L * per_layer
                  + H * H + H + 2 * H + H * V + V)
        v = _init(self._tiny(), (2, 32), jnp.int32)
        assert _param_count(v) == expect

    def test_grads_finite(self):
        m = self._tiny()
        v = _init(m, (2, 32), jnp.int32)
        ids = jnp.ones((2, 32), jnp.int32)

        @jax.jit
        def loss_fn(params):
            out = m.apply({"params": params}, ids, train=True)
            return (out ** 2).mean()

        grads = jax.grad(loss_fn)(v["params"])
        assert all(np.isfinite(g).all() for g in jax.tree.leaves(grads))

    def test_bert_base_is_base_sized(self):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
        m = get_model("bert_base", num_classes=30522)
        assert (m.num_layers, m.hidden, m.num_heads, m.ffn_dim) == \
            (12, 768, 12, 3072)


@pytest.mark.slow
class TestFlashAttention:
    """Pallas flash kernel in interpret mode (CPU) vs the dense reference."""

    def _qkv(self, b=2, l=256, h=2, d=64, seed=0, dtype=jnp.float32):
        rng = np.random.default_rng(seed)
        return tuple(jnp.asarray(rng.normal(size=(b, l, h, d)), dtype)
                     for _ in range(3))

    def test_forward_matches_dense(self):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.ops.pallas_ops import flash_attention
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.ops.attention import dot_product_attention
        q, k, v = self._qkv()
        np.testing.assert_allclose(flash_attention(q, k, v),
                                   dot_product_attention(q, k, v), atol=1e-5)

    def test_backward_matches_dense(self):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.ops.pallas_ops import flash_attention
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.ops.attention import dot_product_attention
        q, k, v = self._qkv(seed=1)
        g = jax.grad(lambda *a: (flash_attention(*a) ** 2).sum(),
                     argnums=(0, 1, 2))(q, k, v)
        gref = jax.grad(lambda *a: (dot_product_attention(*a) ** 2).sum(),
                        argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gref):
            np.testing.assert_allclose(a, b, atol=1e-4)

    def test_fused_bwd_matches_twopass(self, monkeypatch):
        """The r5 single-pass fused backward (one softmax recompute, dq
        as per-key-block partials) == the two-pass FA-2 backward, over
        {bidirectional, causal} x {MHA, grouped-query}.  Block sizes are
        shrunk to 128 so L=512 yields a 4x4 block grid — exercising the
        fused kernel's novel paths (causal masked-tile zeroing, multi-
        block dq-partial reduction, cross-block dk/dv accumulation),
        which a single-block grid never enters (code-review r5)."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.ops import pallas_ops
        monkeypatch.setattr(pallas_ops, "BQ", 128)
        monkeypatch.setattr(pallas_ops, "BK", 128)
        rng = np.random.default_rng(4)
        for causal in (False, True):
            for kvh in (2, 1):
                q, k, v = self._qkv(l=512, seed=4)
                k, v = k[:, :, :kvh], v[:, :, :kvh]
                o, lse = pallas_ops._flash_forward(q, k, v, causal,
                                                   with_lse=True)
                g = jnp.asarray(rng.normal(size=o.shape), o.dtype)
                two = pallas_ops._flash_backward(q, k, v, o, lse, g,
                                                 causal)
                fused = pallas_ops._flash_backward_fused(q, k, v, o, lse,
                                                         g, causal)
                for a, b in zip(two, fused):
                    np.testing.assert_allclose(
                        np.asarray(a), np.asarray(b), atol=1e-4,
                        err_msg=f"causal={causal} kvh={kvh}")

    def test_unaligned_shapes_fall_back_to_dense(self):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.ops.pallas_ops import flash_attention
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.ops.attention import dot_product_attention
        q, k, v = self._qkv(l=100, seed=2)  # 100 % 128 != 0
        np.testing.assert_allclose(flash_attention(q, k, v),
                                   dot_product_attention(q, k, v), atol=1e-6)

    def test_attend_dispatch(self):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.ops.attention import attend
        q, k, v = self._qkv(l=128, seed=3)
        np.testing.assert_allclose(attend(q, k, v, impl="flash"),
                                   attend(q, k, v, impl="dense"), atol=1e-5)
        with pytest.raises(ValueError):
            attend(q, k, v, impl="nope")
        with pytest.raises(ValueError):
            attend(q, k, v, impl="ring")  # no axis_name

    def test_driver_attention_impl_flash(self, devices):
        """--attention_impl flash is plumbed through config -> driver ->
        engine.  On CPU the kernel falls back to dense inside shard_map
        (Pallas HLO-interpreter limitation), so this asserts the plumbing
        and exact numerical agreement; the kernel itself is covered by the
        unit tests above and compiles for real inside the TPU round
        program (bench.py flash entry)."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        mesh = build_mesh({"data": 2}, devices[:2])
        kw = dict(model="bert_tiny", dataset="synthetic_mlm",
                  epochs_global=1, epochs_local=1, batch_size=4,
                  limit_train_samples=32, limit_eval_samples=16,
                  compute_dtype="float32", augment=False,
                  aggregation_by="weights", seed=5)
        flash = train_global(Config(attention_impl="flash", **kw),
                             mesh=mesh, progress=False)
        dense = train_global(Config(**kw), mesh=mesh, progress=False)
        np.testing.assert_allclose(flash["global_train_losses"],
                                   dense["global_train_losses"], rtol=1e-4)
