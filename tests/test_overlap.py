"""Overlapped round pipeline (ISSUE 1 tentpole).

The overlap is scheduling-only: metric fetch + assembly move to a worker
thread and the next round's re-partition + packing run while the device
computes, but the data flow (delayed-EMA straggler feedback in BOTH
modes) is identical — so overlapped and serial runs must produce
bit-identical ``results`` dicts.  Also covers the streamed path's bounded
staging queue and checkpoint restore under cross-round state donation.
"""

import time

import numpy as np
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import (
    _assemble_round_metrics,
    train_global,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import ChunkStager


def cfg(**kw):
    base = dict(model="mlp", dataset="mnist", epochs_global=3, epochs_local=2,
                batch_size=16, limit_train_samples=800,
                limit_eval_samples=100, compute_dtype="float32",
                augment=False, aggregation_by="weights", seed=1)
    base.update(kw)
    return Config(**base)


METRIC_KEYS = (
    "all_epochs_losses", "global_epoch_losses", "global_epoch_accuracies",
    "global_train_losses", "global_train_accuracies",
    "global_val_losses", "global_val_accuracies",
    "worker_specific_train_losses", "worker_specific_train_accuracies",
    "worker_specific_val_losses", "worker_specific_val_accuracies",
    "step_caps", "shard_sizes",
)


def assert_identical_results(a, b):
    for k in METRIC_KEYS:
        assert a[k] == b[k], f"results[{k!r}] differ"
    for i, (wa, wb) in enumerate(zip(a["all_workers_losses"],
                                     b["all_workers_losses"])):
        assert wa == wb, f"all_workers_losses[{i}] differ"


class TestOverlapMatchesSerial:
    # probe AND per-round walls pinned: the only nondeterminism left
    # would be the pipeline itself, which must introduce none.  The
    # per-round-VARYING walls exercise the delayed-EMA repartition —
    # caps and shard indices must still match exactly across modes.
    PROBE = np.array([1.0, 1.5, 1.0, 2.0, 1.0, 1.0, 3.0, 1.0])
    WALLS = staticmethod(lambda e: np.linspace(1.0, 2.0, 8) * (1.0 + e))

    def test_packed_bitwise_identical(self, mesh8):
        runs = {}
        for overlap in (False, True):
            runs[overlap] = train_global(
                cfg(overlap_rounds=overlap), mesh=mesh8, progress=False,
                simulated_durations=self.PROBE,
                simulated_round_durations=self.WALLS)
        assert_identical_results(runs[False], runs[True])

    def test_streamed_bitwise_identical(self, mesh8):
        # streamed path: serial/no-prefetch vs overlapped/double-buffered
        # producer — the stager must be a pure scheduling change too
        serial = train_global(
            cfg(stream_chunk_steps=2, stream_prefetch=0,
                overlap_rounds=False),
            mesh=mesh8, progress=False, simulated_durations=self.PROBE,
            simulated_round_durations=self.WALLS)
        overlapped = train_global(
            cfg(stream_chunk_steps=2, stream_prefetch=2,
                overlap_rounds=True),
            mesh=mesh8, progress=False, simulated_durations=self.PROBE,
            simulated_round_durations=self.WALLS)
        assert_identical_results(serial, overlapped)

    def test_round_timings_recorded(self, mesh8):
        res = train_global(cfg(epochs_global=2), mesh=mesh8, progress=False)
        timings = res["round_timings"]
        assert len(timings) == 2
        for t in timings:
            for k in ("stage_ms", "compute_ms", "fetch_ms", "assemble_ms"):
                assert k in t and t[k] >= 0.0, (k, t)
        # the round gap is the ready->next-dispatch window: every round
        # but the last has one
        assert "gap_ms" in timings[0] and "gap_ms" not in timings[-1]


class TestChunkStager:
    def test_queue_bound_respected(self):
        depth = 2
        produced = [0]

        def gen():
            for i in range(10):
                produced[0] += 1
                yield i

        stager = ChunkStager(gen(), stage_fn=lambda x: x, depth=depth)
        out = []
        for item in stager:
            # give the producer every chance to run ahead; the bounded
            # queue must cap it at depth staged + 1 in its hands
            time.sleep(0.02)
            out.append(item)
            assert produced[0] - len(out) <= depth + 1, \
                (produced[0], len(out))
        assert out == list(range(10))

    def test_generator_error_propagates(self):
        def gen():
            yield 1
            raise RuntimeError("boom")

        stager = ChunkStager(gen(), stage_fn=lambda x: x, depth=2)
        with pytest.raises(RuntimeError, match="boom"):
            list(stager)

    def test_close_unparks_producer_and_drains(self):
        # a consumer that bails mid-round must be able to release the
        # staged windows: close() stops the producer (parked on the full
        # queue) and drains what it staged
        stager = ChunkStager(iter(range(100)), stage_fn=lambda x: x,
                             depth=2)
        it = iter(stager)
        assert next(it) == 0
        stager.close()
        stager._t.join(timeout=5.0)
        assert not stager._t.is_alive()
        assert stager._q.empty()


class TestDonationAndCheckpoint:
    def test_restore_midrun_continues(self, mesh8, tmp_path):
        # cross-round state donation must not corrupt what checkpointing
        # reads: saves happen after round_wait and before the next
        # dispatch, so the buffers are fetched before donation can
        # invalidate them — run, resume, and keep training
        ck = str(tmp_path / "ckpts")
        walls = lambda e: np.ones(8)
        first = train_global(
            cfg(epochs_global=2, checkpoint_dir=ck, checkpoint_every=1),
            mesh=mesh8, progress=False, simulated_round_durations=walls)
        assert len(first["global_train_losses"]) == 2
        resumed = train_global(
            cfg(epochs_global=3, checkpoint_dir=ck, checkpoint_every=1,
                resume=True),
            mesh=mesh8, progress=False, simulated_round_durations=walls)
        # resumed from epoch 2: exactly one more round ran, finitely
        assert len(resumed["global_train_losses"]) == 1
        assert np.isfinite(resumed["global_train_losses"]).all()


class TestVectorizedAssembly:
    def test_matches_reference_loops(self):
        rng = np.random.default_rng(0)
        n, epochs_local, steps = 4, 3, 7
        mx = dict(
            batch_losses=rng.normal(size=(n, epochs_local, steps)).astype(
                np.float32),
            batch_mask=(rng.random((n, epochs_local, steps)) > 0.3).astype(
                np.float32),
            avg_acc=rng.random((n, epochs_local)).astype(np.float32),
            train_loss=rng.random((n, epochs_local)).astype(np.float32),
            train_acc=rng.random((n, epochs_local)).astype(np.float32),
            val_loss=rng.random((n, epochs_local)).astype(np.float32),
            val_acc=rng.random((n, epochs_local)).astype(np.float32),
            global_train_loss=rng.random(n).astype(np.float32),
            global_train_acc=rng.random(n).astype(np.float32),
            global_val_loss=rng.random(n).astype(np.float32),
            global_val_acc=rng.random(n).astype(np.float32),
        )

        def fresh():
            return {
                "all_workers_losses": [[] for _ in range(n)],
                "all_epochs_losses": [], "global_epoch_losses": [],
                "global_epoch_accuracies": [], "global_train_losses": [],
                "global_train_accuracies": [], "global_val_losses": [],
                "global_val_accuracies": [],
                "worker_specific_train_losses": [],
                "worker_specific_train_accuracies": [],
                "worker_specific_val_losses": [],
                "worker_specific_val_accuracies": [],
            }

        # the pre-pipeline reference implementation (driver.py:499-528 at
        # the seed): nested per-epoch/per-worker Python loops
        ref = fresh()
        bl, bm = mx["batch_losses"], mx["batch_mask"]
        current_losses = []
        for e in range(epochs_local):
            epoch_all_workers = []
            for i in range(n):
                valid = bl[i, e][bm[i, e] > 0].tolist()
                ref["all_workers_losses"][i].extend(valid)
                epoch_all_workers.extend(valid)
            ref["all_epochs_losses"].append(epoch_all_workers)
            current_losses.extend(epoch_all_workers)
        ref["global_epoch_losses"].append(current_losses)
        ref["global_epoch_accuracies"].append(mx["avg_acc"][0].tolist())
        ref["global_train_losses"].append(float(mx["global_train_loss"][0]))
        ref["global_train_accuracies"].append(
            float(mx["global_train_acc"][0]))
        ref["global_val_losses"].append(float(mx["global_val_loss"][0]))
        ref["global_val_accuracies"].append(float(mx["global_val_acc"][0]))
        ref["worker_specific_train_losses"].extend(
            mx["train_loss"][0].tolist())
        ref["worker_specific_train_accuracies"].extend(
            mx["train_acc"][0].tolist())
        ref["worker_specific_val_losses"].extend(mx["val_loss"][0].tolist())
        ref["worker_specific_val_accuracies"].extend(
            mx["val_acc"][0].tolist())

        got = fresh()
        _assemble_round_metrics(got, mx, n)
        assert got == ref
