"""Test harness: force an 8-device virtual CPU mesh.

This is the reference-impossible trick that replaces its (absent) test
strategy: every mesh/psum/ppermute path and all 12 DP sync modes run as
ordinary pytest cases on one host (SURVEY.md section 4).

Note: this environment registers an out-of-tree TPU PJRT plugin at
interpreter start and pins ``jax_platforms`` via ``jax.config`` — an env-var
override is silently ignored, so the CPU pin must also go through
``jax.config.update`` after importing jax.
"""

import os

# XLA flags are read at first backend initialization; set before any
# jax.devices() call.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# deadlock workaround for the CPU thunk executor (see the helper's docs)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.xla_flags import (  # noqa: E402
    ensure_sequential_cpu_collectives,
)

ensure_sequential_cpu_collectives()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Opt-in session-persistent XLA compile cache (ISSUE 3 satellite): point
# JAX_GRAFT_TEST_COMPILE_CACHE at a directory (e.g. .jax_cache/tests) and
# repeated suite runs on one host stop re-paying the round-program
# compiles that dominate tier-1 wall.  Opt-in because a cache shared
# across code revisions can mask compile-path regressions — CI tiers that
# only gate on numerics should set it, compile-timing work must not.
_test_cache = os.environ.get("JAX_GRAFT_TEST_COMPILE_CACHE", "")
if _test_cache:
    from learning_deep_neural_network_in_distributed_computing_environment_tpu.xla_flags import (  # noqa: E402
        setup_compile_cache,
    )
    setup_compile_cache(_test_cache, min_compile_secs=0.5)

# JAX-version compat: publishes jax.shard_map / jax.typeof / lax.pcast /
# lax.axis_size shims on legacy runtimes (e.g. 0.4.x) before any test
# references them directly
from learning_deep_neural_network_in_distributed_computing_environment_tpu import (  # noqa: E402
    compat as _compat,
)

_compat.install()

import pytest  # noqa: E402

# --- quick tier ----------------------------------------------------------
# ``pytest -m quick`` selects ONE representative case per subsystem,
# <= ~5 minutes total on the virtual CPU mesh — the pre-commit smoke run
# (the full suite stays the round gate; round-2 verdict weak #8).  Entries
# are nodeid prefixes, so a bare file selects its whole (cheap) module.
QUICK_PREFIXES = (
    "tests/test_model.py::test_param_count_matches_reference",
    "tests/test_comms.py::TestAllReduce::test_equal_is_global_mean",
    "tests/test_comms.py::TestRing::test_equal_blends_with_predecessor",
    "tests/test_comms.py::TestDoubleRing::test_equal_three_way_average",
    "tests/test_partition.py",          # pure-numpy partition math
    "tests/test_train.py::TestStepLR",
    "tests/test_train.py::TestCrossEntropy",
    "tests/test_train.py::TestEngine::test_round_learns_and_lr_epoch_advances",
    "tests/test_eval_viz.py::TestPRF",
    "tests/test_eval_viz.py::TestViz::test_all_six_files_written",
    "tests/test_checkpoint.py::test_save_restore_roundtrip",
    "tests/test_gqa.py::TestDenseGrouped",
    "tests/test_gpt.py::TestCausalAttention::test_dense_causal_equals_masked",
    "tests/test_sp.py::TestRingAttention::test_forward_matches_dense",
    "tests/test_pp.py::TestGpipeSchedule::test_forward_matches_sequential",
    "tests/test_tp.py::TestTPModule::test_forward_matches_dense",
    "tests/test_fsdp.py::TestSpecsAndGather::test_large_leaves_shard_small_replicate",
    "tests/test_moe.py::TestMoEFFN::test_output_shape_and_aux_loss",
    "tests/test_streaming.py::TestPackWindow",
)


# --- known-upstream legacy-JAX failures -> version-gated xfail -----------
# The two tier-1 cases below fail for documented UPSTREAM reasons on the
# legacy 0.4.x runtime (ROADMAP known-failure ledger), not for anything
# this repo controls: (a) the legacy shard_map check_rep machinery has a
# scan-transpose bug under the ring-attention backward ("mismatched
# replication types"), which the engine works around everywhere except
# this pure-schedule gradient unit; (b) jaxlib 0.4.37's CPU client cannot
# run multi-process computations at all.  Marking them xfail keeps the
# tier-1 line CLEAN (pass/xfail, rc 0) while strict=True still ALARMS the
# moment a runtime upgrade makes one pass unexpectedly — the cue to
# remove the gate and re-enable the case.
_JAX_LEGACY = tuple(int(p) for p in jax.__version__.split(".")[:2]) < (0, 5)
KNOWN_UPSTREAM_XFAILS = {
    "tests/test_pp.py::TestGpipeSchedule::test_grads_match_sequential":
        "upstream legacy-JAX check_rep scan-transpose bug in the GPipe "
        "schedule backward (fixed in jax >= 0.5; ROADMAP ledger (a))",
    "tests/test_multihost.py::test_two_process_driver_run":
        "jaxlib 0.4.x CPU client cannot run multi-process computations "
        "(ROADMAP ledger (b))",
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "quick: one fast case per subsystem (pre-commit smoke "
        "tier; the full suite remains the round gate)")


def pytest_collection_modifyitems(config, items):
    for item in items:
        nodeid = item.nodeid.replace("\\", "/")
        if not nodeid.startswith("tests/"):
            nodeid = "tests/" + nodeid
        if any(nodeid.startswith(p) for p in QUICK_PREFIXES):
            item.add_marker(pytest.mark.quick)
        if _JAX_LEGACY and nodeid in KNOWN_UPSTREAM_XFAILS:
            item.add_marker(pytest.mark.xfail(
                reason=KNOWN_UPSTREAM_XFAILS[nodeid], strict=True))


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8 and devs[0].platform == "cpu", \
        f"expected 8 virtual CPU devices, got {devs}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    from learning_deep_neural_network_in_distributed_computing_environment_tpu import mesh as mesh_lib
    return mesh_lib.build_mesh({"data": 8})
