"""Test harness: force an 8-device virtual CPU mesh before jax imports.

This is the reference-impossible trick that replaces its (absent) test
strategy: every mesh/psum/ppermute path and all 12 DP sync modes run as
ordinary pytest cases on one host (SURVEY.md section 4).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (import after env setup)
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    from learning_deep_neural_network_in_distributed_computing_environment_tpu import mesh as mesh_lib
    return mesh_lib.build_mesh({"data": 8})
