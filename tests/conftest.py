"""Test harness: force an 8-device virtual CPU mesh.

This is the reference-impossible trick that replaces its (absent) test
strategy: every mesh/psum/ppermute path and all 12 DP sync modes run as
ordinary pytest cases on one host (SURVEY.md section 4).

Note: this environment registers an out-of-tree TPU PJRT plugin at
interpreter start and pins ``jax_platforms`` via ``jax.config`` — an env-var
override is silently ignored, so the CPU pin must also go through
``jax.config.update`` after importing jax.
"""

import os

# XLA flags are read at first backend initialization; set before any
# jax.devices() call.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8 and devs[0].platform == "cpu", \
        f"expected 8 virtual CPU devices, got {devs}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    from learning_deep_neural_network_in_distributed_computing_environment_tpu import mesh as mesh_lib
    return mesh_lib.build_mesh({"data": 8})
