"""Shard-resident optimizer placement (ISSUE 9 tentpole).

The round-boundary sync is scatter -> APPLY -> gather; ``--opt_placement``
places the apply stage and its state (the ZeRO-1 cross-replica
weight-update scheme, arXiv 2004.13336):

- fp32 apply is BITWISE placement-invariant (sharded == replicated ==
  dense) across worker counts and both blend hows;
- the gradients-mode round-optimizer Adam moments (TrainState.round_opt)
  track the worker-invariant mean gradient, so the sharded layout stores
  each worker's 1/N bucket shard — exactly 1/N per-worker bytes — and is
  the exact row-partition of the replicated layout;
- checkpoints re-layout across placements on restore, elastic membership
  changes re-tile the tracker for the new worker count;
- gossip topologies resolve to the "local" placement (worker-local
  blends — nothing cross-replica-redundant to shard).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu import (
    comms,
    elastic as elastic_lib,
    mesh as mesh_lib,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu import checkpoint as ckpt_lib
from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import (
    LocalSGDEngine,
    TrainState,
)

N = 8
SHAPES = {"a": (13, 7), "b": (257,), "c": (31, 5), "d": (3,)}
TINY_BUCKET = 1024


def stacked_tree(n=N, seed=0):
    rng = np.random.default_rng(seed)
    return {k: jnp.asarray(rng.normal(size=(n, *s)), jnp.float32)
            for k, s in SHAPES.items()}


def per_worker_shapes():
    return {k: jax.ShapeDtypeStruct(s, jnp.float32)
            for k, s in SHAPES.items()}


def sub_mesh(k):
    return mesh_lib.build_mesh({"data": k}, devices=jax.devices()[:k])


def small_cfg(**kw):
    base = dict(model="mlp", dataset="mnist", epochs_local=2,
                epochs_global=2, batch_size=8, compute_dtype="float32",
                augment=False, aggregation_by="weights")
    base.update(kw)
    return Config(**base)


def make_engine(mesh, cfg):
    return LocalSGDEngine(get_model("mlp", num_classes=10, hidden=16),
                          mesh, cfg)


def make_packs(n=8, steps=4, b=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, steps, b, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, (n, steps, b)).astype(np.int32)
    m = np.ones((n, steps, b), np.float32)
    return x, y, m


class TestPlacementResolution:
    def test_auto_follows_the_sync_engine(self):
        # CPU fp32 auto-resolves the dense sync engine, whose arithmetic
        # is literally replicated; the bucketed engine pulls the apply
        # onto the shard
        assert small_cfg().resolve_opt_placement("cpu") == "replicated"
        assert small_cfg(
            sync_mode="sharded").resolve_opt_placement("cpu") == "sharded"
        assert small_cfg().resolve_opt_placement("tpu") == "sharded"
        assert small_cfg(
            sync_dtype="bfloat16", sync_compression="ef",
        ).resolve_opt_placement("cpu") == "sharded"

    def test_explicit_sharded_selects_the_fast_engine(self):
        cfg = small_cfg(opt_placement="sharded")
        assert cfg.resolve_sync_mode("cpu") == "sharded"
        assert cfg.resolve_opt_placement("cpu") == "sharded"

    @pytest.mark.parametrize("topology", ["ring", "double_ring"])
    def test_gossip_resolves_local(self, topology):
        # gossip blends are worker-specific by construction: nothing
        # cross-replica-redundant exists to shard (docs/ARCHITECTURE.md)
        for flag in ("auto", "replicated", "sharded"):
            cfg = small_cfg(topology=topology, opt_placement=flag)
            assert cfg.resolve_opt_placement("cpu") == "local"

    def test_sharded_with_dense_sync_rejected(self):
        with pytest.raises(ValueError, match="sync_mode dense"):
            small_cfg(opt_placement="sharded", sync_mode="dense")

    def test_replicated_with_compressed_wire_rejected(self):
        # the gathered payload IS the encoded mean: the scale must run
        # before the encode, on the shard
        with pytest.raises(ValueError, match="replicated"):
            small_cfg(opt_placement="replicated", sync_dtype="bfloat16")

    def test_comms_rejects_compressed_replicated_apply(self, mesh8):
        tree = stacked_tree()
        with pytest.raises(Exception, match="sharded"):
            comms.make_host_sync(
                mesh8, mode="sharded", wire_dtype=jnp.bfloat16,
                opt_placement="replicated")(tree)


class TestApplyPlacementBitwise:
    @pytest.mark.parametrize("k", [2, 4, 8])
    @pytest.mark.parametrize("how", ["equal", "weighted"])
    def test_fp32_sharded_apply_bitwise_equals_replicated(self, k, how):
        """The acceptance gate: scatter->apply->gather with the apply on
        the 1/N shard vs the post-gather replicated twin — bitwise, and
        both bitwise == the dense all-reduce."""
        mesh = sub_mesh(k)
        tree = stacked_tree(n=k)
        dense = comms.make_host_sync(mesh, mode="dense", how=how,
                                     local_weight=0.3)(tree)[0]
        outs = {
            pl: comms.make_host_sync(
                mesh, mode="sharded", how=how, local_weight=0.3,
                bucket_bytes=TINY_BUCKET, opt_placement=pl)(tree)[0]
            for pl in ("replicated", "sharded")}
        for key in SHAPES:
            a = np.asarray(outs["replicated"][key])
            b = np.asarray(outs["sharded"][key])
            assert np.array_equal(a, b), (how, key)
            assert np.array_equal(np.asarray(dense[key]), b), (how, key)


class TestRoundOptTracker:
    def test_init_layout_bytes_exactly_one_nth(self):
        pw = per_worker_shapes()
        byt = {}
        for pl in ("replicated", "sharded"):
            trk = comms.round_opt_init(pw, N, placement=pl,
                                       bucket_bytes=TINY_BUCKET)
            assert len(trk) == len(comms.bucket_plan(
                list(pw.values()), N, TINY_BUCKET))
            byt[pl] = sum(l.nbytes // N
                          for l in jax.tree_util.tree_leaves(trk))
        assert byt["replicated"] == N * byt["sharded"]

    @pytest.mark.parametrize("how", ["equal", "weighted"])
    def test_sharded_rows_partition_the_replicated_vector(self, mesh8,
                                                          how):
        tree = stacked_tree()
        trackers = {}
        for pl in ("replicated", "sharded"):
            trk = comms.round_opt_init(per_worker_shapes(), N,
                                       placement=pl,
                                       bucket_bytes=TINY_BUCKET)
            fn = comms.make_host_sync(
                mesh8, mode="sharded", how=how, local_weight=0.3,
                bucket_bytes=TINY_BUCKET, opt_placement=pl,
                track_opt=True)
            for _ in range(2):   # two rounds: moments actually decay
                _out, _r, trk = jax.block_until_ready(
                    fn(tree, None, trk))
            trackers[pl] = jax.device_get(trk)
        some_nonzero = False
        for b in trackers["sharded"]:
            for m in ("mu", "nu"):
                srows = np.asarray(trackers["sharded"][b][m])
                rrows = np.asarray(trackers["replicated"][b][m])
                # replicated layout: N identical copies of the vector
                assert np.array_equal(
                    rrows, np.broadcast_to(rrows[:1], rrows.shape))
                # sharded layout: its exact row-partition, bitwise
                assert np.array_equal(srows.reshape(-1), rrows[0]), (b, m)
                some_nonzero |= bool(np.abs(srows).max() > 0)
        assert some_nonzero

    def test_tracker_follows_adam_moments_of_the_mean(self, mesh8):
        # one bucket, one round: mu = (1-b1) * mean, nu = (1-b2) * mean^2
        tree = stacked_tree()
        trk = comms.round_opt_init(per_worker_shapes(), N,
                                   placement="replicated")
        _out, _r, trk = comms.make_host_sync(
            mesh8, mode="sharded", opt_placement="replicated",
            track_opt=True)(tree, None, trk)
        flat = np.concatenate([
            np.asarray(tree[k], np.float32).sum(0).reshape(-1) / N
            for k in sorted(SHAPES)])
        got = np.asarray(jax.device_get(trk)[comms._bucket_name(0)]["mu"])
        filled = flat.size
        np.testing.assert_allclose(
            got[0][:filled], (1.0 - comms.ROUND_ADAM_B1) * flat,
            rtol=1e-6, atol=1e-8)
        assert np.all(got[0][filled:] == 0)   # padding moments stay zero
        nu = np.asarray(jax.device_get(trk)[comms._bucket_name(0)]["nu"])
        np.testing.assert_allclose(
            nu[0][:filled], (1.0 - comms.ROUND_ADAM_B2) * flat * flat,
            rtol=1e-5, atol=1e-10)

    def test_relayout_roundtrips_and_validates(self):
        pw = per_worker_shapes()
        trk = jax.device_get(comms.round_opt_init(
            pw, N, placement="sharded", bucket_bytes=TINY_BUCKET))
        # fill only the FILLED region (padding carries exactly-zero
        # moments by construction — the padded mean is zero every round)
        rng = np.random.default_rng(0)
        plan = comms.bucket_plan(list(pw.values()), N, TINY_BUCKET)
        for i, b in enumerate(plan):
            filled = sum(s for (_i, _o, s) in b.items)
            for m in ("mu", "nu"):
                vec = np.zeros(b.padded, np.float32)
                vec[:filled] = rng.normal(size=filled)
                trk[comms._bucket_name(i)][m] = vec.reshape(N, -1)
        down = comms.round_opt_relayout(trk, pw, 3, placement="sharded",
                                        bucket_bytes=TINY_BUCKET)
        back = comms.round_opt_relayout(down, pw, N, placement="sharded",
                                        bucket_bytes=TINY_BUCKET)
        for b in trk:
            for m in ("mu", "nu"):
                assert np.array_equal(trk[b][m], back[b][m]), (b, m)
        with pytest.raises(ValueError, match="bucket"):
            comms.round_opt_relayout({}, pw, 4, placement="sharded",
                                     bucket_bytes=TINY_BUCKET)


class TestEngineOptPlacement:
    def _round(self, mesh8, cfg):
        engine = make_engine(mesh8, cfg)
        x, y, m = make_packs()
        state = engine.init_state(jax.random.key(0), x[0, 0])
        state, mx = engine.round(state, (x, y, m), (x, y, m))
        return engine, state, mx

    def test_weights_round_bitwise_across_placements(self, mesh8):
        # param_residency pinned replicated: this case gates the ISSUE 9
        # apply PLACEMENT on the full params tree (the sharded-placement
        # run would otherwise auto-resolve the ISSUE 11 resident layout,
        # whose params leaves are empty — tests/test_param_residency.py
        # owns that axis)
        states = {}
        for pl in ("replicated", "sharded"):
            eng, st, _ = self._round(
                mesh8, small_cfg(sync_mode="sharded",
                                 sync_bucket_mb=0.001, opt_placement=pl,
                                 param_residency="replicated"))
            assert eng.opt_placement == pl
            assert st.round_opt is None    # weights mode: no boundary
            states[pl] = st                # moments exist to track
        leaves = {
            pl: jax.tree_util.tree_leaves(states[pl].params)
            for pl in states}
        assert leaves["replicated"] and (
            len(leaves["replicated"]) == len(leaves["sharded"]))
        for a, b in zip(leaves["replicated"], leaves["sharded"]):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_gradients_tracker_layouts_and_norm_bitwise(self, mesh8):
        outs = {}
        for pl in ("replicated", "sharded"):
            eng, st, mx = self._round(
                mesh8, small_cfg(aggregation_by="gradients",
                                 sync_mode="sharded",
                                 sync_bucket_mb=0.001, opt_placement=pl))
            assert eng.round_opt_on
            assert st.round_opt is not None
            outs[pl] = (jax.device_get(st.round_opt),
                        np.asarray(mx["agg_grad_norm"]))
        # the reported aggregated-grad norm is placement-invariant
        assert np.array_equal(outs["replicated"][1], outs["sharded"][1])
        for b in outs["sharded"][0]:
            for m in ("mu", "nu"):
                srows = np.asarray(outs["sharded"][0][b][m])
                rrows = np.asarray(outs["replicated"][0][b][m])
                assert np.array_equal(srows.reshape(-1), rrows[0]), (b, m)
        # the N-fold per-worker state drop, measured
        per = lambda t: sum(l.nbytes // N
                            for l in jax.tree_util.tree_leaves(t))
        assert per(outs["replicated"][0]) == N * per(outs["sharded"][0])

    def test_tracker_off_under_inner_axes_and_weights_mode(self, mesh8):
        eng = make_engine(mesh8, small_cfg(sync_mode="sharded"))
        assert not eng.round_opt_on    # weights mode
        eng = make_engine(mesh8, small_cfg(aggregation_by="gradients"))
        assert not eng.round_opt_on    # dense engine on CPU fp32 auto


class TestCheckpointCrossPlacement:
    def _state_with_tracker(self, mesh8, placement):
        cfg = small_cfg(aggregation_by="gradients", sync_mode="sharded",
                        sync_bucket_mb=0.001, opt_placement=placement)
        engine = make_engine(mesh8, cfg)
        state = engine.init_state(
            jax.random.key(0), np.zeros((8, 28, 28, 1), np.float32))
        # deterministic nonzero moments with the zero-pad invariant held
        host = jax.device_get(state.round_opt)
        rng = np.random.default_rng(7)
        pw = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape[1:], x.dtype),
            jax.device_get(state.params))
        plan = comms.bucket_plan(jax.tree_util.tree_leaves(pw), N,
                                 engine.sync_bucket_bytes)
        for i, b in enumerate(plan):
            filled = sum(s for (_i, _o, s) in b.items)
            vec = np.zeros(b.padded, np.float32)
            vec[:filled] = rng.normal(size=filled)
            for m in ("mu", "nu"):
                name = comms._bucket_name(i)
                host[name][m] = (vec.reshape(N, -1)
                                 if placement == "sharded" else
                                 np.broadcast_to(vec,
                                                 (N, b.padded)).copy())
        filled_state = state.replace(round_opt=jax.tree_util.tree_map(
            lambda a, t: jax.device_put(np.asarray(a),
                                        t.sharding),
            host, state.round_opt))
        return engine, filled_state

    def test_roundtrip_across_placements_both_directions(self, mesh8,
                                                         tmp_path):
        _eng_s, st_s = self._state_with_tracker(mesh8, "sharded")
        _eng_r, tmpl_r = self._state_with_tracker(mesh8, "replicated")
        # sharded save -> replicated restore
        ckpt_lib.save_checkpoint(str(tmp_path / "s"), st_s, 1)
        got_r, ep = ckpt_lib.restore_checkpoint(
            ckpt_lib.latest_checkpoint(str(tmp_path / "s")), tmpl_r)
        assert ep == 1
        for b in jax.device_get(st_s.round_opt):
            for m in ("mu", "nu"):
                s = np.asarray(jax.device_get(st_s.round_opt)[b][m])
                r = np.asarray(jax.device_get(got_r.round_opt)[b][m])
                assert np.array_equal(
                    r, np.broadcast_to(r[:1], r.shape)), (b, m)
                assert np.array_equal(s.reshape(-1), r[0]), (b, m)
        # replicated save -> sharded restore, closing the loop bitwise
        ckpt_lib.save_checkpoint(str(tmp_path / "r"), got_r, 2)
        got_s, _ = ckpt_lib.restore_checkpoint(
            ckpt_lib.latest_checkpoint(str(tmp_path / "r")), st_s)
        for b in jax.device_get(st_s.round_opt):
            for m in ("mu", "nu"):
                assert np.array_equal(
                    np.asarray(jax.device_get(st_s.round_opt)[b][m]),
                    np.asarray(jax.device_get(got_s.round_opt)[b][m]))

    def test_pre_tracker_checkpoint_restores_zero_moments(self, mesh8,
                                                          tmp_path):
        _eng, st = self._state_with_tracker(mesh8, "sharded")
        legacy = st.replace(round_opt=None)   # a pre-ISSUE-9 layout
        ckpt_lib.save_checkpoint(str(tmp_path / "l"), legacy, 3)
        got, ep = ckpt_lib.restore_checkpoint(
            ckpt_lib.latest_checkpoint(str(tmp_path / "l")), st)
        assert ep == 3
        for leaf in jax.tree_util.tree_leaves(got.round_opt):
            assert np.all(np.asarray(leaf) == 0)


class TestElasticReshardRoundOpt:
    def _host_state(self, placement, n=4):
        pw = per_worker_shapes()
        rng = np.random.default_rng(3)
        params = {k: rng.normal(size=(n, *s)).astype(np.float32)
                  for k, s in SHAPES.items()}
        trk = jax.device_get(comms.round_opt_init(
            pw, n, placement=placement, bucket_bytes=TINY_BUCKET))
        plan = comms.bucket_plan(list(pw.values()), n, TINY_BUCKET)
        for i, b in enumerate(plan):
            filled = sum(s for (_i, _o, s) in b.items)
            vec = np.zeros(b.padded, np.float32)
            vec[:filled] = rng.normal(size=filled)
            for m in ("mu", "nu"):
                trk[comms._bucket_name(i)][m] = (
                    vec.reshape(n, -1) if placement == "sharded"
                    else np.broadcast_to(vec, (n, b.padded)).copy())
        return TrainState(
            params=params, batch_stats={},
            opt_state={"mu": jax.tree_util.tree_map(np.zeros_like,
                                                    params)},
            lr_epoch=np.zeros((n,), np.int32),
            rng=np.zeros((n, 2), np.uint32),
            round_opt=trk)

    @pytest.mark.parametrize("placement", ["replicated", "sharded"])
    def test_kill_join_retiles_the_tracker(self, placement):
        host = self._host_state(placement)
        out = elastic_lib.reshard_state(
            host, kept_positions=[0, 2, 3], joiner_ids=[4], seed=0,
            round_opt_placement=placement, sync_bucket_bytes=TINY_BUCKET)
        # per-worker rows re-tiled for the SAME worker count: vectors
        # must be preserved exactly (kill+join is a swap, n unchanged)
        for b in host.round_opt:
            for m in ("mu", "nu"):
                a, c = host.round_opt[b][m], out.round_opt[b][m]
                if placement == "sharded":
                    assert np.array_equal(np.asarray(a).reshape(-1),
                                          np.asarray(c).reshape(-1))
                else:
                    assert np.array_equal(np.asarray(a)[0],
                                          np.asarray(c)[0])
        # survivors' per-worker state row-edited as before
        np.testing.assert_array_equal(
            out.params["a"][:3], host.params["a"][[0, 2, 3]])

    def test_shrink_then_grow_roundtrips(self):
        host = self._host_state("sharded", n=4)
        down = elastic_lib.reshard_state(
            host, kept_positions=[0, 1, 2], joiner_ids=[], seed=0,
            round_opt_placement="sharded", sync_bucket_bytes=TINY_BUCKET)
        back = elastic_lib.reshard_state(
            down, kept_positions=[0, 1, 2], joiner_ids=[5], seed=0,
            round_opt_placement="sharded", sync_bucket_bytes=TINY_BUCKET)
        for b in host.round_opt:
            for m in ("mu", "nu"):
                assert np.array_equal(
                    np.asarray(host.round_opt[b][m]).reshape(-1),
                    np.asarray(back.round_opt[b][m]).reshape(-1)), (b, m)

    def test_missing_layout_kwargs_raise(self):
        host = self._host_state("sharded")
        with pytest.raises(ValueError, match="round_opt_placement"):
            elastic_lib.reshard_state(host, kept_positions=[0, 1],
                                      joiner_ids=[], seed=0)


# ----------------------------------------------------------------------
# Driver e2e composition (slow: each case is two full train_global runs)
# ----------------------------------------------------------------------

def _e2e_cfg(**kw):
    base = dict(model="mlp", dataset="mnist", epochs_global=5,
                epochs_local=1, batch_size=16, limit_train_samples=400,
                limit_eval_samples=100, compute_dtype="float32",
                augment=False, seed=1, num_workers=4,
                sync_mode="sharded", sync_bucket_mb=0.001)
    base.update(kw)
    return Config(**base)


PROBE4 = np.array([1.0, 1.5, 1.0, 2.0])

TAIL_KEYS = ("global_train_losses", "global_val_losses",
             "global_train_accuracies", "global_val_accuracies",
             "step_caps", "shard_sizes")


@pytest.mark.slow
class TestElasticCompose:
    """ISSUE 9 satellite: kill+join THROUGH a sharded-optimizer run keeps
    the PR 8 bitwise-trajectory gate, sanitized."""

    def test_weights_sharded_placement_keeps_the_bitwise_gate(self):
        kw = dict(chaos="kill@2:w1,join@2", sanitize=True,
                  opt_placement="sharded", aggregation_by="weights")
        walls = lambda e: np.ones(4)
        full = train_global(_e2e_cfg(**kw), progress=False,
                            simulated_durations=PROBE4,
                            simulated_round_durations=walls)
        assert full["sync_engine"]["mode"] == "sharded"
        assert full["sync_engine"]["opt_placement"] == "sharded"
        assert len(full["elastic"]["events"]) == 2
        assert full["sanitize"]["retrace_count"] == 0
        snap = full["elastic"]["snapshots"][0]
        fresh = train_global(_e2e_cfg(**kw), progress=False,
                             simulated_durations=PROBE4,
                             simulated_round_durations=walls,
                             elastic_snapshot=snap)
        for k in TAIL_KEYS:
            assert full[k][2:] == fresh[k], f"results[{k!r}] diverged"

    def test_gradients_tracker_survives_kill_join_bitwise(self):
        kw = dict(chaos="kill@2:w1,join@2", sanitize=True,
                  opt_placement="sharded", aggregation_by="gradients")
        walls = lambda e: np.ones(4)
        full = train_global(_e2e_cfg(**kw), progress=False,
                            simulated_durations=PROBE4,
                            simulated_round_durations=walls)
        assert full["sanitize"]["retrace_count"] == 0
        assert full["state"].round_opt is not None
        snap = full["elastic"]["snapshots"][0]
        # the snapshot carries the re-tiled tracker for the new roster
        assert snap.host_state.round_opt is not None
        fresh = train_global(_e2e_cfg(**kw), progress=False,
                             simulated_durations=PROBE4,
                             simulated_round_durations=walls,
                             elastic_snapshot=snap)
        for k in TAIL_KEYS:
            assert full[k][2:] == fresh[k], f"results[{k!r}] diverged"
        # and the final tracker state itself is bitwise across the pair
        for a, b in zip(
                jax.tree_util.tree_leaves(
                    jax.device_get(full["state"].round_opt)),
                jax.tree_util.tree_leaves(
                    jax.device_get(fresh["state"].round_opt))):
            assert np.array_equal(np.asarray(a), np.asarray(b))
