"""Grouped-query attention: K/V stay at the grouped head count end to end.

GQA's point is K/V bandwidth (and ring-traffic) savings, so ``attend`` and
every impl behind it consume [B, L, KV, D] K/V directly — these tests pin
each impl's grouped path to the reference semantics (repeat K/V to the full
head count, run MHA):

- dense grouped einsum == repeat-then-MHA (forward + grads, causal too);
- Pallas flash kernels (forward + blockwise backward) == grouped dense;
- ring attention (rep-x smaller rotating blocks) == grouped dense;
- Ulysses == grouped dense when kv_heads divide the seq axis, loud error
  otherwise.

No reference equivalent exists (the reference has no attention at all,
SURVEY.md section 2.3); GQA is part of the Llama family (models/llama.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from learning_deep_neural_network_in_distributed_computing_environment_tpu.ops.attention import (
    attend,
    dot_product_attention,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.parallel.sp import (
    ring_attention,
    ulysses_attention,
)

H, KV, D = 4, 2, 16
REP = H // KV


def _qkv(b=2, l=64, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, l, H, D)), dtype)
    k = jnp.asarray(rng.normal(size=(b, l, KV, D)), dtype)
    v = jnp.asarray(rng.normal(size=(b, l, KV, D)), dtype)
    return q, k, v


def _expanded(q, k, v):
    """The semantics GQA must reproduce: repeat K/V to full heads, run MHA."""
    return q, jnp.repeat(k, REP, axis=2), jnp.repeat(v, REP, axis=2)


class TestDenseGrouped:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_expanded(self, causal):
        q, k, v = _qkv()
        out = dot_product_attention(q, k, v, causal=causal)
        ref = dot_product_attention(*_expanded(q, k, v), causal=causal)
        np.testing.assert_allclose(out, ref, atol=1e-6)

    def test_grads_match_expanded(self):
        q, k, v = _qkv(seed=1)
        g = jax.grad(lambda *a: (dot_product_attention(*a) ** 2).sum(),
                     argnums=(0, 1, 2))(q, k, v)
        gref = jax.grad(
            lambda q, k, v: (dot_product_attention(
                *_expanded(q, k, v)) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gref):
            np.testing.assert_allclose(a, b, atol=1e-5)

    def test_indivisible_heads_rejected(self):
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.normal(size=(1, 8, 4, D)), jnp.float32)
        k = v = jnp.asarray(rng.normal(size=(1, 8, 3, D)), jnp.float32)
        with pytest.raises(ValueError, match="not divisible"):
            dot_product_attention(q, k, v)


@pytest.mark.slow
class TestFlashGrouped:
    """The Pallas kernels (interpret mode on CPU) with grouped K/V block
    specs and the group-folded dK/dV grid."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_dense(self, causal):
        q, k, v = _qkv(l=256)
        out = attend(q, k, v, impl="flash", causal=causal)
        ref = dot_product_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_dense(self, causal):
        q, k, v = _qkv(l=256, seed=2)
        loss = lambda impl: lambda q, k, v: (
            attend(q, k, v, impl=impl, causal=causal) ** 2).sum()
        g = jax.grad(loss("flash"), argnums=(0, 1, 2))(q, k, v)
        gref = jax.grad(loss("dense"), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gref):
            np.testing.assert_allclose(a, b, atol=5e-4)


@pytest.fixture(scope="module")
def seq_mesh(devices):
    return Mesh(np.array(devices[:2]), ("seq",))


def _sharded(seq_mesh, fn):
    return jax.jit(jax.shard_map(
        fn, mesh=seq_mesh,
        in_specs=(P(None, "seq"),) * 3, out_specs=P(None, "seq")))


@pytest.mark.slow
class TestRingGrouped:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_dense(self, seq_mesh, causal):
        q, k, v = _qkv()
        out = _sharded(seq_mesh, lambda q, k, v: ring_attention(
            q, k, v, "seq", causal=causal))(q, k, v)
        ref = dot_product_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_grads_match_dense(self, seq_mesh):
        q, k, v = _qkv(seed=3)
        ring = _sharded(seq_mesh,
                        lambda q, k, v: ring_attention(q, k, v, "seq"))
        g = jax.grad(lambda *a: (ring(*a) ** 2).sum(),
                     argnums=(0, 1, 2))(q, k, v)
        gref = jax.grad(lambda *a: (dot_product_attention(*a) ** 2).sum(),
                        argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gref):
            np.testing.assert_allclose(a, b, atol=1e-4)


@pytest.mark.slow
class TestUlyssesGrouped:
    def test_forward_matches_dense(self, seq_mesh):
        # seq axis 2 divides both H=4 and KV=2
        q, k, v = _qkv()
        out = _sharded(seq_mesh, lambda q, k, v: ulysses_attention(
            q, k, v, "seq"))(q, k, v)
        ref = dot_product_attention(q, k, v)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_kv_not_divisible_rejected(self, devices):
        mesh = Mesh(np.array(devices[:4]), ("seq",))
        q, k, v = _qkv()   # KV=2 not divisible by seq=4
        f = jax.jit(jax.shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, "seq"), mesh=mesh,
            in_specs=(P(None, "seq"),) * 3, out_specs=P(None, "seq")))
        with pytest.raises(ValueError, match="kv heads"):
            f(q, k, v)
