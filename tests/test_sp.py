"""Sequence/context parallelism: ring + all-to-all (Ulysses) attention.

Correctness is asserted against dense attention on a 4-device ``seq`` mesh
(forward AND gradients), and end-to-end through the driver on a
(data=2, seq=4) mesh against the dense run with identical seed/config —
the long-context capability required of the framework (no reference
equivalent exists; SURVEY.md section 5 'Long-context').
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from learning_deep_neural_network_in_distributed_computing_environment_tpu.ops.attention import (
    dot_product_attention,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.parallel.sp import (
    ring_attention,
    ulysses_attention,
)


@pytest.fixture(scope="module")
def seq_mesh(devices):
    return Mesh(np.array(devices[:4]), ("seq",))


def _qkv(b=2, l=64, h=4, d=16, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
                 for _ in range(3))


def _sharded(seq_mesh, fn):
    return jax.jit(jax.shard_map(
        fn, mesh=seq_mesh,
        in_specs=(P(None, "seq"),) * 3, out_specs=P(None, "seq")))


class TestRingAttention:
    def test_forward_matches_dense(self, seq_mesh):
        q, k, v = _qkv()
        out = _sharded(seq_mesh, lambda q, k, v: ring_attention(q, k, v, "seq"))(q, k, v)
        ref = dot_product_attention(q, k, v)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    @pytest.mark.slow
    def test_grads_match_dense(self, seq_mesh):
        q, k, v = _qkv(seed=1)
        ring = _sharded(seq_mesh, lambda q, k, v: ring_attention(q, k, v, "seq"))
        g = jax.grad(lambda *a: (ring(*a) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
        gref = jax.grad(lambda *a: (dot_product_attention(*a) ** 2).sum(),
                        argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gref):
            np.testing.assert_allclose(a, b, atol=1e-4)


@pytest.mark.slow
class TestZigzagRing:
    """Zig-zag causal ring: device i holds half-chunks (i, 2n-1-i) so
    every rotation has exactly 2 live sub-blocks per device and the dead
    ones are cond-skipped — exactness vs dense causal attention."""

    def _zig(self, mesh, n):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.parallel.sp import (
            ring_attention_zigzag,
        )
        return jax.jit(jax.shard_map(
            lambda q, k, v: ring_attention_zigzag(q, k, v, "seq"),
            mesh=mesh, in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq")))

    @pytest.mark.parametrize("n", [2, 4])
    def test_forward_matches_dense(self, devices, n):
        mesh = Mesh(np.array(devices[:n]), ("seq",))
        q, k, v = _qkv(l=16 * n)
        out = self._zig(mesh, n)(q, k, v)
        ref = dot_product_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    @pytest.mark.slow
    def test_grads_match_dense(self, devices):
        mesh = Mesh(np.array(devices[:4]), ("seq",))
        q, k, v = _qkv(l=64, seed=5)
        zig = self._zig(mesh, 4)
        g = jax.grad(lambda *a: (zig(*a) ** 2).sum(),
                     argnums=(0, 1, 2))(q, k, v)
        gref = jax.grad(
            lambda *a: (dot_product_attention(*a, causal=True) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gref):
            np.testing.assert_allclose(a, b, atol=1e-4)

    def test_grouped_kv_matches_dense(self, devices):
        mesh = Mesh(np.array(devices[:4]), ("seq",))
        rng = np.random.default_rng(6)
        q = jnp.asarray(rng.normal(size=(2, 64, 4, 16)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(2, 64, 2, 16)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(2, 64, 2, 16)), jnp.float32)
        out = self._zig(mesh, 4)(q, k, v)
        ref = dot_product_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_odd_chunk_rejected(self, devices):
        mesh = Mesh(np.array(devices[:2]), ("seq",))
        q, k, v = _qkv(l=6)  # chunk 3 per device: odd
        with pytest.raises(ValueError, match="even"):
            self._zig(mesh, 2)(q, k, v)

    def test_driver_matches_dense_run(self, devices):
        kw = dict(model="gpt_tiny", dataset="synthetic_lm", seed=13)
        dense = _composition_run(devices[:2], {"data": 2}, **kw)
        zig = _composition_run(devices[:8], {"data": 2, "seq": 4},
                               sequence_parallel="ring_zigzag", **kw)
        np.testing.assert_allclose(zig["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)


class TestUlyssesAttention:
    def test_forward_matches_dense(self, seq_mesh):
        q, k, v = _qkv(seed=2)
        out = _sharded(seq_mesh, lambda q, k, v: ulysses_attention(q, k, v, "seq"))(q, k, v)
        ref = dot_product_attention(q, k, v)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    @pytest.mark.slow
    def test_grads_match_dense(self, seq_mesh):
        q, k, v = _qkv(seed=3)
        uly = _sharded(seq_mesh, lambda q, k, v: ulysses_attention(q, k, v, "seq"))
        g = jax.grad(lambda *a: (uly(*a) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
        gref = jax.grad(lambda *a: (dot_product_attention(*a) ** 2).sum(),
                        argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gref):
            np.testing.assert_allclose(a, b, atol=1e-4)


@pytest.mark.slow
class TestDriverSequenceParallel:
    """BERT training seq-sharded over a (data=2, seq=4) mesh must match the
    dense data=2 run: same shards, same rng, numerics within fp32 tolerance."""

    def _run(self, devices, sp_mode, mesh_axes):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        mesh = build_mesh(mesh_axes, devices)
        cfg = Config(model="bert_tiny", dataset="synthetic_mlm",
                     epochs_global=2, epochs_local=1, batch_size=8,
                     limit_train_samples=128, limit_eval_samples=32,
                     compute_dtype="float32", augment=False,
                     aggregation_by="weights", seed=7,
                     sequence_parallel=sp_mode)
        return train_global(cfg, mesh=mesh, progress=False)

    @pytest.mark.parametrize("sp_mode", ["ring", "all_to_all"])
    def test_matches_dense_run(self, devices, sp_mode):
        dense = self._run(devices[:2], "none", {"data": 2})
        sp = self._run(devices, sp_mode, {"data": 2, "seq": 4})
        np.testing.assert_allclose(sp["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)
        assert sp["global_train_losses"][-1] < sp["global_train_losses"][0]

    def test_requires_seq_axis(self, devices):
        with pytest.raises(ValueError, match="seq"):
            self._run(devices, "ring", {"data": 8})


def _composition_run(devices, mesh_axes, model="bert_tiny",
                     dataset="synthetic_mlm", seed=7, **extra):
    """Shared driver harness for the composition classes below."""
    from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
    from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
    from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
    cfg = Config(model=model, dataset=dataset, epochs_global=2,
                 epochs_local=1, batch_size=8, limit_train_samples=128,
                 limit_eval_samples=32, compute_dtype="float32",
                 augment=False, aggregation_by="weights", seed=seed, **extra)
    return train_global(cfg, mesh=build_mesh(mesh_axes, devices),
                        progress=False)


@pytest.mark.slow
class TestSeqTensorComposition:
    """SP x TP: ring attention over 'seq' with Megatron head/FFN shards
    over 'model' in the same step (heads are local to each model shard;
    the k/v ring rotation and the TP psums ride different axes)."""

    def test_matches_dense_run(self, devices):
        dense = _composition_run(devices[:2], {"data": 2})
        both = _composition_run(devices[:8],
                                {"data": 2, "seq": 2, "model": 2},
                                sequence_parallel="ring")
        np.testing.assert_allclose(both["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)

    def test_llama_causal_matches_dense(self, devices):
        kw = dict(model="llama_tiny", dataset="synthetic_lm", seed=8)
        dense = _composition_run(devices[:2], {"data": 2}, **kw)
        both = _composition_run(devices[:8],
                                {"data": 2, "seq": 2, "model": 2},
                                sequence_parallel="ring", **kw)
        np.testing.assert_allclose(both["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)


@pytest.mark.slow
class TestSeqFsdpComposition:
    """SP x FSDP: L over 'seq', B over 'fsdp' in the same step — the loss
    denominator and metric sums psum over BOTH partial-batch axes, grads
    psum over seq then reduce-scatter over fsdp."""

    def test_matches_dense_run(self, devices):
        dense = _composition_run(devices[:2], {"data": 2}, seed=9)
        both = _composition_run(devices[:8],
                                {"data": 2, "fsdp": 2, "seq": 2},
                                sequence_parallel="ring", seed=9)
        np.testing.assert_allclose(both["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)
        specs = [str(l.sharding.spec) for l in
                 jax.tree_util.tree_leaves(both["state"].params)]
        assert any("fsdp" in s for s in specs)


@pytest.mark.slow
class TestSeqPipelineComposition:
    """SP x PP: ring attention over 'seq' INSIDE each GPipe stage while
    activations rotate over 'pipe' between stages.  Runs with the
    sequential CPU thunk scheduler (conftest XLA flag): the
    concurrency-optimized executor can enter the seq-pair psums and the
    pipe ppermutes in different per-device orders and deadlock the
    collective rendezvous — the flag, not the program, was the round-3
    blocker."""

    @pytest.mark.parametrize("sp_mode", ["ring", "all_to_all"])
    def test_matches_dense_run(self, devices, sp_mode):
        kw = dict(model="gpt_tiny", dataset="synthetic_lm", seed=11)
        dense = _composition_run(devices[:2], {"data": 2}, **kw)
        both = _composition_run(devices[:8],
                                {"data": 2, "pipe": 2, "seq": 2},
                                sequence_parallel=sp_mode, **kw)
        np.testing.assert_allclose(both["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)
        assert both["global_train_losses"][-1] < both["global_train_losses"][0]
