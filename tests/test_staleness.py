"""Semi-synchronous rounds (ISSUE 16 tentpole).

Covers the staleness contract end to end: the delivery-blend helpers
against a numpy reference; K=0 structurally identical to the synchronous
engine (no staleness program is even built — the bitwise gate is the
absence of the code path, not delivery-time arithmetic); K=1 BITWISE
equal to its serial delayed-blend reference (same programs, same
delivery schedule, zero overlap — JAX_GRAFT_STALENESS_SERIAL) across
all three topologies incl. the EF-compressed wire; the delivery
schedule and end-of-run drain (round R's delta lands at the entry of
round R+K+1, everything pending folds at exit); per-round
``sync_hidden_ms`` telemetry + the ``results["async_rounds"]`` summary;
the sim lab's ``--sim_staleness`` convergence twin; and every eagerly
rejected K>0 combo failing fast in Config with its real reason.

Tier-1 keeps one e2e gate per axis (the allreduce K=1 bitwise gate, the
schedule/drain accounting, the sim twin's schema); the full topology x
EF x sanitized sweeps ride the slow marker.
"""

import os

import jax
import numpy as np
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu import (
    comms,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global

KW = dict(model="mlp", dataset="mnist", epochs_global=3, epochs_local=1,
          batch_size=16, limit_train_samples=256, limit_eval_samples=64,
          compute_dtype="float32", augment=False,
          aggregation_by="weights", proportionality="uniform", seed=0)


def run(mesh, k=0, serial=False, rounds=3, **extra):
    """One driver run; ``serial=True`` arms the scheduling-only serial
    reference (same programs, same delayed-delivery schedule, the sync
    wall fully exposed at dispatch)."""
    if serial:
        os.environ["JAX_GRAFT_STALENESS_SERIAL"] = "1"
    try:
        return train_global(
            Config(**{**KW, "epochs_global": rounds, **extra},
                   sync_staleness=k),
            mesh=mesh, progress=False)
    finally:
        os.environ.pop("JAX_GRAFT_STALENESS_SERIAL", None)


_CACHE: dict = {}


def run_cached(mesh, tag="", **kw):
    """Memoized ``run`` — tier-1 cases share trajectories (the mesh is
    the session-scoped mesh8, so the config tuple is the full key);
    ``tag`` forces a distinct run of an identical config (determinism
    checks need two real executions)."""
    key = (tag,) + tuple(sorted(kw.items()))
    if key not in _CACHE:
        _CACHE[key] = run(mesh, **kw)
    return _CACHE[key]


def params_bitwise(a, b):
    la = jax.tree_util.tree_leaves(a["state"].params)
    lb = jax.tree_util.tree_leaves(b["state"].params)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


def trajectories_bitwise(a, b):
    return (a["global_train_losses"] == b["global_train_losses"]
            and a["global_val_accuracies"] == b["global_val_accuracies"]
            and params_bitwise(a, b))


# --------------------------------------------------------------------
# The delivery blend (comms unit, numpy reference)
# --------------------------------------------------------------------
class TestDeliveryBlend:
    def tree(self, seed, n=2):
        rng = np.random.default_rng(seed)
        return {"w": np.asarray(rng.normal(size=(n, 5, 3)), np.float32),
                "b": np.asarray(rng.normal(size=(n, 7)), np.float32)}

    def test_delta_is_blend_minus_base_exact(self):
        base, blend = self.tree(0), self.tree(1)
        d = comms.stale_delta(blend, base)
        for k in base:
            assert np.array_equal(np.asarray(d[k]), blend[k] - base[k])

    def test_deliver_folds_delta_additively(self):
        later, delta = self.tree(2), self.tree(3)
        out = comms.deliver_stale(later, delta)
        for k in later:
            assert np.array_equal(np.asarray(out[k]), later[k] + delta[k])

    def test_two_worker_equal_allreduce_delayed_schedule(self):
        # the K=1 schedule as plain numpy: each round trains (here: a
        # fixed per-worker increment), syncs to the 2-worker mean as a
        # DELTA, and folds round R's delta into round R+2's entry
        # params — the helpers driven through the same schedule must
        # agree bitwise with the hand-rolled arithmetic
        rng = np.random.default_rng(7)
        p0 = np.asarray(rng.normal(size=(2, 4)), np.float32)
        steps = [np.asarray(rng.normal(size=(2, 4)), np.float32)
                 for _ in range(3)]

        def schedule(delta_fn, deliver_fn):
            p, pending = p0.copy(), []
            for s in steps:
                if len(pending) > 1:
                    p = deliver_fn(p, pending.pop(0))
                t = p + s                              # the local phase
                blend = np.broadcast_to(
                    (t[0] + t[1]) / 2.0, t.shape)      # equal FedAvg
                pending.append(delta_fn(blend, t))
                p = t
            while pending:                             # the drain
                p = deliver_fn(p, pending.pop(0))
            return p

        ref = schedule(lambda b, t: b - t, lambda p, d: p + d)
        got = schedule(
            lambda b, t: np.asarray(comms.stale_delta(b, t)),
            lambda p, d: np.asarray(comms.deliver_stale(p, d)))
        assert np.array_equal(ref, got)


# --------------------------------------------------------------------
# K=0: the staleness machinery is structurally absent
# --------------------------------------------------------------------
class TestK0Structural:
    def test_k0_builds_no_staleness_programs(self, mesh8):
        res = run_cached(mesh8, tag="a", k=0)
        names = set(res["memory"]["programs"])
        assert not any(n.startswith(("deliver", "stale_sync"))
                       for n in names), names
        assert res["async_rounds"] == {"enabled": False}
        for t in res["round_timings"]:
            assert t["sync_hidden_ms"] == 0.0

    def test_k0_run_to_run_bitwise(self, mesh8):
        a = run_cached(mesh8, tag="a", k=0)
        b = run_cached(mesh8, tag="b", k=0)
        assert trajectories_bitwise(a, b)


# --------------------------------------------------------------------
# K=1: bitwise equal to the serial delayed-blend reference
# --------------------------------------------------------------------
class TestK1BitwiseGate:
    def test_allreduce_overlap_eq_serial(self, mesh8):
        ovl = run_cached(mesh8, k=1)
        ser = run_cached(mesh8, k=1, serial=True)
        assert trajectories_bitwise(ovl, ser)
        # the serial arm exposes the whole wall by construction
        assert ser["async_rounds"]["sync_hidden_ms_total"] == 0.0

    @pytest.mark.slow
    @pytest.mark.parametrize("topo", ["ring", "double_ring"])
    def test_gossip_topologies_overlap_eq_serial(self, mesh8, topo):
        ovl = run(mesh8, k=1, topology=topo)
        ser = run(mesh8, k=1, serial=True, topology=topo)
        assert trajectories_bitwise(ovl, ser)

    @pytest.mark.slow
    def test_ef_compressed_wire_composes(self, mesh8):
        ef = dict(topology="ring", sync_compression="ef",
                  sync_dtype="bfloat16")
        ovl = run(mesh8, k=1, **ef)
        ser = run(mesh8, k=1, serial=True, **ef)
        assert trajectories_bitwise(ovl, ser)
        # the engine-side residual chain is restored into the state at
        # the drain — the EF contract survives staleness
        assert ovl["state"].sync_residual is not None

    @pytest.mark.slow
    def test_k2_overlap_eq_serial(self, mesh8):
        ovl = run(mesh8, k=2, rounds=4)
        ser = run(mesh8, k=2, rounds=4, serial=True)
        assert trajectories_bitwise(ovl, ser)


# --------------------------------------------------------------------
# Schedule, drain, and telemetry
# --------------------------------------------------------------------
class TestScheduleAndTelemetry:
    def test_every_round_syncs_and_drains(self, mesh8):
        res = run_cached(mesh8, k=1)
        ar = res["async_rounds"]
        assert ar["enabled"] is True and ar["staleness"] == 1
        # every round dispatched one sync; all were delivered (in-loop
        # fences + the end-of-run drain)
        assert ar["delivered"] == 3
        assert ar["sync_ms_total"] >= ar["sync_hidden_ms_total"] >= 0.0
        rows = res["round_timings"]
        assert all("sync_hidden_ms" in t for t in rows)
        # rows 0..K zero-fill (no delivery has landed yet); row K+1
        # carries round 0's delivered walls
        assert rows[0]["sync_hidden_ms"] == 0.0
        assert rows[1]["sync_hidden_ms"] == 0.0

    def test_k_beyond_run_length_pure_drain(self, mesh8):
        # K=5 over 2 rounds: no in-loop delivery ever comes due — the
        # drain must fold both pending deltas into the final state
        res = run_cached(mesh8, k=5, rounds=2)
        assert res["async_rounds"]["delivered"] == 2
        ser = run_cached(mesh8, k=5, rounds=2, serial=True)
        assert trajectories_bitwise(res, ser)

    def test_staleness_programs_tracked(self, mesh8):
        res = run_cached(mesh8, k=1)
        names = set(res["memory"]["programs"])
        assert any(n.startswith("stale_sync") for n in names), names
        assert any(n.startswith("deliver") for n in names), names

    @pytest.mark.slow
    def test_sanitized_k1_all_zero_row(self, mesh8):
        res = run(mesh8, k=1, sanitize=True)
        assert res["sanitize"] == {
            "enabled": True, "transfer_guard_violations": 0,
            "retrace_count": 0, "recompile_count": 0,
            "donation_failures": 0}
        assert trajectories_bitwise(res, run(mesh8, k=1))


# --------------------------------------------------------------------
# The sim lab twin (--sim_staleness)
# --------------------------------------------------------------------
class TestSimStaleness:
    SKW = dict(KW, sim_workers=16)

    def sim_run(self, k, rounds=3, tag="", cached=True, **extra):
        key = ("sim", tag, k, rounds) + tuple(sorted(extra.items()))
        if not cached:
            _CACHE.pop(key, None)
        if key not in _CACHE:
            _CACHE[key] = train_global(
                Config(**{**self.SKW, "epochs_global": rounds, **extra},
                       sim_staleness=k), progress=False)
        return _CACHE[key]

    def test_k0_builds_no_deliver_program(self):
        res = self.sim_run(0)
        assert not any(n.startswith("sim_deliver")
                       for n in res["memory"]["programs"])
        assert res["sim"]["staleness"] == 0

    def test_k1_schema_and_drain(self):
        res = self.sim_run(1)
        assert res["sim"]["staleness"] == 1
        assert any(n.startswith("sim_deliver")
                   for n in res["memory"]["programs"])
        # the fused sim sync has no wall to hide — zero-filled column
        for t in res["round_timings"]:
            assert t["sync_hidden_ms"] == 0.0
        # real-engine staleness stays off (its knob is rejected here)
        assert res["async_rounds"] == {"enabled": False}

    def test_staleness_changes_the_trajectory(self):
        k0 = self.sim_run(0)
        k1 = self.sim_run(1)
        # a one-round-stale consensus is a DIFFERENT algorithm: the
        # curves must diverge after the first delivery (round K+1)
        assert (k0["global_train_losses"][:1]
                == k1["global_train_losses"][:1])
        assert k0["global_train_losses"] != k1["global_train_losses"]

    def test_k_runs_deterministic(self):
        a = self.sim_run(2, tag="a")
        b = self.sim_run(2, tag="b")
        assert a["global_train_losses"] == b["global_train_losses"]
        assert params_bitwise(a, b)

    @pytest.mark.slow
    def test_convergence_curves_across_matrix(self):
        # the paper's 2x3 matrix x K in {0,1,2}: every cell produces a
        # finite curve of the full run length (the sim-lab numbers the
        # ROADMAP closure quotes come from bench --entry async)
        for mode in ("balanced", "disbalanced"):
            for topo in ("allreduce", "ring", "double_ring"):
                for k in (0, 1, 2):
                    res = self.sim_run(k, cached=False,
                                       data_mode=mode, topology=topo)
                    accs = res["global_val_accuracies"]
                    assert len(accs) == 3
                    assert all(np.isfinite(a) for a in accs)

    @pytest.mark.slow
    def test_sanitized_sim_k1_all_zero_row(self):
        res = self.sim_run(1, sanitize=True)
        assert res["sanitize"]["transfer_guard_violations"] == 0
        assert res["sanitize"]["retrace_count"] == 0
        assert res["sanitize"]["recompile_count"] == 0


# --------------------------------------------------------------------
# Eager config validation: every rejected K>0 combo, with its reason
# --------------------------------------------------------------------
class TestConfigRejections:
    def test_negative_staleness(self):
        with pytest.raises(ValueError, match="sync_staleness must be"):
            Config(sync_staleness=-1)
        with pytest.raises(ValueError, match="sim_staleness must be"):
            Config(sim_staleness=-1)

    def test_sim_staleness_needs_sim_workers(self):
        with pytest.raises(ValueError, match="needs --sim_workers"):
            Config(sim_staleness=1)

    def test_sim_staleness_needs_weights_mode(self):
        with pytest.raises(ValueError, match="no between-round consensus"):
            Config(sim_staleness=1, sim_workers=8,
                   aggregation_by="gradients")

    def test_sync_staleness_rejects_sim_workers(self):
        with pytest.raises(ValueError, match="use --sim_staleness"):
            Config(sync_staleness=1, aggregation_by="weights",
                   sim_workers=8)

    def test_sync_staleness_needs_weights_mode(self):
        with pytest.raises(ValueError, match="nothing to deliver late"):
            Config(sync_staleness=1, aggregation_by="gradients")

    def test_rejects_chaos(self):
        with pytest.raises(ValueError, match="NO consensus is\\s+in flight"):
            Config(sync_staleness=1, aggregation_by="weights",
                   chaos="random")

    def test_rejects_hierarchical(self):
        with pytest.raises(ValueError, match="cannot pipeline"):
            Config(sync_staleness=1, aggregation_by="weights",
                   num_slices=2, topology="ring")

    def test_rejects_resident_params(self):
        with pytest.raises(ValueError, match="entry gather DEPEND"):
            Config(sync_staleness=1, aggregation_by="weights",
                   param_residency="resident")

    def test_rejects_buddy_redundancy(self):
        with pytest.raises(ValueError, match="nothing is uniquely held"):
            Config(sync_staleness=1, aggregation_by="weights",
                   shard_redundancy="buddy")

    def test_rejects_streamed_rounds(self):
        with pytest.raises(ValueError, match="already\\s+overlaps"):
            Config(sync_staleness=1, aggregation_by="weights",
                   stream_chunk_steps=2)

    def test_rejects_checkpointing(self):
        with pytest.raises(ValueError, match="in-flight\\s+consensus"):
            Config(sync_staleness=1, aggregation_by="weights",
                   checkpoint_dir="/tmp/x")
        with pytest.raises(ValueError, match="in-flight\\s+consensus"):
            Config(sync_staleness=1, aggregation_by="weights",
                   checkpoint_dir="/tmp/x", resume=True)

    def test_auto_residency_resolves_replicated(self):
        cfg = Config(sync_staleness=1, aggregation_by="weights")
        assert cfg.resolve_param_residency("cpu") == "replicated"
        assert cfg.resolve_param_residency("tpu") == "replicated"
