"""Sharded reduce-scatter round sync (ISSUE 2 tentpole).

Covers the numerics contract end to end: the fp32 sharded path is
BIT-IDENTICAL to the dense all-reduce across worker counts; uneven-bucket
padding round-trips exactly; the bf16-compressed path drifts within bf16
rounding per sync and, with error feedback, tracks the fp32 path over many
rounds where the uncompensated path stalls; the engine wires the mode
selection, residual state, and per-round telemetry; and the bench A/B
reports bytes-on-the-wire with sharded at 2(N-1)/N of dense.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu import (
    comms,
    mesh as mesh_lib,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import LocalSGDEngine

N = 8

# uneven leaf sizes: none divisible by 8, so every bucket needs padding;
# TINY bucket target forces multiple buckets including a mid-tree boundary
SHAPES = {"a": (13, 7), "b": (257,), "c": (31, 5), "d": (3,)}
TINY_BUCKET = 1024  # bytes => 256 fp32 elements per bucket target


def stacked_tree(n=N, seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    return {k: jnp.asarray(rng.normal(size=(n, *s)) * scale, jnp.float32)
            for k, s in SHAPES.items()}


def sub_mesh(k):
    return mesh_lib.build_mesh({"data": k}, devices=jax.devices()[:k])


class TestBucketPlan:
    def leaves(self):
        return [np.zeros(s, np.float32) for s in ((13, 7), (257,), (31, 5))]

    def test_padding_multiple_of_n_and_order_preserved(self):
        plan = comms.bucket_plan(self.leaves(), n=8, bucket_bytes=TINY_BUCKET)
        seen = []
        for b in plan:
            assert b.padded % 8 == 0
            filled = 0
            for (i, off, size) in b.items:
                assert off == filled  # contiguous, flatten order
                filled += size
                seen.append(i)
            assert b.padded >= filled
        assert seen == [0, 1, 2]  # every leaf exactly once, in order

    def test_tiny_bucket_target_splits_into_multiple_buckets(self):
        plan = comms.bucket_plan(self.leaves(), n=8, bucket_bytes=TINY_BUCKET)
        assert len(plan) >= 2
        one = comms.bucket_plan(self.leaves(), n=8, bucket_bytes=1 << 30)
        assert len(one) == 1

    def test_wire_bytes_accounting(self):
        tree = {k: jax.ShapeDtypeStruct(s, jnp.float32)
                for k, s in SHAPES.items()}
        total = sum(int(np.prod(s)) for s in SHAPES.values())
        assert comms.sync_wire_bytes(tree, N, mode="dense") == total * 4
        sharded = comms.sync_wire_bytes(tree, N, mode="sharded",
                                        wire_dtype=jnp.float32)
        padded = sum(b.padded for b in comms.bucket_plan(
            list(tree.values()), N, comms.DEFAULT_BUCKET_BYTES))
        assert sharded == 2 * (N - 1) * (padded // N) * 4
        # acceptance: sharded moves ~2(N-1)/N of dense bytes per bucket
        assert sharded / (total * 4) == pytest.approx(2 * (N - 1) / N,
                                                      rel=0.02)
        compressed = comms.sync_wire_bytes(tree, N, mode="sharded",
                                           wire_dtype=jnp.bfloat16)
        assert compressed * 2 == sharded
        assert comms.sync_wire_bytes(tree, 1, mode="sharded") == 0


class TestShardedBitIdentity:
    @pytest.mark.parametrize("k", [2, 4, 8])
    @pytest.mark.parametrize("how", ["equal", "weighted"])
    def test_fp32_sharded_bitwise_equals_dense(self, k, how):
        mesh = sub_mesh(k)
        tree = stacked_tree(n=k)
        dense = comms.make_host_sync(mesh, mode="dense", how=how,
                                     local_weight=0.3)(tree)[0]
        sharded = comms.make_host_sync(mesh, mode="sharded", how=how,
                                       local_weight=0.3,
                                       bucket_bytes=TINY_BUCKET)(tree)[0]
        for key in SHAPES:
            assert np.array_equal(np.asarray(dense[key]),
                                  np.asarray(sharded[key])), key

    def test_uneven_bucket_padding_roundtrips_exactly(self, mesh8):
        # all workers hold IDENTICAL small-integer-valued floats: the
        # cross-worker sum is exact (integers < 2^20 in fp32) and /8 is a
        # power-of-two scale, so the mean equals the input BITWISE — any
        # difference could only come from the pack/pad/unpack plumbing
        rng = np.random.default_rng(3)
        tree = {k: jnp.broadcast_to(
                    jnp.asarray(rng.integers(-1000, 1000, s), jnp.float32),
                    (N, *s))
                for k, s in SHAPES.items()}
        out = comms.make_host_sync(mesh8, mode="sharded",
                                   bucket_bytes=TINY_BUCKET)(tree)[0]
        for key in SHAPES:
            assert np.array_equal(np.asarray(tree[key]),
                                  np.asarray(out[key])), key


class TestCompressed:
    def test_single_sync_drift_is_bf16_bounded(self, mesh8):
        tree = stacked_tree(scale=1.0)
        dense = comms.make_host_sync(mesh8, mode="dense")(tree)[0]
        res = jax.tree_util.tree_map(jnp.zeros_like, tree)
        comp, new_res = comms.make_host_sync(
            mesh8, mode="sharded", wire_dtype=jnp.bfloat16)(tree, res)
        err = max(float(np.abs(np.asarray(comp[k], np.float32)
                               - np.asarray(dense[k], np.float32)).max())
                  for k in SHAPES)
        # two bf16 roundings (contribution + gathered mean) on O(1) values
        assert err < 0.05
        # the residual carries the fp32 rounding error of the own
        # contribution — nonzero for generic values
        assert any(float(np.abs(np.asarray(l)).max()) > 0
                   for l in jax.tree_util.tree_leaves(new_res))

    def test_error_feedback_tracks_fp32_where_plain_bf16_stalls(self, mesh8):
        # stall regime by construction: params ~100 sit on a bf16 grid of
        # ~0.5, per-round per-worker updates of 0.02..0.08 are far below
        # the half-quantum, so bf16(p + g) == bf16(p) and the uncompensated
        # compressed sync freezes the parameters while the fp32 reference
        # drifts ~15 quanta over 150 rounds.  Error feedback accumulates
        # the dropped sub-quantum mass in the fp32 residual until it
        # crosses a grid point, so the EF path tracks the drift.
        rng = np.random.default_rng(0)
        shape = (N, 512)
        row = (rng.uniform(64, 128, shape[1])
               * rng.choice([-1.0, 1.0], shape[1]))
        base = jnp.asarray(np.broadcast_to(row, shape), jnp.float32)
        step = jnp.asarray(rng.uniform(0.02, 0.08, shape), jnp.float32)
        dense = comms.make_host_sync(mesh8, mode="dense")
        comp = comms.make_host_sync(mesh8, mode="sharded",
                                    wire_dtype=jnp.bfloat16)
        rounds = 150
        p_ref = p_ef = p_raw = {"w": base}
        r_ef = {"w": jnp.zeros(shape, jnp.float32)}
        add = jax.jit(lambda t: {"w": t["w"] + step})
        for _ in range(rounds):
            # block each round: pipelined 8-thread collectives can starve
            # the XLA:CPU rendezvous (test_comms gossip note)
            p_ref = jax.block_until_ready(dense(add(p_ref))[0])
            p_ef, r_ef = jax.block_until_ready(comp(add(p_ef), r_ef))
            p_raw = jax.block_until_ready(comp(add(p_raw))[0])
        move = float(np.abs(np.asarray(p_ref["w"]) - np.asarray(base)).mean())
        err_ef = float(np.abs(np.asarray(p_ef["w"])
                              - np.asarray(p_ref["w"])).mean())
        err_raw = float(np.abs(np.asarray(p_raw["w"])
                               - np.asarray(p_ref["w"])).mean())
        assert move > 5.0  # the reference drifted many bf16 quanta
        assert err_ef < 0.15 * move, (err_ef, move)
        assert err_raw > 3 * err_ef, (err_raw, err_ef)


def small_cfg(**kw):
    base = dict(model="mlp", dataset="mnist", epochs_local=2, epochs_global=2,
                batch_size=8, compute_dtype="float32", augment=False,
                aggregation_by="weights")
    base.update(kw)
    return Config(**base)


def make_engine(mesh8, cfg):
    model = get_model("mlp", num_classes=10, hidden=16)
    return LocalSGDEngine(model, mesh8, cfg)


def make_packs(n=8, steps=4, b=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, steps, b, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, (n, steps, b)).astype(np.int32)
    m = np.ones((n, steps, b), np.float32)
    return x, y, m


class TestEngineSync:
    def _round_params(self, mesh8, cfg):
        engine = make_engine(mesh8, cfg)
        x, y, m = make_packs()
        state = engine.init_state(jax.random.key(0), x[0, 0])
        state, mx = engine.round(state, (x, y, m), (x, y, m))
        return state, mx, engine

    def test_weights_round_bitwise_identical_across_modes(self, mesh8):
        s_dense, mx_d, _ = self._round_params(
            mesh8, small_cfg(sync_mode="dense"))
        s_shard, mx_s, eng = self._round_params(
            mesh8, small_cfg(sync_mode="sharded", sync_bucket_mb=0.001))
        assert eng.sync_mode == "sharded"
        for a, b in zip(jax.tree_util.tree_leaves(s_dense.params),
                        jax.tree_util.tree_leaves(s_shard.params)):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        assert np.array_equal(np.asarray(mx_d["train_loss"]),
                              np.asarray(mx_s["train_loss"]))

    def test_gradients_norm_bitwise_identical_across_modes(self, mesh8):
        _, mx_d, _ = self._round_params(
            mesh8, small_cfg(aggregation_by="gradients", sync_mode="dense"))
        _, mx_s, _ = self._round_params(
            mesh8, small_cfg(aggregation_by="gradients",
                             sync_mode="sharded", sync_bucket_mb=0.001))
        assert np.array_equal(np.asarray(mx_d["agg_grad_norm"]),
                              np.asarray(mx_s["agg_grad_norm"]))
        assert float(np.asarray(mx_s["agg_grad_norm"]).ravel()[0]) > 0

    def test_compressed_round_carries_residual_and_stays_close(self, mesh8):
        cfg = small_cfg(sync_mode="sharded", sync_dtype="bfloat16",
                        sync_compression="ef")
        engine = make_engine(mesh8, cfg)
        assert engine.sync_ef
        x, y, m = make_packs()
        state = engine.init_state(jax.random.key(0), x[0, 0])
        assert state.sync_residual is not None
        state, _ = engine.round(state, (x, y, m), (x, y, m))
        res_mag = max(float(np.abs(np.asarray(l)).max())
                      for l in jax.tree_util.tree_leaves(state.sync_residual))
        assert 0 < res_mag < 0.01  # bf16-rounding scale, not garbage
        # FedAvg with a compressed wire still leaves replicas identical
        for leaf in jax.tree_util.tree_leaves(state.params):
            arr = np.asarray(leaf)
            assert np.array_equal(arr, np.broadcast_to(arr[:1], arr.shape))

    def test_sharded_ring_resolves_to_gossip_engine(self, mesh8):
        # the sharded-is-allreduce-only rejection is lifted (ISSUE 4):
        # --sync_mode sharded names the bucketed fast path, which for
        # gossip topologies is the per-bucket ppermute engine
        eng = make_engine(mesh8, small_cfg(sync_mode="sharded",
                                           topology="ring"))
        assert eng.sync_mode == "gossip"

    def test_auto_resolves_dense_on_cpu_sharded_for_bf16(self, mesh8):
        assert make_engine(mesh8, small_cfg()).sync_mode == "dense"
        eng = make_engine(mesh8, small_cfg(sync_dtype="bfloat16",
                                           sync_compression="ef"))
        assert eng.sync_mode == "sharded"


class TestConfigValidation:
    def test_bf16_dense_rejected(self):
        with pytest.raises(ValueError, match="sync_mode dense"):
            Config(sync_mode="dense", sync_dtype="bfloat16")

    def test_ef_requires_bf16(self):
        with pytest.raises(ValueError, match="bfloat16"):
            Config(sync_compression="ef")

    def test_bf16_ring_rides_the_gossip_engine(self):
        # a compressed-ring request used to fail fast so the flags could
        # not be silently ignored; since ISSUE 4 the bucketed gossip
        # engine honors them — auto must resolve onto it even on CPU
        cfg = Config(sync_dtype="bfloat16", sync_compression="ef",
                     topology="ring")
        assert cfg.resolve_sync_mode("cpu") == "gossip"


class TestDriverTelemetry:
    def test_round_timings_carry_sync_bytes_and_mode(self, mesh8):
        res = train_global(
            Config(model="mlp", dataset="mnist", epochs_global=2,
                   epochs_local=1, batch_size=16, limit_train_samples=256,
                   limit_eval_samples=64, compute_dtype="float32",
                   augment=False, aggregation_by="weights",
                   sync_mode="sharded"),
            mesh=mesh8, progress=False)
        assert len(res["round_timings"]) == 2
        for t in res["round_timings"]:
            assert t["sync_mode"] == "sharded"
            assert t["sync_bytes"] > 0
            # ISSUE 16 schema: every row carries sync_hidden_ms, and a
            # synchronous run zero-fills it (same convention as sync_ms)
            assert t["sync_hidden_ms"] == 0.0
        # run-artifact engine provenance (ISSUE 9 satellite): sync mode,
        # resolved optimizer placement, and measured per-worker resident
        # bytes for every state component
        se = res["sync_engine"]
        assert se["mode"] == "sharded"
        assert se["opt_placement"] == "sharded"   # auto follows the engine
        # ISSUE 11: weights x equal under the sharded engine auto-resolves
        # the scatter-resident params layout, and the state-bytes split
        # records it — the resident shard is EXACTLY 1/N of the transient
        # gathered peak (the padded full buffers the round-entry gather
        # materializes in compute scope)
        assert se["param_residency"] == "resident"
        pw = se["per_worker_state_bytes"]
        assert pw["params"] > 0 and pw["opt_state"] > 0
        assert pw["params"] * 8 == pw["params_gathered_peak"]
        assert pw["ef_residual"] == 0 and pw["round_opt"] == 0
        assert res["compile_cache"]["enabled"] is False
        import os
        if not os.environ.get("JAX_GRAFT_TEST_COMPILE_CACHE"):
            # process-global counters: with the opt-in session cache
            # armed (conftest), this run's compiles legitimately fire
            # hit/miss events even though the CONFIG flag is off
            assert res["compile_cache"] == {"enabled": False, "hits": 0,
                                            "misses": 0}

    def test_streamed_rounds_measure_sync_wall(self, mesh8):
        res = train_global(
            Config(model="mlp", dataset="mnist", epochs_global=2,
                   epochs_local=1, batch_size=16, limit_train_samples=256,
                   limit_eval_samples=64, compute_dtype="float32",
                   augment=False, aggregation_by="weights",
                   sync_mode="sharded", stream_chunk_steps=2),
            mesh=mesh8, progress=False)
        for t in res["round_timings"]:
            assert t["sync_bytes"] > 0
            assert t["sync_ms"] >= 0.0  # the standalone sync program ran
            assert t["sync_hidden_ms"] == 0.0  # streamed rounds stay sync
        # the streamed path rides the resident layout too (enter program
        # + scatter-exit standalone sync); a replicated layout would
        # report a zero transient gather peak instead
        pw = res["sync_engine"]["per_worker_state_bytes"]
        assert res["sync_engine"]["param_residency"] == "resident"
        assert pw["params"] * 8 == pw["params_gathered_peak"]


class TestBenchEntry:
    def test_measure_sync_reports_bytes_wall_and_identity(self):
        import bench

        out = bench.measure_sync()
        assert out["n_workers"] == N
        assert out["bitwise_sharded_eq_dense"] is True
        assert out["sharded_vs_dense_bytes"] == pytest.approx(
            out["expected_bytes_ratio"], rel=0.02)
        for mode in ("dense", "sharded", "compressed"):
            assert out[mode]["ms"] > 0
            assert out[mode]["wire_mb"] > 0
        assert out["compressed"]["wire_mb"] == pytest.approx(
            out["sharded"]["wire_mb"] / 2, rel=0.01)
        assert out["compressed_max_abs_err"] < 0.05
        # optimizer-placement axis (ISSUE 9): per-worker opt-state bytes
        # at exactly 1/N of replicated, both placements bitwise
        pl = out["opt_placement"]
        assert pl["opt_state_bytes_ratio"] == pl["expected_opt_state_ratio"]
        assert pl["bitwise_sharded_eq_replicated"] is True
        assert pl["tracker_bitwise_consistent"] is True
        for row in ("replicated", "sharded"):
            assert pl[row]["ms"] > 0
            assert pl[row]["opt_state_mb_per_worker"] > 0


class TestInt8Compressed:
    """int8 + per-bucket-scale second compression tier (ISSUE 3
    satellite): symmetric round-to-nearest on a max|x|/127 grid, the
    sender's fp32 scale riding a tiny all-gather next to the payload."""

    def test_single_sync_error_is_scale_bounded(self, mesh8):
        tree = stacked_tree(scale=1.0)
        dense = comms.make_host_sync(mesh8, mode="dense")(tree)[0]
        res = jax.tree_util.tree_map(jnp.zeros_like, tree)
        out, new_res = comms.make_host_sync(
            mesh8, mode="sharded", wire_dtype=jnp.int8,
            bucket_bytes=TINY_BUCKET)(tree, res)
        # per-element error <= one int8 step of each phase: contribution
        # steps are ~max|x|/127 per worker (averaged over N) plus the
        # gathered mean's own step — O(1) values quantize to ~0.03 steps
        err = max(float(np.abs(np.asarray(out[k], np.float32)
                               - np.asarray(dense[k], np.float32)).max())
                  for k in SHAPES)
        assert err < 0.1
        assert any(float(np.abs(np.asarray(l)).max()) > 0
                   for l in jax.tree_util.tree_leaves(new_res))

    def test_weighted_int8_close_to_dense(self, mesh8):
        tree = stacked_tree(scale=1.0)
        dense = comms.make_host_sync(mesh8, mode="dense", how="weighted",
                                     local_weight=0.3)(tree)[0]
        res = jax.tree_util.tree_map(jnp.zeros_like, tree)
        out, _ = comms.make_host_sync(
            mesh8, mode="sharded", how="weighted", local_weight=0.3,
            wire_dtype=jnp.int8, bucket_bytes=TINY_BUCKET)(tree, res)
        err = max(float(np.abs(np.asarray(out[k], np.float32)
                               - np.asarray(dense[k], np.float32)).max())
                  for k in SHAPES)
        assert err < 0.1

    def test_error_feedback_time_average_converges(self, mesh8):
        # error feedback makes the QUANTIZATION ERROR zero-mean over
        # rounds: re-syncing the same tree repeatedly, the time-average
        # of the compressed output approaches the exact dense mean far
        # beyond single-shot precision (the residual re-injects every
        # dropped sub-quantum until it crosses a grid point)
        tree = stacked_tree(scale=1.0)
        dense = comms.make_host_sync(mesh8, mode="dense")(tree)[0]
        sync = comms.make_host_sync(mesh8, mode="sharded",
                                    wire_dtype=jnp.int8,
                                    bucket_bytes=TINY_BUCKET)
        res = jax.tree_util.tree_map(jnp.zeros_like, tree)
        acc = None
        rounds = 24
        single = None
        for _ in range(rounds):
            out, res = jax.block_until_ready(sync(tree, res))
            if single is None:
                single = out
            acc = out if acc is None else jax.tree_util.tree_map(
                lambda a, b: a + b, acc, out)
        err_one = max(float(np.abs(np.asarray(single[k], np.float32)
                                   - np.asarray(dense[k], np.float32)).max())
                      for k in SHAPES)
        err_avg = max(float(np.abs(np.asarray(acc[k]) / rounds
                                   - np.asarray(dense[k])).max())
                      for k in SHAPES)
        assert err_avg < 0.25 * err_one, (err_avg, err_one)

    def test_wire_bytes_quarter_of_fp32(self):
        tree = {k: jax.ShapeDtypeStruct(s, jnp.float32)
                for k, s in SHAPES.items()}
        b32 = comms.sync_wire_bytes(tree, N, mode="sharded",
                                    wire_dtype=jnp.float32)
        b8 = comms.sync_wire_bytes(tree, N, mode="sharded",
                                   wire_dtype=jnp.int8)
        assert b8 == b32 // 4

    def test_engine_int8_round_carries_residual(self, mesh8):
        cfg = small_cfg(sync_mode="sharded", sync_dtype="int8",
                        sync_compression="ef")
        engine = make_engine(mesh8, cfg)
        assert engine.sync_ef
        assert engine.sync_wire_dtype == jnp.int8
        x, y, m = make_packs()
        state = engine.init_state(jax.random.key(0), x[0, 0])
        state, _ = engine.round(state, (x, y, m), (x, y, m))
        # FedAvg with a quantized wire still leaves replicas identical
        for leaf in jax.tree_util.tree_leaves(state.params):
            arr = np.asarray(leaf)
            assert np.array_equal(arr, np.broadcast_to(arr[:1], arr.shape))

    def test_int8_auto_resolves_sharded(self, mesh8):
        eng = make_engine(mesh8, small_cfg(sync_dtype="int8",
                                           sync_compression="ef"))
        assert eng.sync_mode == "sharded"

    def test_int8_dense_rejected(self):
        with pytest.raises(ValueError, match="sync_mode dense"):
            Config(sync_mode="dense", sync_dtype="int8")


class TestShardedSyncInnerAxes:
    """The legacy check_rep verification that lifted the auto-mode dense
    fallback (ISSUE 3 satellite / ROADMAP open item): psum_scatter /
    all_to_all / all_gather over 'data' inside a mesh with inner TP/PP/EP
    axes are bit-identical to the dense twin under check_rep=True with
    the engine-style replication re-certification on the outputs."""

    def _run(self, mesh_axes, spec_sharded, how="equal", wire=None):
        from jax import lax
        from jax.sharding import PartitionSpec as P
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.compat import (
            shard_map,
        )
        mesh = mesh_lib.build_mesh(mesh_axes)
        n = mesh_axes["data"]
        rng = np.random.default_rng(0)
        tree = {"sharded": jnp.asarray(rng.normal(size=(n, 6, 8)),
                                       jnp.float32),
                "repl": jnp.asarray(rng.normal(size=(n, 33)), jnp.float32)}
        specs = {"sharded": spec_sharded, "repl": P("data")}
        inner = tuple(a for a in mesh_axes if a != "data")

        def cert(t):
            # the engine's _certify_replication for the repl leaf: an
            # identity pmean re-establishes the out-spec's replication
            # certificate legacy check_rep cannot infer
            return {"sharded": t["sharded"],
                    "repl": lax.pmean(t["repl"], inner)}

        def body(t):
            sq = jax.tree_util.tree_map(lambda a: a[0], t)
            out, _ = comms.sharded_sync(sq, how=how, local_weight=0.3,
                                        wire_dtype=wire,
                                        bucket_bytes=TINY_BUCKET)
            dense = comms.aggregate(sq, how=how, topology="allreduce",
                                    local_weight=0.3)
            ex = lambda tt: jax.tree_util.tree_map(lambda a: a[None], tt)
            return ex(cert(out)), ex(cert(dense))

        f = shard_map(body, mesh=mesh, in_specs=(specs,),
                      out_specs=(specs, specs), check_rep=True)
        out, dense = jax.jit(f)(tree)
        return out, dense

    @pytest.mark.parametrize("how", ["equal", "weighted"])
    @pytest.mark.parametrize("axes,spec", [
        ({"data": 4, "model": 2}, ("data", None, "model")),
        ({"data": 2, "pipe": 2, "model": 2}, ("data", "pipe", "model")),
        ({"data": 4, "expert": 2}, ("data", "expert")),
    ], ids=["tp", "pp_tp", "ep"])
    def test_fp32_bitwise_under_inner_axes(self, axes, spec, how):
        from jax.sharding import PartitionSpec as P
        out, dense = self._run(axes, P(*spec), how=how)
        for k in ("sharded", "repl"):
            assert np.array_equal(np.asarray(out[k]),
                                  np.asarray(dense[k])), k

    @pytest.mark.slow
    def test_engine_auto_mode_no_longer_gates_on_inner_axes(self):
        # the lifted gate: auto still resolves dense on the CPU backend,
        # but an EXPLICIT sharded engine on a TP mesh must produce the
        # bitwise-dense round (the configuration the gate used to block)
        mesh = mesh_lib.build_mesh({"data": 4, "model": 2})
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models.bert import (
            tp_param_specs,
        )
        outs = {}
        for mode in ("dense", "sharded"):
            cfg = Config(model="bert_tiny", dataset="synthetic_mlm",
                         batch_size=8, compute_dtype="float32",
                         augment=False, aggregation_by="weights",
                         epochs_local=1, sync_mode=mode,
                         sync_bucket_mb=0.001)
            model = get_model("bert_tiny", num_classes=30522,
                              scan_layers=True)
            tmodel = get_model("bert_tiny", num_classes=30522,
                               scan_layers=True, tp_size=2,
                               model_axis="model")
            eng = LocalSGDEngine(model, mesh, cfg, train_model=tmodel,
                                 param_specs_fn=tp_param_specs)
            rng = np.random.default_rng(0)
            x = rng.integers(0, 30522, (4, 2, 8, 16)).astype(np.int32)
            y = rng.integers(0, 30522, (4, 2, 8, 16)).astype(np.int32)
            m = np.ones((4, 2, 8), np.float32)
            state = eng.init_state(jax.random.key(0), x[0, 0])
            state, _ = eng.round(state, (x, y, m), (x, y, m))
            outs[mode] = jax.tree_util.tree_leaves(
                jax.device_get(state.params))
        for a, b in zip(outs["dense"], outs["sharded"]):
            assert np.array_equal(np.asarray(a), np.asarray(b))


class TestBuddyWireAccounting:
    """ISSUE 12 satellite: the buddy-redundancy hop's wire bytes ride
    ``sync_bytes`` — redundancy on must equal baseline + exactly one
    ppermute hop of the shard-resident rows in the wire dtype, per
    topology (gossip topologies keep every state worker-local, so
    redundancy is a no-op there and the accounting is unchanged)."""

    def _engine(self, topology, redundancy, **cfg_kw):
        cfg_kw.setdefault("aggregation_by", "weights")
        cfg = Config(model="mlp", batch_size=8, compute_dtype="float32",
                     augment=False, topology=topology,
                     sync_mode="sharded", shard_redundancy=redundancy,
                     **cfg_kw)
        eng = LocalSGDEngine(get_model("mlp", num_classes=10, hidden=8),
                             sub_mesh(4), cfg)
        state = eng.init_state(
            jax.random.key(0), np.zeros((8, 28, 28, 1), np.float32))
        eng._arm_sync_stats(state.params)
        return eng

    @pytest.mark.parametrize("topology", ["allreduce", "ring",
                                          "double_ring"])
    def test_redundancy_adds_exactly_one_hop(self, topology):
        on = self._engine(topology, "auto")
        off = self._engine(topology, "off")
        sb_on = on.last_sync_stats["sync_bytes"]
        sb_off = off.last_sync_stats["sync_bytes"]
        if topology == "allreduce":
            # weights x equal x sharded resolves resident -> buddy on
            assert on.buddy_on and not off.buddy_on
            expect = comms.buddy_wire_bytes(
                on.params_template, 4,
                bucket_bytes=on.sync_bucket_bytes)
            assert expect > 0
            assert sb_on == sb_off + expect, (sb_on, sb_off, expect)
        else:
            # gossip: nothing shard-resident, redundancy resolves off
            assert not on.buddy_on
            assert sb_on == sb_off

    def test_compressed_wire_hop_is_wire_dtype_sized(self):
        on = self._engine("allreduce", "auto", sync_dtype="bfloat16",
                          sync_compression="ef")
        off = self._engine("allreduce", "off", sync_dtype="bfloat16",
                           sync_compression="ef")
        # params row in bf16 (2 bytes) + the fp32 EF own-span (4 bytes)
        expect = comms.buddy_wire_bytes(
            on.params_template, 4, wire_dtype=jnp.bfloat16,
            bucket_bytes=on.sync_bucket_bytes, ef=True)
        assert on.last_sync_stats["sync_bytes"] == \
            off.last_sync_stats["sync_bytes"] + expect

    def test_tracker_hop_counts_two_fp32_rows(self):
        on = self._engine("allreduce", "auto",
                          aggregation_by="gradients")
        off = self._engine("allreduce", "off",
                           aggregation_by="gradients")
        assert on.round_opt_on and on.buddy_on
        expect = comms.buddy_wire_bytes(
            on.params_template, 4, params=False, tracker=True,
            bucket_bytes=on.sync_bucket_bytes)
        assert expect > 0
        assert on.last_sync_stats["sync_bytes"] == \
            off.last_sync_stats["sync_bytes"] + expect


class TestHierWireAccountingInEngine:
    """ISSUE 13 satellite: exact per-LEVEL byte accounting through the
    ENGINE's telemetry arming — outer (DCN) bytes are exactly
    ``hops x filled_bucket_row`` in the outer wire dtype (the gossip
    hop rides the 1/N_inner scatter shard, never the full tree), inner
    (ICI) bytes unchanged from the flat sharded engine at W workers.
    The comms-level exactness matrix lives in tests/test_hier_sync.py;
    flat engines report every byte as the ICI level with zero DCN."""

    def _engine(self, s, w, **cfg_kw):
        cfg_kw.setdefault("aggregation_by", "weights")
        cfg_kw.setdefault("topology", "ring" if s > 1 else "allreduce")
        cfg = Config(model="mlp", batch_size=8, compute_dtype="float32",
                     augment=False, num_slices=s, **cfg_kw)
        mesh = (mesh_lib.build_mesh({"slice": s, "data": w},
                                    devices=jax.devices()[:s * w])
                if s > 1 else sub_mesh(w))
        eng = LocalSGDEngine(get_model("mlp", num_classes=10, hidden=8),
                             mesh, cfg)
        state = eng.init_state(
            jax.random.key(0), np.zeros((8, 28, 28, 1), np.float32))
        eng._arm_sync_stats(state.params)
        return eng

    @pytest.mark.parametrize("topology,hops", [("ring", 1),
                                               ("double_ring", 2)])
    def test_dcn_bytes_exactly_hops_times_shard_row(self, topology, hops):
        eng = self._engine(2, 4, topology=topology)
        stats = eng.last_sync_stats
        plan = comms.bucket_plan(
            jax.tree_util.tree_leaves(eng.params_template), 4,
            eng.sync_bucket_bytes)
        expect_dcn = hops * sum((b.padded // 4) * 4 for b in plan)
        expect_ici = comms.sync_wire_bytes(
            eng.params_template, 4, mode="sharded",
            wire_dtype=jnp.float32, bucket_bytes=eng.sync_bucket_bytes)
        assert stats["sync_bytes_dcn"] == expect_dcn
        assert stats["sync_bytes_ici"] == expect_ici
        assert stats["sync_bytes"] == expect_ici + expect_dcn

    def test_compressed_outer_wire_quarters_dcn_only(self):
        fp = self._engine(2, 2, topology="ring")
        q = self._engine(2, 2, topology="ring", sync_dtype_outer="int8")
        assert q.last_sync_stats["sync_bytes_dcn"] * 4 == \
            fp.last_sync_stats["sync_bytes_dcn"]
        assert q.last_sync_stats["sync_bytes_ici"] == \
            fp.last_sync_stats["sync_bytes_ici"]

    def test_flat_engines_report_zero_dcn(self):
        for kw in (dict(sync_mode="sharded", topology="allreduce"),
                   dict(sync_mode="sharded", topology="ring"),
                   dict(sync_mode="dense", topology="allreduce")):
            eng = self._engine(1, 4, **kw)
            stats = eng.last_sync_stats
            assert stats["sync_bytes_dcn"] == 0
            assert stats["sync_bytes_ici"] == stats["sync_bytes"]
            assert stats["sync_ms_ici"] == 0.0
            assert stats["sync_ms_dcn"] == 0.0
