"""Runtime sanitizer (ISSUE 6): clean-run provenance, transfer-guard
violation counting, the retrace-budget counter, and env-var arming."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import (
    _round_guard,
    train_global,
)
from learning_deep_neural_network_in_distributed_computing_environment_tpu.xla_flags import (
    compile_event_counts,
    install_compile_counter,
)

CLEAN = {"enabled": True, "transfer_guard_violations": 0,
         "retrace_count": 0, "recompile_count": 0, "donation_failures": 0}


def cfg(**kw):
    base = dict(model="mlp", dataset="mnist", epochs_global=2,
                epochs_local=1, batch_size=16, limit_train_samples=512,
                limit_eval_samples=64, compute_dtype="float32",
                augment=False, aggregation_by="weights", seed=3)
    base.update(kw)
    return Config(**base)


class TestDriverSanitize:
    def test_clean_packed_run_records_zeros(self, mesh8):
        res = train_global(cfg(sanitize=True), mesh=mesh8, progress=False)
        assert res["sanitize"] == CLEAN
        # sanitize mode changes no numerics: same run unsanitized matches
        ref = train_global(cfg(), mesh=mesh8, progress=False)
        assert res["global_train_losses"] == ref["global_train_losses"]

    def test_clean_streamed_run_records_zeros(self, mesh8):
        # the streamed path is where this PR's three runtime hazards
        # lived (per-round jit rebuild, unsharded-zeros d2d reshard,
        # implicit scalar H2Ds) — keep it under the harness so a
        # regression of any of them trips the guard or retrace budget
        res = train_global(cfg(sanitize=True, stream_chunk_steps=4),
                           mesh=mesh8, progress=False)
        assert res["sanitize"] == CLEAN

    def test_unsanitized_run_records_disabled(self, mesh8):
        res = train_global(cfg(), mesh=mesh8, progress=False)
        assert res["sanitize"]["enabled"] is False
        assert res["sanitize"]["transfer_guard_violations"] == 0

    def test_env_var_arms_the_sanitizer(self, mesh8, monkeypatch):
        monkeypatch.setenv("JAX_GRAFT_SANITIZE", "1")
        res = train_global(cfg(epochs_global=1), mesh=mesh8,
                           progress=False)
        assert res["sanitize"]["enabled"] is True

    @pytest.mark.parametrize("value", ["0", "false"])
    def test_falsy_env_var_means_off(self, mesh8, monkeypatch, value):
        monkeypatch.setenv("JAX_GRAFT_SANITIZE", value)
        res = train_global(cfg(epochs_global=1), mesh=mesh8,
                           progress=False)
        assert res["sanitize"]["enabled"] is False


class TestRoundGuard:
    def test_implicit_transfer_counted_and_reraised(self):
        san = {"enabled": True, "transfer_guard_violations": 0}
        x = jnp.ones((4,))
        with pytest.raises(Exception, match="[Dd]isallow"):
            with _round_guard(san):
                _ = x + 1.0  # bare Python scalar: implicit H2D
        assert san["transfer_guard_violations"] == 1

    def test_explicit_staging_passes(self):
        san = {"enabled": True, "transfer_guard_violations": 0}
        with _round_guard(san):
            a = jax.device_put(np.ones(3, np.float32))
            _ = jax.device_get(a)
        assert san["transfer_guard_violations"] == 0

    def test_disabled_guard_is_a_no_op(self):
        san = {"enabled": False, "transfer_guard_violations": 0}
        x = jnp.ones((4,))
        with _round_guard(san):
            _ = x + 1.0  # allowed: guard off
        assert san["transfer_guard_violations"] == 0


class TestCompileCounter:
    def test_fresh_jit_counts_trace_and_compile(self):
        assert install_compile_counter()
        before = compile_event_counts()
        f = jax.jit(lambda a: a * 3 + 1)
        jax.block_until_ready(f(jnp.arange(7.0)))
        mid = compile_event_counts()
        assert mid["traces"] > before["traces"]
        assert mid["compiles"] > before["compiles"]
        # cached second call adds neither — the retrace-budget signal
        jax.block_until_ready(f(jnp.arange(7.0)))
        after = compile_event_counts()
        assert after == mid
