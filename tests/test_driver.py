"""End-to-end integration: train_global over the variant matrix on the
8-worker CPU mesh (SURVEY.md section 4 'Integration')."""

import numpy as np
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global


def cfg(**kw):
    base = dict(model="mlp", dataset="mnist", epochs_global=2, epochs_local=2,
                batch_size=16, limit_train_samples=800,
                limit_eval_samples=100, compute_dtype="float32",
                augment=False, aggregation_by="weights", seed=1)
    base.update(kw)
    return Config(**base)


def run(mesh8, **kw):
    return train_global(cfg(**kw), mesh=mesh8, progress=False)


class TestEndToEnd:
    def test_balanced_allreduce_learns(self, mesh8):
        res = run(mesh8)
        assert res["global_train_losses"][-1] < res["global_train_losses"][0]
        assert res["global_val_accuracies"][-1] > 50.0
        # reference metric structure shapes (trainer.py:192)
        assert len(res["global_train_losses"]) == 2
        assert len(res["all_epochs_losses"]) == 4  # epochs_global*epochs_local
        assert len(res["all_workers_losses"]) == 8
        assert all(len(w) > 0 for w in res["all_workers_losses"])
        assert len(res["worker_specific_train_losses"]) == 4
        assert len(res["global_epoch_accuracies"][0]) == 2

    @pytest.mark.parametrize("topology", ["ring", "double_ring"])
    def test_gossip_topologies_run(self, mesh8, topology):
        res = run(mesh8, topology=topology, aggregation_type="weighted")
        assert res["global_train_losses"][-1] < res["global_train_losses"][0]

    def test_disbalanced_mode(self, mesh8):
        res = run(mesh8, data_mode="disbalanced", fixed_ratio=0.6)
        assert np.isfinite(res["global_train_losses"]).all()

    def test_heterogeneous_durations_shift_shards(self, mesh8):
        # inverse proportionality: 4x-slower worker 0 gets ~4x less data
        sims = np.array([4.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0])
        res2 = train_global(cfg(proportionality="inverse"), mesh=mesh8,
                            simulated_durations=sims, progress=False)
        w0 = len(res2["all_workers_losses"][0])
        w1 = len(res2["all_workers_losses"][1])
        assert w0 < w1  # slower worker saw fewer batches

    def test_reference_direct_proportionality(self, mesh8):
        # reference-compat mode: slower worker gets MORE data (SURVEY.md 2.5.1)
        sims = np.array([4.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0])
        res = train_global(cfg(proportionality="direct"), mesh=mesh8,
                           simulated_durations=sims, progress=False)
        w0 = len(res["all_workers_losses"][0])
        w1 = len(res["all_workers_losses"][1])
        assert w0 > w1

    def test_time_limit_caps_steps(self, mesh8):
        # a tiny time budget caps every worker's steps per round
        sims = np.full(8, 8.0)  # 8s probe for 10 batches -> 0.8 s/batch
        res = train_global(
            cfg(time_limit=1.6), mesh=mesh8, simulated_durations=sims,
            # keep the measured per-epoch wall consistent with the probe
            # (0.8 s/batch x 2 capped steps) so the cap stays at 2
            simulated_round_durations=lambda e: np.full(8, 1.6),
            progress=False)
        # cap = 1.6/0.8 = 2 batches/worker/epoch -> per local epoch at most
        # 2*16=32 examples contribute
        for i in range(8):
            per_epoch = len(res["all_workers_losses"][i]) / 4  # 4 local epochs
            assert per_epoch <= 2

    def test_midrun_slowdown_shrinks_next_cap(self, mesh8):
        # VERDICT r1 'Next' #8: the straggler budget must react to MEASURED
        # round wall time, not just the initial probe.  Worker walls are
        # uniform in round 0; from round 1 on every worker reports a 100x
        # wall.  Under the overlapped pipeline's DELAYED EMA (round r+1 is
        # packed while round r still runs, so the freshest wall it can
        # consume is round r-1's) the reaction lands one round later:
        # round 3's cap shrinks from round 1's measured wall.
        sims = np.full(8, 8.0)  # probe: 0.8 s/batch -> cap 16.0/0.8 = 20

        def walls(epoch):
            base = np.full(8, 0.8)  # per-epoch wall -> spb stays ~0.8
            if epoch >= 1:
                base *= 100.0       # mid-run slowdown
            return base

        res = train_global(cfg(epochs_global=4, epochs_local=1,
                               time_limit=16.0),
                           mesh=mesh8, simulated_durations=sims,
                           simulated_round_durations=walls, progress=False)
        caps = res["step_caps"]
        assert len(caps) == 4
        # rounds 1-2 still see only the uniform round-0 wall
        assert caps[2][0] == caps[1][0], caps
        # round 3 consumed round 1's 100x wall through the delayed EMA
        assert caps[3][0] < caps[2][0], caps

    @pytest.mark.slow
    def test_bert_mlm_end_to_end(self, mesh8):
        # BASELINE ladder entry 5 (BERT MLM): token task with [B, L] labels
        # through pack_shard -> engine -> eval (VERDICT r1 missing #2).
        # slow tier (ISSUE 2 triage): the two bert driver e2e cases are the
        # longest tier-1 rounds (~50 s combined); bert coverage stays in
        # tier-1 via test_models_extra/test_pp unit+module tests
        res = run(mesh8, model="bert_tiny", dataset="synthetic_mlm",
                  epochs_global=2, epochs_local=1, batch_size=8,
                  limit_train_samples=256, limit_eval_samples=64, lr=1e-3)
        assert res["global_train_losses"][-1] < res["global_train_losses"][0]
        assert np.isfinite(res["global_train_losses"]).all()

    @pytest.mark.slow
    def test_bert_mlm_final_evaluation(self, mesh8):
        # the rank-0 evaluator must handle [B, L] token labels (masked
        # positions only) without crashing and produce finite P/R/F1.
        # slow tier (ISSUE 2 triage), see test_bert_mlm_end_to_end
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.eval import evaluate
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import rank0_variables
        res = run(mesh8, model="bert_tiny", dataset="synthetic_mlm",
                  epochs_global=1, epochs_local=1, batch_size=8,
                  limit_train_samples=128, limit_eval_samples=48)
        test = res["test"]
        loss, acc, preds, labels, metrics = evaluate(
            res["model"], rank0_variables(res["state"]),
            test.images, test.labels, batch_size=8, verbose=False)
        assert np.isfinite(loss) and 0.0 <= acc <= 100.0
        assert preds.shape == labels.shape
        assert all(np.isfinite(v) for v in metrics.values())


class TestCompileCacheTelemetry:
    def test_counter_counts_monitoring_events(self):
        # the persistent-cache hit/miss report rides jax's monitoring
        # events; count them directly so the plumbing is verified without
        # depending on backend cache support
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.xla_flags import (
            compile_cache_counts,
            install_cache_counter,
        )
        assert install_cache_counter()
        from jax._src import monitoring
        before = compile_cache_counts()
        monitoring.record_event("/jax/compilation_cache/cache_hits")
        monitoring.record_event("/jax/compilation_cache/cache_misses")
        monitoring.record_event("/jax/compilation_cache/cache_misses")
        after = compile_cache_counts()
        assert after["hits"] - before["hits"] == 1
        assert after["misses"] - before["misses"] == 2

    def test_train_global_reports_per_run_delta(self, mesh8):
        # enabled=False run: counters exist and the delta is zero
        res = train_global(cfg(epochs_global=1), mesh=mesh8, progress=False)
        assert res["compile_cache"]["enabled"] is False
        assert res["compile_cache"]["hits"] >= 0
        assert res["compile_cache"]["misses"] >= 0
