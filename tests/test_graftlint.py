"""graftlint rule fixtures: true positives AND true negatives per rule
(R1-R5), suppression-comment + baseline-file behavior, and the two
acceptance gates — the repo lints clean against its checked-in baseline,
and an injected true positive flips the exit to non-zero."""

import json
import os
import subprocess
import sys

import pytest

from tools.graftlint.core import (_suppressed, _suppressions,
                                  apply_baseline, lint_paths,
                                  load_baseline, write_baseline, Finding)
from tools.graftlint.rules import lint_source

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(
    REPO, "learning_deep_neural_network_in_distributed_computing"
          "_environment_tpu")
BASELINE = os.path.join(REPO, "tools", "graftlint", "baseline.json")


def rules_for(src: str) -> list[str]:
    """Rule ids firing on a snippet, suppression comments honored."""
    per_line, file_level = _suppressions(src)
    return [r.rule for r in lint_source(src, "snippet.py")
            if not _suppressed(r, per_line, file_level)]


# --------------------------------------------------------------------
# R1: host sync in traced regions
# --------------------------------------------------------------------
class TestR1HostSync:
    def test_item_in_jit_flagged(self):
        src = """
import jax
@jax.jit
def f(x):
    return x.item()
"""
        assert rules_for(src) == ["R1"]

    def test_item_on_host_fn_clean(self):
        src = """
def host(x):
    return x.item()
"""
        assert rules_for(src) == []

    def test_np_asarray_on_traced_flagged(self):
        src = """
import jax, numpy as np
def body(x):
    return np.asarray(x) + 1
g = jax.jit(body)
"""
        assert rules_for(src) == ["R1"]

    def test_float_of_traced_flagged_but_static_float_clean(self):
        src = """
import jax, jax.numpy as jnp
@jax.jit
def f(x, k=4):
    y = jnp.sum(x)
    bad = float(y)
    return bad
def outer(self, x):
    k = 3
    good = float(k)   # host int -> host float, no sync
    return good
"""
        assert rules_for(src) == ["R1"]

    def test_implicit_bool_branch_flagged(self):
        src = """
import jax
@jax.jit
def f(x):
    if x > 0:
        return x
    return -x
"""
        assert rules_for(src) == ["R1"]

    def test_is_none_branch_clean(self):
        src = """
import jax
@jax.jit
def f(x, d=None):
    if d is not None:
        x = x + d
    return x
"""
        assert rules_for(src) == []

    def test_scan_body_is_traced(self):
        src = """
from jax import lax
def run(xs):
    def body(c, x):
        return c, x.tolist()
    return lax.scan(body, 0.0, xs)
"""
        assert rules_for(src) == ["R1"]


# --------------------------------------------------------------------
# R2: retrace hazards
# --------------------------------------------------------------------
class TestR2Retrace:
    def test_jit_in_loop_flagged(self):
        src = """
import jax
def run(xs):
    out = []
    for x in xs:
        out.append(jax.jit(lambda a: a + 1)(x))
    return out
"""
        assert "R2" in rules_for(src)

    def test_module_scope_jit_clean(self):
        src = """
import jax
f = jax.jit(lambda a: a + 1)
def run(x):
    return f(x)
"""
        assert rules_for(src) == []

    def test_construct_and_call_flagged(self):
        src = """
import jax
def run(g, x):
    return jax.jit(g)(x)
"""
        assert rules_for(src) == ["R2"]

    def test_local_jit_then_call_flagged(self):
        src = """
import jax
def run(g, x):
    fn = jax.jit(g)
    return fn(x)
"""
        assert rules_for(src) == ["R2"]

    def test_jit_decorated_local_def_then_call_flagged(self):
        src = """
import jax
def evaluate(x):
    @jax.jit
    def run(a):
        return a + 1
    return run(x)
"""
        assert "R2" in rules_for(src)

    def test_jit_decorated_module_def_clean(self):
        src = """
import jax
@jax.jit
def run(a):
    return a + 1
def evaluate(x):
    return run(x)
"""
        assert rules_for(src) == []

    def test_builder_returning_jit_clean(self):
        src = """
import jax
def build(fn):
    return jax.jit(fn, donate_argnums=(0,))
"""
        assert rules_for(src) == []

    def test_unhashable_static_arg_flagged(self):
        src = """
import jax
def f(a, b):
    return a
out = jax.jit(f, static_argnums=(1,))(1, [2, 3])
"""
        assert "R2" in rules_for(src)


# --------------------------------------------------------------------
# R3: collective axis-name vocabulary
# --------------------------------------------------------------------
class TestR3AxisNames:
    def test_unknown_axis_flagged(self):
        src = """
from jax import lax
def body(x):
    return lax.psum(x, "workers")
"""
        assert rules_for(src) == ["R3"]

    def test_vocabulary_axes_clean(self):
        src = """
from jax import lax
def body(x):
    y = lax.pmean(x, "data")
    return lax.psum(y, ("data", "model"))
"""
        assert rules_for(src) == []

    def test_axis_constant_name_clean(self):
        src = """
from jax import lax
from pkg.mesh import DATA_AXIS
def body(x):
    return lax.psum(x, DATA_AXIS)
"""
        assert rules_for(src) == []

    def test_tuple_with_typo_flagged(self):
        src = """
from jax import lax
def body(x):
    return lax.pmean(x, ("data", "modl"))
"""
        assert rules_for(src) == ["R3"]

    def test_axis_outside_enclosing_shard_map_specs_flagged(self):
        # mesh is a VARIABLE (as in all real call sites): the check keys
        # on the statically-visible specs alone
        src = """
import jax
from jax.sharding import PartitionSpec as P
from jax import lax

def inner(x):
    return lax.psum(x, "model")

prog = jax.shard_map(inner, mesh=mesh, in_specs=(P("data"),),
                     out_specs=P("data"))
"""
        assert rules_for(src) == ["R3"]

    def test_dynamic_specs_skip_subset_check(self):
        src = """
import jax
from jax import lax

def inner(x):
    return lax.psum(x, "model")

prog = jax.shard_map(inner, mesh=mesh, in_specs=tuple(specs),
                     out_specs=out)
"""
        assert rules_for(src) == []


# --------------------------------------------------------------------
# R4: donation hygiene
# --------------------------------------------------------------------
class TestR4Donation:
    def test_use_after_donate_flagged(self):
        src = """
import jax
def step(g, state, x):
    f = jax.jit(g, donate_argnums=(0,))
    out = f(state, x)
    return state  # graftlint reads the donated buffer again
"""
        assert "R4" in rules_for(src)

    def test_rebound_donated_name_clean(self):
        src = """
import jax
def step(g, state, x):
    f = jax.jit(g, donate_argnums=(0,))
    state = f(state, x)
    return state
"""
        assert "R4" not in rules_for(src)

    def test_rebinding_in_later_statement_clears_donated_name(self):
        src = """
import jax
def step(g, state, x):
    f = jax.jit(g, donate_argnums=(0,))
    out = f(state, x)
    state = out[0]
    return state  # reads the NEW binding, not the donated buffer
"""
        assert "R4" not in rules_for(src)

    def test_read_of_donated_name_before_rebind_still_flagged(self):
        src = """
import jax
def step(g, state, x):
    f = jax.jit(g, donate_argnums=(0,))
    out = f(state, x)
    norm = state.sum()   # donated buffer read BEFORE the rebind
    state = out[0]
    return state, norm
"""
        assert "R4" in rules_for(src)

    def test_jit_of_shard_map_without_donation_flagged(self):
        src = """
import jax
from jax import shard_map
fn = shard_map(lambda x: x, mesh=None, in_specs=None, out_specs=None)
prog = jax.jit(fn)
"""
        assert rules_for(src) == ["R4"]

    def test_jit_of_shard_map_with_donation_clean(self):
        src = """
import jax
from jax import shard_map
fn = shard_map(lambda x: x, mesh=None, in_specs=None, out_specs=None)
prog = jax.jit(fn, donate_argnums=(0,))
"""
        assert rules_for(src) == []

    def test_apply_stage_tracker_use_after_donate_flagged(self):
        # ISSUE 9 fixture: the shard-resident apply-stage program donates
        # BOTH the grads and the round-optimizer tracker rows
        # (train._build_sync donate=(0, 1)); reading the donated tracker
        # input after the call is the exact hazard class R4 exists for
        src = """
import jax
def sync_round(sync, grads, round_opt):
    prog = jax.jit(sync, donate_argnums=(0, 1))
    norm, new_opt = prog(grads, round_opt)
    stale = round_opt  # donated tracker rows read after the call
    return norm, stale
"""
        assert "R4" in rules_for(src)

    def test_apply_stage_tracker_rebound_clean(self):
        # the engine's real shape: the donated tracker name is rebound to
        # the program's output before any further read
        src = """
import jax
def sync_round(sync, grads, round_opt):
    prog = jax.jit(sync, donate_argnums=(0, 1))
    norm, round_opt = prog(grads, round_opt)
    return norm, round_opt
"""
        assert "R4" not in rules_for(src)

    def test_enter_gather_resident_use_after_donate_flagged(self):
        # ISSUE 11 fixture: the round-entry gather program DONATES the
        # resident bucket shards into the gather (train.py streamed
        # "enter" cache / comms.make_resident_gather donate=True);
        # reading the donated resident input after the call would touch
        # freed 1/N shard buffers — the exact hazard class R4 exists for
        src = """
import jax
def enter_round(gather, resident):
    prog = jax.jit(gather, donate_argnums=(0,))
    params = prog(resident)
    shard_bytes = resident  # donated resident shards read after the call
    return params, shard_bytes
"""
        assert "R4" in rules_for(src)

    def test_enter_gather_resident_rebound_clean(self):
        # the engine's real shape: the resident name is rebound to the
        # NEXT sync's scatter output before any further read — the
        # steady-state resident cycle (gather consumes, scatter renews)
        src = """
import jax
def enter_round(gather, sync, resident):
    prog = jax.jit(gather, donate_argnums=(0,))
    params = prog(resident)
    resident = sync(params)
    return resident
"""
        assert "R4" not in rules_for(src)

    def test_buddy_hop_state_use_after_donate_flagged(self):
        # ISSUE 12 fixture: the buddy-redundant sync program donates the
        # state whose shard rows it re-scatters AND ring-copies
        # (train._build_sync donate=(0,...)); reading the donated
        # state's OLD buddy rows after the call — instead of the fresh
        # copy the hop just produced — touches freed buffers, the exact
        # hazard class R4 exists for (the driver therefore drops the
        # previous buddy before dispatch and reads only the output's)
        src = """
import jax
def sync_round(sync, params, residual):
    prog = jax.jit(sync, donate_argnums=(0, 1))
    out = prog(params, residual)
    stale = residual  # donated EF rows read after the buddy-hop sync
    return out, stale
"""
        assert "R4" in rules_for(src)

    def test_buddy_hop_rebound_to_fresh_copy_clean(self):
        # the engine's real shape: every protected row (resident shards,
        # residual, buddy) is rebound to the sync program's OUTPUT dict
        # before any further read — the fresh ring copy replaces the
        # donated generation
        src = """
import jax
def sync_round(sync, params, residual):
    prog = jax.jit(sync, donate_argnums=(0, 1))
    out = prog(params, residual)
    params = out["out"]
    residual = out["residual"]
    buddy = out["buddy"]
    return params, residual, buddy
"""
        assert "R4" not in rules_for(src)

    def test_hier_outer_residual_use_after_donate_flagged(self):
        # ISSUE 13 fixture: the hierarchical standalone sync donates the
        # params AND both EF residual levels (train._build_sync
        # donate=(0, 1, 2)); reading the donated OUTER residual rows
        # after the call — instead of the fresh generation the program's
        # output dict carries — touches freed 1/W-span buffers, the
        # exact hazard class R4 exists for
        src = """
import jax
def hier_round(sync, params, residual, outer_residual):
    prog = jax.jit(sync, donate_argnums=(0, 1, 2))
    out = prog(params, residual, outer_residual)
    stale = outer_residual  # donated DCN EF rows read after the sync
    return out, stale
"""
        assert "R4" in rules_for(src)

    def test_hier_outer_residual_rebound_clean(self):
        # the engine's real shape: every donated level is rebound to the
        # program's output dict before any further read (round_start /
        # round_streamed_start)
        src = """
import jax
def hier_round(sync, params, residual, outer_residual):
    prog = jax.jit(sync, donate_argnums=(0, 1, 2))
    out = prog(params, residual, outer_residual)
    params = out["out"]
    residual = out["residual"]
    outer_residual = out["outer_residual"]
    return params, residual, outer_residual
"""
        assert "R4" not in rules_for(src)

    def test_sim_stacked_state_use_after_donate_flagged(self):
        # ISSUE 14 fixture: the simulated round program donates the
        # whole worker-STACKED TrainState (sim.SimEngine._build_round,
        # donate_argnums=(0,)) — with hundreds of simulated workers the
        # stacked carry is the chip's dominant allocation, so a read of
        # the donated input after dispatch touches freed [N, ...]
        # buffers (and a declined donation would silently DOUBLE the
        # state memory the whole lab exists to save)
        src = """
import jax
def sim_loop(sim_round, state, x, y, m):
    prog = jax.jit(sim_round, donate_argnums=(0,))
    new_state, metrics = prog(state, x, y, m)
    probe = state  # donated stacked carry read after dispatch
    return new_state, metrics, probe
"""
        assert "R4" in rules_for(src)

    def test_sim_stacked_state_rebound_to_output_clean(self):
        # the engine's real shape: the caller rebinds its state name to
        # the round's output before any further read (driver round loop)
        src = """
import jax
def sim_loop(sim_round, state, x, y, m):
    prog = jax.jit(sim_round, donate_argnums=(0,))
    state, metrics = prog(state, x, y, m)
    return state, metrics
"""
        assert "R4" not in rules_for(src)

    def test_stale_presync_state_use_after_overlap_flagged(self):
        # ISSUE 16 fixture: under --sync_staleness the stale sync
        # program reads a round's trained state WITHOUT donating it
        # while the NEXT round's program donates those same buffers —
        # device-safe (the runtime orders the donating write after the
        # already-dispatched sync's read) but host-unsafe: after the
        # overlapped dispatch the donated pre-sync state must never be
        # read again on the host, exactly the in-flight contract R4
        # polices
        src = """
import jax
def overlapped_rounds(round_prog, stale_sync, state, batch):
    prog = jax.jit(round_prog, donate_argnums=(0,))
    pending = stale_sync(state)     # in flight: reads, never donates
    new_state = prog(state, batch)  # donates the same buffers
    probe = state   # donated pre-sync state read after the dispatch
    return new_state, pending, probe
"""
        assert "R4" in rules_for(src)

    def test_stale_delivery_rebinds_to_blend_clean(self):
        # the engine's real shape (train._deliver_oldest / the round
        # loop): at the fence every consumer rebinds its state name to
        # the delivery fold's output — the delivered blend replaces the
        # donated generation before any further read
        src = """
import jax
def overlapped_rounds(round_prog, stale_sync, deliver, state, batch):
    prog = jax.jit(round_prog, donate_argnums=(0,))
    pending = stale_sync(state)
    state = prog(state, batch)
    state = deliver(state, pending)   # the delivered blend
    return state
"""
        assert "R4" not in rules_for(src)

    def test_chunked_prefill_pool_use_after_donate_flagged(self):
        # ISSUE 17 fixture: the [1, C] chunk program donates BOTH page
        # pools every call (engine._build_prefill_program
        # donate_argnums=(1, 2)) and the scheduler calls it once per
        # chunk — reading the pre-chunk kc/vc between chunks touches the
        # freed generation of the dominant serve allocation, the exact
        # hazard class R4 exists for
        src = """
import jax
def prefill_loop(chunk_step, params, kc, vc, chunk, tail):
    prog = jax.jit(chunk_step, donate_argnums=(1, 2))
    tok, logits, kc2, vc2 = prog(params, kc, vc, chunk)
    warm = kc  # donated page pool read between chunks
    tok, logits, kc2, vc2 = prog(params, kc2, vc2, tail)
    return tok, warm
"""
        assert "R4" in rules_for(src)

    def test_chunked_prefill_pool_rebound_each_chunk_clean(self):
        # the engine's real shape: every chunk rebinds the pool names to
        # the returned pools in the same statement, so the next chunk
        # (and the interleaved decode step) only ever sees the current
        # generation
        src = """
import jax
def prefill_loop(chunk_step, params, kc, vc, chunks):
    prog = jax.jit(chunk_step, donate_argnums=(1, 2))
    for c in chunks:
        tok, logits, kc, vc = prog(params, kc, vc, c)
    return tok, kc, vc
"""
        assert "R4" not in rules_for(src)

    def test_draft_cache_read_after_verify_dispatch_flagged(self):
        # ISSUE 18 fixture: the speculative tick runs the draft's
        # donated decode step k times, then dispatches the target's
        # fused verify.  The draft pools' CARRY names still point at the
        # generation the last draft step donated — reading one after the
        # verify dispatch (e.g. to "snapshot" draft KV for rollback)
        # touches freed pages.  Rollback is arithmetic on the accepted
        # length, never a pool read — exactly the contract R4 polices
        src = """
import jax
def spec_tick(draft_step, verify, params, dkc, dvc, kc, vc, burst, y):
    dprog = jax.jit(draft_step, donate_argnums=(1, 2))
    for j in range(4):
        y, dkc2, dvc2 = dprog(params, dkc, dvc, y)
    vprog = jax.jit(verify, donate_argnums=(1, 2))
    emitted, acc, kc, vc = vprog(params, kc, vc, burst)
    snapshot = dkc  # donated draft carry read after verify dispatch
    return emitted, acc, snapshot
"""
        assert "R4" in rules_for(src)

    def test_draft_cache_rebound_to_output_clean(self):
        # the engine's real shape (scheduler._spec_step via
        # ServeEngine.decode / .verify): every draft step rebinds the
        # draft pool names to its outputs in the same statement, and
        # accept/rollback is computed from `acc` alone — no pool read
        # ever sees a stale generation
        src = """
import jax
def spec_tick(draft_step, verify, params, dkc, dvc, kc, vc, burst, y):
    dprog = jax.jit(draft_step, donate_argnums=(1, 2))
    for j in range(4):
        y, dkc, dvc = dprog(params, dkc, dvc, y)
    vprog = jax.jit(verify, donate_argnums=(1, 2))
    emitted, acc, kc, vc = vprog(params, kc, vc, burst)
    return emitted, acc, dkc, dvc, kc, vc
"""
        assert "R4" not in rules_for(src)

    def test_rebound_name_no_longer_shard_map_clean(self):
        src = """
import jax
from jax import shard_map
fn = shard_map(lambda x: x, mesh=None, in_specs=None, out_specs=None)
prog = jax.jit(fn, donate_argnums=(0,))
fn = make_plain_step()
other = jax.jit(fn)
"""
        assert rules_for(src) == []

    def test_jit_before_shard_map_assignment_not_matched(self):
        src = """
import jax
from jax import shard_map
fn = make_plain_step()
prog = jax.jit(fn)
fn = shard_map(lambda x: x, mesh=None, in_specs=None, out_specs=None)
"""
        assert rules_for(src) == []


# --------------------------------------------------------------------
# R6: checkpoint_name remat-label vocabulary (ISSUE 15)
# --------------------------------------------------------------------
class TestR6RematNames:
    def test_typo_label_flagged(self):
        # the hazard: a typo'd label never matches a --remat_policy
        # save_names:/offload_names: set — silent save-nothing
        src = """
from pkg.compat import checkpoint_name
def block(x):
    return checkpoint_name(x, "atn_out")
"""
        assert rules_for(src) == ["R6"]

    def test_vocabulary_labels_clean(self):
        src = """
from jax.ad_checkpoint import checkpoint_name
def block(x):
    a = checkpoint_name(x, "attn_out")
    f = checkpoint_name(a, name="mlp_out")
    return checkpoint_name(a + f, "block_out")
"""
        assert rules_for(src) == []

    def test_dotted_spelling_and_kwarg_typo_flagged(self):
        src = """
import jax
def block(x):
    return jax.ad_checkpoint.checkpoint_name(x, name="block_output")
"""
        assert rules_for(src) == ["R6"]

    def test_dynamic_label_skipped(self):
        # same silence rule as R3's dynamic axis args: a computed label
        # is someone else's contract
        src = """
from pkg.compat import checkpoint_name
def block(x, label):
    return checkpoint_name(x, label)
"""
        assert rules_for(src) == []

    def test_remat_vocab_discovered_from_models_init(self):
        # the vocabulary is DISCOVERED from models/__init__.py's
        # REMAT_NAMES constant, like R3's mesh.py axis discovery
        from tools.graftlint.core import discover_remat_vocab
        vocab = discover_remat_vocab([PKG])
        assert {"attn_out", "mlp_out", "block_out",
                "moe_dispatch"} <= set(vocab)

    def test_custom_vocab_overrides_default(self):
        src = """
from pkg.compat import checkpoint_name
def block(x):
    return checkpoint_name(x, "my_custom_site")
"""
        assert [r.rule for r in lint_source(src, "s.py")] == ["R6"]
        assert [r.rule for r in lint_source(
            src, "s.py",
            remat_vocab=frozenset({"my_custom_site"}))] == []


# --------------------------------------------------------------------
# R5: dtype-promotion traps
# --------------------------------------------------------------------
class TestR5DtypeTraps:
    def test_np_float64_in_traced_flagged(self):
        src = """
import jax, numpy as np
@jax.jit
def f(x):
    return x * np.float64(0.5)
"""
        assert rules_for(src) == ["R5"]

    def test_astype_builtin_float_flagged(self):
        src = """
import jax
@jax.jit
def f(x):
    return x.astype(float)
"""
        assert rules_for(src) == ["R5"]

    def test_zeros_like_scan_carry_flagged(self):
        src = """
import jax, jax.numpy as jnp
from jax import lax
@jax.jit
def f(xs):
    def body(c, x):
        return c + x, None
    out, _ = lax.scan(body, jnp.zeros_like(xs[0]), xs)
    return out
"""
        assert rules_for(src) == ["R5"]

    def test_zeros_like_with_pinned_dtype_clean(self):
        src = """
import jax, jax.numpy as jnp
from jax import lax
@jax.jit
def f(xs):
    def body(c, x):
        return c + x, None
    out, _ = lax.scan(
        body, jnp.zeros_like(xs[0], dtype=jnp.float32), xs)
    return out
"""
        assert rules_for(src) == []

    def test_zeros_like_with_positional_dtype_clean(self):
        src = """
import jax, jax.numpy as jnp
from jax import lax
@jax.jit
def f(xs):
    def body(c, x):
        return c + x, None
    out, _ = lax.scan(body, jnp.zeros_like(xs[0], jnp.float32), xs)
    return out
"""
        assert rules_for(src) == []


# --------------------------------------------------------------------
# Suppression comments
# --------------------------------------------------------------------
class TestSuppression:
    BAD = """
import jax
@jax.jit
def f(x):
    return x.item(){comment}
"""

    def test_same_line_disable(self):
        src = self.BAD.format(
            comment="  # graftlint: disable=R1 -- fixture")
        assert rules_for(src) == []

    def test_line_above_disable(self):
        src = """
import jax
@jax.jit
def f(x):
    # graftlint: disable=R1 -- fixture
    return x.item()
"""
        assert rules_for(src) == []

    def test_wrong_rule_does_not_suppress(self):
        src = self.BAD.format(comment="  # graftlint: disable=R3")
        assert rules_for(src) == ["R1"]

    def test_disable_all(self):
        src = self.BAD.format(comment="  # graftlint: disable=all")
        assert rules_for(src) == []

    def test_file_level_disable(self):
        src = "# graftlint: disable-file=R1\n" + self.BAD.format(comment="")
        assert rules_for(src) == []

    def test_comment_inside_string_is_not_a_suppression(self):
        src = """
import jax
@jax.jit
def f(x):
    s = "# graftlint: disable=R1"
    return x.item()
"""
        assert rules_for(src) == ["R1"]


# --------------------------------------------------------------------
# Baseline behavior
# --------------------------------------------------------------------
class TestBaseline:
    def _findings(self, tmp_path, src):
        p = tmp_path / "mod.py"
        p.write_text(src)
        return lint_paths([str(p)], repo_root=str(tmp_path))

    BAD = """
import jax
@jax.jit
def f(x):
    return x.item()
"""

    def test_baselined_finding_is_consumed(self, tmp_path):
        findings = self._findings(tmp_path, self.BAD)
        assert [f.rule for f in findings] == ["R1"]
        bl_path = tmp_path / "baseline.json"
        write_baseline(findings, str(bl_path))
        new, accepted = apply_baseline(
            self._findings(tmp_path, self.BAD), load_baseline(str(bl_path)))
        assert new == [] and len(accepted) == 1
        assert accepted[0].baselined

    def test_extra_finding_on_top_of_baseline_reported(self, tmp_path):
        findings = self._findings(tmp_path, self.BAD)
        bl_path = tmp_path / "baseline.json"
        write_baseline(findings, str(bl_path))
        worse = self.BAD + """
@jax.jit
def g(x):
    return x.tolist()
"""
        new, accepted = apply_baseline(
            self._findings(tmp_path, worse), load_baseline(str(bl_path)))
        assert len(accepted) == 1
        assert [f.rule for f in new] == ["R1"]
        assert "tolist" in new[0].line_text

    def test_line_drift_does_not_invalidate_baseline(self, tmp_path):
        findings = self._findings(tmp_path, self.BAD)
        bl_path = tmp_path / "baseline.json"
        write_baseline(findings, str(bl_path))
        shifted = "\n\n\n# moved down\n" + self.BAD
        new, accepted = apply_baseline(
            self._findings(tmp_path, shifted), load_baseline(str(bl_path)))
        assert new == [] and len(accepted) == 1

    def test_overlapping_paths_lint_each_file_once(self, tmp_path):
        p = tmp_path / "mod.py"
        p.write_text(self.BAD)
        findings = lint_paths([str(tmp_path), str(p)],
                              repo_root=str(tmp_path))
        assert len(findings) == 1  # dir + file-in-dir is ONE lint

    def test_unparseable_file_reports_not_crashes(self, tmp_path):
        p = tmp_path / "broken.py"
        p.write_text("def f():\n        x = 1\n      y = 2\n")
        findings = lint_paths([str(p)], repo_root=str(tmp_path))
        assert [f.rule for f in findings] == ["R2"]
        assert "does not parse" in findings[0].message

    def test_scoped_write_baseline_keeps_other_files_entries(
            self, tmp_path):
        a, b = tmp_path / "a.py", tmp_path / "b.py"
        a.write_text(self.BAD)
        b.write_text(self.BAD)
        bl_path = tmp_path / "baseline.json"
        write_baseline(lint_paths([str(tmp_path)],
                                  repo_root=str(tmp_path)), str(bl_path))
        # re-write from a NARROWER scope: b.py's entry must survive
        old = load_baseline(str(bl_path))
        write_baseline(lint_paths([str(a)], repo_root=str(tmp_path)),
                       str(bl_path), old, scoped_files={"a.py"})
        kept = load_baseline(str(bl_path))
        assert ("b.py", "R1", "return x.item()") in kept.entries

    def test_justifications_carry_over_on_rewrite(self, tmp_path):
        findings = self._findings(tmp_path, self.BAD)
        bl_path = tmp_path / "baseline.json"
        write_baseline(findings, str(bl_path))
        data = json.loads(bl_path.read_text())
        data["entries"][0]["justification"] = "known metric readback"
        bl_path.write_text(json.dumps(data))
        write_baseline(self._findings(tmp_path, self.BAD), str(bl_path),
                       load_baseline(str(bl_path)))
        data2 = json.loads(bl_path.read_text())
        assert data2["entries"][0]["justification"] == \
            "known metric readback"


# --------------------------------------------------------------------
# Acceptance gates
# --------------------------------------------------------------------
class TestRepoGate:
    def test_package_lints_clean_against_checked_in_baseline(self):
        findings = lint_paths([PKG], repo_root=REPO)
        new, _ = apply_baseline(findings, load_baseline(BASELINE))
        assert new == [], "\n".join(str(f) for f in new)

    def test_cli_exit_codes(self, tmp_path):
        env = dict(os.environ, PYTHONPATH=REPO)
        clean = subprocess.run(
            [sys.executable, "-m", "tools.graftlint"],
            cwd=REPO, env=env, capture_output=True, text=True)
        assert clean.returncode == 0, clean.stdout + clean.stderr
        bad = tmp_path / "injected.py"
        bad.write_text("""
import jax
@jax.jit
def f(x):
    return x.item()
""")
        injected = subprocess.run(
            [sys.executable, "-m", "tools.graftlint", PKG, str(bad)],
            cwd=REPO, env=env, capture_output=True, text=True)
        assert injected.returncode == 1, injected.stdout + injected.stderr
        assert "R1" in injected.stdout

    def test_axis_vocab_discovered_from_mesh_py(self):
        # ISSUE 13: the hierarchical mesh's ``slice`` outer axis is an
        # X_AXIS constant in mesh.py, so R3's vocabulary discovery must
        # pick it up — collectives over "slice" lint clean, typos don't
        from tools.graftlint.core import discover_axis_vocab
        vocab, constants = discover_axis_vocab([PKG])
        assert {"data", "model", "pipe", "seq", "expert",
                "fsdp", "slice"} <= set(vocab)
        assert constants.get("DATA_AXIS") == "data"
        assert constants.get("SLICE_AXIS") == "slice"

    def test_vmapped_code_without_axis_names_lints_clean(self):
        # ISSUE 14: the simulator's whole point is that vmap'd per-worker
        # code carries NO mesh axis names — the cross-worker reductions
        # are stacked math (sequential fold, roll).  R3's collective-
        # axis-name vocabulary check must have nothing to say about it.
        src = """
import jax
import jax.numpy as jnp
from jax import lax
def sim_sync(local_round, stacked, x):
    outs = jax.vmap(local_round)(stacked, x)
    def add(acc, row):
        return acc + row, None
    folded, _ = lax.scan(add, outs[0], outs[1:])
    return (outs + jnp.roll(outs, 1, axis=0)) / 2.0, folded
"""
        assert "R3" not in rules_for(src)

    def test_slice_axis_collectives_lint_clean(self):
        # the hierarchical program's shape: psum_scatter over the inner
        # axis, ppermute over the discovered "slice" outer axis
        src = """
from jax import lax
def hier(m, ns):
    r1 = lax.ppermute(m, "slice", [(i, (i + 1) % ns)
                                   for i in range(ns)])
    return (m + r1) / 2.0
"""
        assert "R3" not in rules_for(src)
        bad = src.replace('"slice"', '"slices"')
        assert "R3" in rules_for(bad)

    def test_finding_str_and_key(self):
        f = Finding("a.py", 3, 1, "R1", "msg", "  x.item()  ")
        assert f.key == ("a.py", "R1", "x.item()")
        assert "a.py:3:1: R1 msg" == str(f)
