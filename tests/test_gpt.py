"""Causal attention (dense/flash/ring/ulysses) + the GPT-2 family.

The reference has no sequence models (SURVEY.md 2.3); this is the
beyond-reference autoregressive ladder: causal masking in every attention
impl, the canonical GPT-2-small parameter count, and driver-level e2e
training under DP / TP / sequence parallelism.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from jax.experimental.shard_map import shard_map


def _qkv(l=128, h=4, d=16, b=2, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
                 for _ in range(3))


class TestCausalAttention:
    def test_dense_causal_equals_masked(self):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.ops.attention import dot_product_attention
        q, k, v = _qkv()
        d = dot_product_attention(q, k, v, causal=True)
        mask = jnp.asarray(np.tril(np.ones((128, 128), bool)))
        ref = dot_product_attention(q, k, v, mask=mask)
        np.testing.assert_allclose(d, ref, atol=1e-6)
        # position 0 attends only itself -> output == v[0]
        np.testing.assert_allclose(d[:, 0], v[:, 0], atol=1e-6)

    def test_flash_causal_forward_and_grad(self):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.ops.attention import (
            attend, dot_product_attention)
        q, k, v = _qkv()
        d = dot_product_attention(q, k, v, causal=True)
        np.testing.assert_allclose(attend(q, k, v, impl="flash", causal=True),
                                   d, atol=1e-5)
        gf = jax.grad(lambda q: (attend(q, k, v, impl="flash",
                                        causal=True) ** 2).sum())(q)
        gd = jax.grad(lambda q: (dot_product_attention(
            q, k, v, causal=True) ** 2).sum())(q)
        np.testing.assert_allclose(gf, gd, atol=1e-4)

    @pytest.mark.parametrize("impl", ["ring", "all_to_all"])
    def test_seq_parallel_causal_matches_dense(self, impl, devices):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.ops.attention import (
            attend, dot_product_attention)
        q, k, v = _qkv()
        mesh = build_mesh({"seq": 4}, devices[:4])
        f = jax.jit(shard_map(
            lambda q, k, v: attend(q, k, v, impl=impl, axis_name="seq",
                                   causal=True),
            mesh=mesh, in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq")))
        np.testing.assert_allclose(
            f(q, k, v), dot_product_attention(q, k, v, causal=True),
            atol=1e-5)


@pytest.mark.slow
class TestGPT:
    def test_gpt2_small_param_count_canonical(self):
        """Tied-head GPT-2 small == 124,439,808 params (the published
        count: wte 50257x768 + wpe 1024x768 + 12 blocks + ln_f)."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
        m = get_model("gpt2_small")
        vs = jax.eval_shape(
            lambda: m.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32)))
        n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(vs["params"]))
        assert n == 124_439_808

    def test_gpt_tiny_forward_shape_and_causality(self):
        """Logits at position t must not depend on tokens after t."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
        m = get_model("gpt_tiny")
        x = jnp.asarray(np.random.default_rng(0).integers(2, 100, (2, 16)),
                        jnp.int32)
        v = jax.jit(lambda k: m.init(k, x))(jax.random.key(0))
        out = m.apply(v, x)
        assert out.shape == (2, 16, 50257)
        x2 = x.at[:, 8:].set(7)  # perturb the future
        out2 = m.apply(v, x2)
        np.testing.assert_allclose(out[:, :8], out2[:, :8], atol=1e-5)
        assert np.abs(np.asarray(out[:, 8:]) -
                      np.asarray(out2[:, 8:])).max() > 1e-3

    def test_synthetic_lm_labels_are_shifted_inputs(self):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.data import load_dataset
        train, test = load_dataset("synthetic_lm", seed=0,
                                   limit_train=32, limit_test=8)
        assert train.num_classes == 1000
        np.testing.assert_array_equal(train.labels[:, :-1],
                                      train.images[:, 1:])
        assert (train.labels[:, -1] == -1).all()

    def test_gpt_tiny_e2e_dp_loss_decreases(self, mesh8):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        cfg = Config(model="gpt_tiny", dataset="synthetic_lm",
                     epochs_global=2, epochs_local=1, batch_size=8,
                     limit_train_samples=256, limit_eval_samples=64,
                     compute_dtype="float32", augment=False,
                     aggregation_by="weights", seed=0)
        res = train_global(cfg, mesh=mesh8, progress=False)
        l = res["global_train_losses"]
        assert l[-1] < l[0], l

    @pytest.mark.parametrize("axes,extra", [
        ({"data": 2, "model": 2}, {}),
        ({"data": 2, "seq": 2}, {"sequence_parallel": "ring"}),
        ({"data": 2, "pipe": 2}, {}),
        ({"data": 2, "expert": 2}, {"num_experts": 4}),
    ], ids=["tensor", "seq_ring", "pipeline", "expert_moe"])
    def test_gpt_tiny_parallel_modes(self, axes, extra, devices):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        mesh = build_mesh(axes, devices[:4])
        cfg = Config(model="gpt_tiny", dataset="synthetic_lm",
                     epochs_global=1, epochs_local=1, batch_size=8,
                     limit_train_samples=128, limit_eval_samples=32,
                     compute_dtype="float32", augment=False,
                     aggregation_by="weights", seed=1, **extra)
        res = train_global(cfg, mesh=mesh, progress=False)
        assert np.isfinite(res["global_train_losses"]).all()

    def test_gpt_tp_vocab_parallel_tied_head_matches_dense(self, devices):
        """GPT x TP shards the TIED embedding table's vocab dim (r4):
        masked-psum lookup + local-slice logits must compute exactly the
        dense function — trajectories equal, table physically sharded."""
        import jax
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh

        def run(axes, devs):
            cfg = Config(model="gpt_tiny", dataset="synthetic_lm",
                         epochs_global=2, epochs_local=1, batch_size=8,
                         limit_train_samples=128, limit_eval_samples=32,
                         compute_dtype="float32", augment=False,
                         aggregation_by="weights", seed=5)
            return train_global(cfg, mesh=build_mesh(axes, devs),
                                progress=False)

        dense = run({"data": 2}, devices[:2])
        tp = run({"data": 2, "model": 2}, devices[:4])
        np.testing.assert_allclose(tp["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)
        emb = tp["state"].params["tok_emb"]["embedding"]
        assert "model" in str(emb.sharding.spec)
