"""Pipeline parallelism (GPipe schedule, ``parallel/pp.py``).

Correctness ladder: the pure schedule vs sequential application on a
4-stage ``pipe`` mesh (forward AND gradients through the ppermute
pipeline); the scanned-layer BERT vs the loop-unrolled BERT (same math,
different parameter layout); and end-to-end through the driver on a
(data=2, pipe=2) mesh against the dense data=2 run.  Beyond-reference
capability (the reference is data-parallel only, SURVEY.md 2.3).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
from learning_deep_neural_network_in_distributed_computing_environment_tpu.parallel.pp import (
    gpipe_schedule,
    pp_param_specs,
)


@pytest.fixture(scope="module")
def pipe_mesh(devices):
    return Mesh(np.array(devices[:4]), ("pipe",))


class TestGpipeSchedule:
    """Stage function: x -> x * w_s (per-stage weight from a stacked
    [P, 1] array sharded over pipe), composed = prod(w) * x."""

    def _run(self, pipe_mesh, m=8, mb=2):
        rng = np.random.default_rng(0)
        xs = jnp.asarray(rng.normal(size=(m, mb, 16)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(4, 1)), jnp.float32)

        def fn(w_local, xs):
            return gpipe_schedule(
                lambda a: jnp.tanh(a * w_local[0]), xs, "pipe", m)

        sharded = jax.jit(jax.shard_map(
            fn, mesh=pipe_mesh, in_specs=(P("pipe"), P()), out_specs=P()))

        def ref(w, xs):
            a = xs
            for i in range(4):
                a = jnp.tanh(a * w[i])
            return a
        return sharded, ref, w, xs

    def test_forward_matches_sequential(self, pipe_mesh):
        sharded, ref, w, xs = self._run(pipe_mesh)
        np.testing.assert_allclose(sharded(w, xs), ref(w, xs), atol=1e-6)

    def test_grads_match_sequential(self, pipe_mesh):
        sharded, ref, w, xs = self._run(pipe_mesh)
        g = jax.grad(lambda w, xs: (sharded(w, xs) ** 2).sum(),
                     argnums=(0, 1))(w, xs)
        gr = jax.grad(lambda w, xs: (ref(w, xs) ** 2).sum(),
                      argnums=(0, 1))(w, xs)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(a, b, atol=1e-5)


class TestScannedBert:
    def test_scanned_params_are_stacked(self):
        m = get_model("bert_tiny", num_classes=97, scan_layers=True)
        x = jnp.zeros((2, 16), jnp.int32)
        params = m.init(jax.random.key(0), x, train=False)["params"]
        qkv = params["layers"]["layer"]["attn"]["qkv"]["kernel"]
        assert qkv.shape[0] == 2  # bert_tiny: 2 stacked layers
        specs = pp_param_specs(params, axis="pipe")
        assert specs["layers"]["layer"]["attn"]["qkv"]["kernel"][0] == "pipe"
        assert specs["tok_emb"]["embedding"] == P()

    def test_scanned_forward_matches_unrolled(self):
        """Same per-layer params => identical logits for the two layouts."""
        loop = get_model("bert_tiny", num_classes=97)
        scan = get_model("bert_tiny", num_classes=97, scan_layers=True)
        x = jnp.asarray(
            np.random.default_rng(0).integers(0, 97, (2, 16)), jnp.int32)
        pl_ = loop.init(jax.random.key(1), x, train=False)["params"]
        ps = {k: v for k, v in pl_.items() if not k.startswith("layer")}
        ps["layers"] = {"layer": jax.tree.map(
            lambda *ls: jnp.stack(ls), pl_["layer0"], pl_["layer1"])}
        np.testing.assert_allclose(
            scan.apply({"params": ps}, x, train=False),
            loop.apply({"params": pl_}, x, train=False), atol=1e-5)


class TestDriverPipelineParallel:
    """BERT training pipelined over a (data=2, pipe=2) mesh must match the
    dense data=2 run: same shards, same rng, numerics within fp32
    tolerance.  (bert_tiny has 2 layers -> one per stage.)"""

    def _run(self, devices, mesh_axes, **kw):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        mesh = build_mesh(mesh_axes, devices)
        cfg = Config(model="bert_tiny", dataset="synthetic_mlm",
                     epochs_global=2, epochs_local=1, batch_size=8,
                     limit_train_samples=128, limit_eval_samples=32,
                     compute_dtype="float32", augment=False,
                     aggregation_by="weights", seed=7, **kw)
        return train_global(cfg, mesh=mesh, progress=False)

    def test_matches_dense_run(self, devices):
        dense = self._run(devices[:2], {"data": 2})
        pp = self._run(devices[:4], {"data": 2, "pipe": 2})
        np.testing.assert_allclose(pp["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)
        assert pp["global_train_losses"][-1] < pp["global_train_losses"][0]

    def test_microbatch_override(self, devices):
        pp = self._run(devices[:4], {"data": 2, "pipe": 2},
                       pp_microbatches=4)
        assert np.isfinite(pp["global_train_losses"]).all()

    def test_requires_attention_model(self, devices):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        mesh = build_mesh({"data": 2, "pipe": 2}, devices[:4])
        cfg = Config(model="mlp", dataset="mnist", limit_train_samples=64,
                     limit_eval_samples=16, augment=False)
        with pytest.raises(ValueError, match="pipe"):
            train_global(cfg, mesh=mesh, progress=False)


class TestPipelineRemat:
    """``--pp_remat``: per-layer rematerialization (the GPipe paper's
    memory recipe) — identical numerics, strictly smaller autodiff
    residuals."""

    def test_remat_shrinks_saved_residuals(self):
        """The vjp closure is a pytree whose leaves ARE the saved
        residuals; remat must cut their total bytes well below the
        all-intermediates profile while computing the same function."""
        x = jnp.asarray(
            np.random.default_rng(0).integers(0, 97, (8, 64)), jnp.int32)
        outs, sizes = {}, {}
        params = None
        for remat in (False, True):
            m = get_model("bert_tiny", num_classes=97, scan_layers=True,
                          remat=remat)
            if params is None:
                params = m.init(jax.random.key(0), x, train=False)["params"]
            out, vjp_fn = jax.vjp(
                lambda p: m.apply({"params": p}, x, train=True), params)
            outs[remat] = out
            sizes[remat] = sum(l.nbytes for l in
                               jax.tree_util.tree_leaves(vjp_fn))
        np.testing.assert_allclose(outs[True], outs[False], atol=1e-6)
        assert sizes[True] < 0.6 * sizes[False], sizes

    def test_driver_pp_remat_matches_dense(self, devices):
        run = TestDriverPipelineParallel()
        dense = run._run(devices[:2], {"data": 2})
        pp = run._run(devices[:4], {"data": 2, "pipe": 2}, pp_remat=True)
        np.testing.assert_allclose(pp["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)


class TestDriverPipelineTensorParallel:
    """3-D composition: (data=2, pipe=2, model=2) — the stacked layer axis
    shards over 'pipe' AND the inner Megatron dims over 'model'
    (bert.pp_tp_param_specs); numerics must match the dense data=2 run."""

    def test_matches_dense_run(self, devices):
        run = TestDriverPipelineParallel()
        dense = run._run(devices[:2], {"data": 2})
        both = run._run(devices[:8], {"data": 2, "pipe": 2, "model": 2})
        np.testing.assert_allclose(both["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)
        specs = [str(l.sharding.spec) for l in
                 jax.tree_util.tree_leaves(both["state"].params)]
        assert any("pipe" in s and "model" in s for s in specs)

    def test_pp_tp_specs_pattern(self):
        """Stacked leaves get ('pipe', <megatron parts>); the vocab-parallel
        decode outside the stack keeps its plain TP spec."""
        from jax.sharding import PartitionSpec as P
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models.bert import (
            pp_tp_param_specs,
        )
        model = get_model("bert_tiny", num_classes=96, scan_layers=True)
        x = jnp.zeros((2, 16), jnp.int32)
        variables = jax.eval_shape(
            lambda k: model.init(k, x, train=False), jax.random.key(0))
        specs = pp_tp_param_specs(variables["params"], pipe_axis="pipe",
                                  axis="model")
        flat = {jax.tree_util.keystr(p): s for p, s in
                jax.tree_util.tree_leaves_with_path(
                    specs, is_leaf=lambda s: isinstance(s, P))}
        qkv = next(s for k, s in flat.items()
                   if "layers" in k and "qkv" in k and "kernel" in k)
        assert qkv[0] == "pipe" and "model" in qkv
        dec = next(s for k, s in flat.items()
                   if "mlm_decoder" in k and "kernel" in k)
        assert dec == P(None, "model")
