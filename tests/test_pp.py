"""Pipeline parallelism (GPipe schedule, ``parallel/pp.py``).

Correctness ladder: the pure schedule vs sequential application on a
4-stage ``pipe`` mesh (forward AND gradients through the ppermute
pipeline); the scanned-layer BERT vs the loop-unrolled BERT (same math,
different parameter layout); and end-to-end through the driver on a
(data=2, pipe=2) mesh against the dense data=2 run.  Beyond-reference
capability (the reference is data-parallel only, SURVEY.md 2.3).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
from learning_deep_neural_network_in_distributed_computing_environment_tpu.parallel.pp import (
    gpipe_schedule,
    pp_param_specs,
)


@pytest.fixture(scope="module")
def pipe_mesh(devices):
    return Mesh(np.array(devices[:4]), ("pipe",))


class TestGpipeSchedule:
    """Stage function: x -> x * w_s (per-stage weight from a stacked
    [P, 1] array sharded over pipe), composed = prod(w) * x."""

    def _run(self, pipe_mesh, m=8, mb=2):
        rng = np.random.default_rng(0)
        xs = jnp.asarray(rng.normal(size=(m, mb, 16)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(4, 1)), jnp.float32)

        def fn(w_local, xs):
            return gpipe_schedule(
                lambda a: jnp.tanh(a * w_local[0]), xs, "pipe", m)

        sharded = jax.jit(jax.shard_map(
            fn, mesh=pipe_mesh, in_specs=(P("pipe"), P()), out_specs=P()))

        def ref(w, xs):
            a = xs
            for i in range(4):
                a = jnp.tanh(a * w[i])
            return a
        return sharded, ref, w, xs

    def test_forward_matches_sequential(self, pipe_mesh):
        sharded, ref, w, xs = self._run(pipe_mesh)
        np.testing.assert_allclose(sharded(w, xs), ref(w, xs), atol=1e-6)

    def test_grads_match_sequential(self, pipe_mesh):
        sharded, ref, w, xs = self._run(pipe_mesh)
        g = jax.grad(lambda w, xs: (sharded(w, xs) ** 2).sum(),
                     argnums=(0, 1))(w, xs)
        gr = jax.grad(lambda w, xs: (ref(w, xs) ** 2).sum(),
                      argnums=(0, 1))(w, xs)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(a, b, atol=1e-5)


class TestScannedBert:
    def test_scanned_params_are_stacked(self):
        m = get_model("bert_tiny", num_classes=97, scan_layers=True)
        x = jnp.zeros((2, 16), jnp.int32)
        params = m.init(jax.random.key(0), x, train=False)["params"]
        qkv = params["layers"]["layer"]["attn"]["qkv"]["kernel"]
        assert qkv.shape[0] == 2  # bert_tiny: 2 stacked layers
        specs = pp_param_specs(params, axis="pipe")
        assert specs["layers"]["layer"]["attn"]["qkv"]["kernel"][0] == "pipe"
        assert specs["tok_emb"]["embedding"] == P()

    def test_scanned_forward_matches_unrolled(self):
        """Same per-layer params => identical logits for the two layouts."""
        loop = get_model("bert_tiny", num_classes=97)
        scan = get_model("bert_tiny", num_classes=97, scan_layers=True)
        x = jnp.asarray(
            np.random.default_rng(0).integers(0, 97, (2, 16)), jnp.int32)
        pl_ = loop.init(jax.random.key(1), x, train=False)["params"]
        ps = {k: v for k, v in pl_.items() if not k.startswith("layer")}
        ps["layers"] = {"layer": jax.tree.map(
            lambda *ls: jnp.stack(ls), pl_["layer0"], pl_["layer1"])}
        np.testing.assert_allclose(
            scan.apply({"params": ps}, x, train=False),
            loop.apply({"params": pl_}, x, train=False), atol=1e-5)


@pytest.mark.slow
class TestDriverPipelineParallel:
    """BERT training pipelined over a (data=2, pipe=2) mesh must match the
    dense data=2 run: same shards, same rng, numerics within fp32
    tolerance.  (bert_tiny has 2 layers -> one per stage.)"""

    def _run(self, devices, mesh_axes, **kw):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        mesh = build_mesh(mesh_axes, devices)
        base = dict(model="bert_tiny", dataset="synthetic_mlm",
                    epochs_global=2, epochs_local=1, batch_size=8,
                    limit_train_samples=128, limit_eval_samples=32,
                    compute_dtype="float32", augment=False,
                    aggregation_by="weights", seed=7)
        base.update(kw)
        return train_global(Config(**base), mesh=mesh, progress=False)

    def test_matches_dense_run(self, devices):
        dense = self._run(devices[:2], {"data": 2})
        pp = self._run(devices[:4], {"data": 2, "pipe": 2})
        np.testing.assert_allclose(pp["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)
        assert pp["global_train_losses"][-1] < pp["global_train_losses"][0]

    def test_microbatch_override(self, devices):
        pp = self._run(devices[:4], {"data": 2, "pipe": 2},
                       pp_microbatches=4)
        assert np.isfinite(pp["global_train_losses"]).all()

    def test_requires_attention_model(self, devices):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh
        mesh = build_mesh({"data": 2, "pipe": 2}, devices[:4])
        cfg = Config(model="mlp", dataset="mnist", limit_train_samples=64,
                     limit_eval_samples=16, augment=False)
        with pytest.raises(ValueError, match="pipe"):
            train_global(cfg, mesh=mesh, progress=False)


class TestPipelineRemat:
    """``--pp_remat``: per-layer rematerialization (the GPipe paper's
    memory recipe) — identical numerics, strictly smaller autodiff
    residuals."""

    def test_remat_shrinks_saved_residuals(self):
        """The vjp closure is a pytree whose leaves ARE the saved
        residuals; remat must cut their total bytes well below the
        all-intermediates profile while computing the same function."""
        x = jnp.asarray(
            np.random.default_rng(0).integers(0, 97, (8, 64)), jnp.int32)
        outs, sizes = {}, {}
        params = None
        for remat in (False, True):
            m = get_model("bert_tiny", num_classes=97, scan_layers=True,
                          remat=remat)
            if params is None:
                params = m.init(jax.random.key(0), x, train=False)["params"]
            out, vjp_fn = jax.vjp(
                lambda p: m.apply({"params": p}, x, train=True), params)
            outs[remat] = out
            sizes[remat] = sum(l.nbytes for l in
                               jax.tree_util.tree_leaves(vjp_fn))
        np.testing.assert_allclose(outs[True], outs[False], atol=1e-6)
        assert sizes[True] < 0.6 * sizes[False], sizes

    @pytest.mark.slow
    def test_driver_pp_remat_matches_dense(self, devices):
        run = TestDriverPipelineParallel()
        dense = run._run(devices[:2], {"data": 2})
        pp = run._run(devices[:4], {"data": 2, "pipe": 2}, pp_remat=True)
        np.testing.assert_allclose(pp["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)


@pytest.mark.slow
class TestOneF1B:
    """1F1B schedule (VERDICT r3 'next' #3): loss and every gradient tree
    must equal the dense reference exactly; residual memory must be
    independent of the microbatch count, unlike autodiff-through-GPipe."""

    PSTAGES, M, MB, D = 4, 8, 2, 16

    def _setup(self, m=None):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.parallel.pp import onef1b_loss
        m = m or self.M
        rng = np.random.default_rng(0)
        W = jnp.asarray(rng.normal(size=(self.PSTAGES, self.D, self.D)) * 0.3,
                        jnp.float32)
        H = jnp.asarray(rng.normal(size=(self.D, 3)) * 0.3, jnp.float32)
        xs = jnp.asarray(rng.normal(size=(m, self.MB, self.D)), jnp.float32)
        tgt = jnp.asarray(rng.normal(size=(m, self.MB, 3)), jnp.float32)

        def stage_apply(w, x):
            return jnp.tanh(x @ w[0])

        def loss_fn(hp, y, i):
            return ((y @ hp - tgt[i]) ** 2).sum() / (m * self.MB)

        return onef1b_loss, stage_apply, loss_fn, W, H, xs, tgt, m

    def _sharded(self, pipe_mesh, m=None):
        onef1b_loss, stage_apply, loss_fn, W, H, xs, tgt, m = self._setup(m)

        def run(w, hp, x):
            def inner(wl, hp, x):
                return onef1b_loss(stage_apply, loss_fn, wl, hp, x,
                                   axis_name="pipe", num_micro=m)[0]
            return jax.shard_map(inner, mesh=pipe_mesh,
                                 in_specs=(P("pipe"), P(), P()),
                                 out_specs=P())(w, hp, x)

        def ref(w, hp, x):
            y = x
            for l in range(self.PSTAGES):
                y = jnp.tanh(y @ w[l])
            return ((y @ hp - tgt) ** 2).sum() / (m * self.MB)

        return run, ref, W, H, xs

    def test_loss_and_grads_match_dense(self, pipe_mesh):
        run, ref, W, H, xs = self._sharded(pipe_mesh)
        loss, grads = jax.jit(
            jax.value_and_grad(run, argnums=(0, 1, 2)))(W, H, xs)
        ref_loss, ref_grads = jax.value_and_grad(
            ref, argnums=(0, 1, 2))(W, H, xs)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        for g, r, name in zip(grads, ref_grads, ("stage", "head", "xs")):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                       rtol=1e-4, atol=1e-6, err_msg=name)

    def test_eight_stages_matches_dense(self, devices):
        """p=8 exercises the residual ring-buffer regime where the naive
        min(p+1, m) sizing clobbers in-flight inputs (code-review r4):
        grads must still match the dense reference exactly."""
        mesh8p = Mesh(np.array(devices[:8]), ("pipe",))
        old = self.PSTAGES
        self.PSTAGES = 8
        try:
            run, ref, W, H, xs = self._sharded(mesh8p, m=16)
            loss, grads = jax.jit(
                jax.value_and_grad(run, argnums=(0, 1, 2)))(W, H, xs)
            ref_loss, ref_grads = jax.value_and_grad(
                ref, argnums=(0, 1, 2))(W, H, xs)
            np.testing.assert_allclose(float(loss), float(ref_loss),
                                       rtol=1e-5)
            for g, r, name in zip(grads, ref_grads, ("stage", "head", "xs")):
                np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                           rtol=1e-4, atol=1e-6,
                                           err_msg=name)
        finally:
            self.PSTAGES = old

    def test_odd_microbatch_count(self, pipe_mesh):
        """M need not be a multiple of the stage count."""
        run, ref, W, H, xs = self._sharded(pipe_mesh, m=7)
        loss, grads = jax.jit(
            jax.value_and_grad(run, argnums=(0, 1, 2)))(W, H, xs)
        ref_loss, ref_grads = jax.value_and_grad(
            ref, argnums=(0, 1, 2))(W, H, xs)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(grads[0]),
                                   np.asarray(ref_grads[0]), rtol=1e-4,
                                   atol=1e-6)

    def test_driver_1f1b_matches_dense(self, devices):
        """--pp_schedule 1f1b end to end: the engine's train step runs
        the manual schedule (head+CE per microbatch inside), and the
        loss trajectory must still match the dense data=2 run."""
        run = TestDriverPipelineParallel()
        dense = run._run(devices[:2], {"data": 2})
        pp = run._run(devices[:4], {"data": 2, "pipe": 2},
                      pp_schedule="1f1b", pp_microbatches=4)
        np.testing.assert_allclose(pp["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)
        assert pp["global_train_losses"][-1] < pp["global_train_losses"][0]

    def test_driver_1f1b_gpt_tied_head(self, devices):
        """GPT under 1f1b: the tied tok_emb gets gradient contributions
        from BOTH the in-schedule head and the out-of-schedule embedding
        lookup — trajectory must match the dense twin."""
        run = TestDriverPipelineParallel()
        kw = dict(model="gpt_tiny", dataset="synthetic_lm")
        dense = run._run(devices[:2], {"data": 2}, **kw)
        pp = run._run(devices[:4], {"data": 2, "pipe": 2},
                      pp_schedule="1f1b", **kw)
        np.testing.assert_allclose(pp["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)

    def test_driver_1f1b_llama(self, devices):
        """Llama under 1f1b (RMSNorm head + untied lm_head, RoPE inside
        the stages): trajectory must match the dense twin."""
        run = TestDriverPipelineParallel()
        kw = dict(model="llama_tiny", dataset="synthetic_lm")
        dense = run._run(devices[:2], {"data": 2}, **kw)
        pp = run._run(devices[:4], {"data": 2, "pipe": 2},
                      pp_schedule="1f1b", **kw)
        np.testing.assert_allclose(pp["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)

    def test_driver_1f1b_tp_matches_gpipe_and_dense(self, devices):
        """1F1B x TP (r5): GPT's tied vocab-parallel head runs INSIDE the
        schedule (masked-psum lookup outside, local-slice CE within each
        microbatch's head slot).  The strongest check compares the FINAL
        PARAMETERS — not just the loss trajectory — against the GPipe
        pp x tp run on the identical mesh/seed: both must produce the
        same gradients, so after identical Adam updates the weights must
        agree to float tolerance.  Trajectory must also match dense."""
        run = TestDriverPipelineParallel()
        kw = dict(model="gpt_tiny", dataset="synthetic_lm")
        dense = run._run(devices[:2], {"data": 2}, **kw)
        mesh3d = {"data": 2, "pipe": 2, "model": 2}
        gpipe = run._run(devices, mesh3d, **kw)
        onef = run._run(devices, mesh3d, pp_schedule="1f1b", **kw)
        np.testing.assert_allclose(onef["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)
        for a, b in zip(jax.tree_util.tree_leaves(onef["state"].params),
                        jax.tree_util.tree_leaves(gpipe["state"].params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-5)

    def test_driver_1f1b_fsdp_matches_gpipe_and_dense(self, devices):
        """1F1B x FSDP (r5): ZeRO-3 shards gather OUTSIDE the custom-VJP
        schedule, so the reduce-scatter is the gather's transpose
        downstream of the schedule's full grads.  Final params must
        match the GPipe fsdp x pp run on the identical mesh/seed (same
        gradients, same Adam updates), and the trajectory must match
        dense."""
        run = TestDriverPipelineParallel()
        kw = dict(model="gpt_tiny", dataset="synthetic_lm")
        dense = run._run(devices[:2], {"data": 2}, **kw)
        mesh3d = {"data": 2, "pipe": 2, "fsdp": 2}
        gpipe = run._run(devices, mesh3d, pp_microbatches=4, **kw)
        onef = run._run(devices, mesh3d, pp_schedule="1f1b",
                        pp_microbatches=4, **kw)
        np.testing.assert_allclose(onef["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)
        for a, b in zip(jax.tree_util.tree_leaves(onef["state"].params),
                        jax.tree_util.tree_leaves(gpipe["state"].params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-5)

    def test_driver_1f1b_sp_matches_gpipe_and_dense(self, devices):
        """1F1B x SP (r5): the schedule's fwd/bwd slots run MASKED (not
        cond-skipped) under SP because a ppermute inside a pipe-varying
        cond miscomputes (parallel/pp.py r5 note); the head slot keeps
        the skip (chunk-local numerator over the pre-psum'd global
        denominator — no collective).  Params must match the GPipe
        sp x pp run statistically; trajectory must match dense."""
        run = TestDriverPipelineParallel()
        base = dict(model="gpt_tiny", dataset="synthetic_lm")
        kw = dict(base, sequence_parallel="ring")
        dense = run._run(devices[:2], {"data": 2}, **base)
        mesh3d = {"data": 2, "pipe": 2, "seq": 2}
        gpipe = run._run(devices, mesh3d, **kw)
        onef = run._run(devices, mesh3d, pp_schedule="1f1b",
                        pp_microbatches=4, **kw)
        np.testing.assert_allclose(onef["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)
        # params vs the GPipe twin: STATISTICAL, not elementwise — the
        # 1F1B backward recomputes the ring attention (remat) while
        # GPipe differentiates stored residuals, a different fp32
        # reduction path whose noise Adam amplifies to ~1e-3 on dense
        # leaves over two epochs (measured: every transformer weight
        # <= 1.8e-3 max / ~2e-4 mean), and further on the sparsely-
        # updated embedding tables where tiny-gradient sign flips
        # accumulate full Adam steps (tok_emb 1.3e-2 max).  A real
        # gradient bug diverges at 1e-1 scale or fails the dense-
        # trajectory check above, which caught the original in-cond
        # ppermute miscomputation.
        for (path, a), (_, b) in zip(
                jax.tree_util.tree_leaves_with_path(onef["state"].params),
                jax.tree_util.tree_leaves_with_path(
                    gpipe["state"].params)):
            d = np.abs(np.asarray(a, np.float64) - np.asarray(b))
            cap = 3e-2 if "embedding" in jax.tree_util.keystr(path) \
                else 5e-3
            assert d.max() < cap and d.mean() < 2e-3, (
                jax.tree_util.keystr(path), d.max(), d.mean())

    def test_driver_1f1b_vit_classifier_head(self, devices):
        """ViT under 1f1b (r5): the image family's embed (patchify +
        pos) / stage (encoder layers) / head (mean-pool + classifier)
        decomposition — classification labels exercise the engine's
        label-shape-generic microbatching.  Trajectory must match the
        dense twin."""
        run = TestDriverPipelineParallel()
        kw = dict(model="vit_tiny", dataset="cifar10")
        dense = run._run(devices[:2], {"data": 2}, **kw)
        pp = run._run(devices[:4], {"data": 2, "pipe": 2},
                      pp_schedule="1f1b", pp_microbatches=4, **kw)
        np.testing.assert_allclose(pp["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)

    def test_driver_1f1b_tp_bert_untied_head(self, devices):
        """1F1B x TP with BERT's UNTIED vocab-parallel MLM decode (the
        other head construction): trajectory matches the dense twin."""
        run = TestDriverPipelineParallel()
        dense = run._run(devices[:2], {"data": 2})
        pp = run._run(devices, {"data": 2, "pipe": 2, "model": 2},
                      pp_schedule="1f1b", pp_microbatches=4)
        np.testing.assert_allclose(pp["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)

    def test_residuals_flat_in_microbatch_count(self, pipe_mesh):
        """vjp-closure-leaf comparison (the --pp_remat test's method):
        GPipe-through-autodiff residuals grow with M (every schedule
        step's stage intermediates are saved); the 1F1B custom_vjp's
        residuals are the three gradient trees — Θ(params + inputs),
        independent of the per-microbatch activation count.  At
        M = 2 x stages the 1F1B profile must beat GPipe's."""
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.parallel.pp import gpipe_schedule

        def gpipe_bytes(m):
            _, stage_apply, loss_fn, W, H, xs, tgt, m = self._setup(m)

            def run(w, hp, x):
                def inner(wl, hp, x):
                    outs = gpipe_schedule(
                        lambda a: jnp.tanh(a @ wl[0]), x, "pipe", m)
                    return ((outs @ hp - tgt) ** 2).sum() / (m * self.MB)
                return jax.shard_map(inner, mesh=pipe_mesh,
                                     in_specs=(P("pipe"), P(), P()),
                                     out_specs=P())(w, hp, x)

            _, vjp_fn = jax.vjp(run, W, H, xs)
            return sum(l.nbytes for l in jax.tree_util.tree_leaves(vjp_fn))

        def onef1b_bytes(m):
            run, _, W, H, xs = self._sharded(pipe_mesh, m)
            _, vjp_fn = jax.vjp(run, W, H, xs)
            return sum(l.nbytes for l in jax.tree_util.tree_leaves(vjp_fn))

        m2p = 2 * self.PSTAGES
        gp8, gp16 = gpipe_bytes(m2p), gpipe_bytes(2 * m2p)
        f8, f16 = onef1b_bytes(m2p), onef1b_bytes(2 * m2p)
        # GPipe residuals scale with M; 1F1B's only M-dependence is the
        # input-cotangent tree (gradient-sized, same shape as xs)
        assert gp16 > 1.5 * gp8, (gp8, gp16)
        extra = f16 - f8
        xs_bytes = 2 * m2p * self.MB * self.D * 4
        assert extra <= 2 * xs_bytes, (f8, f16, xs_bytes)
        # the headline claim: at M = 2 x stages, 1F1B beats all-live GPipe
        assert f8 < gp8, (f8, gp8)
        assert f16 < gp16, (f16, gp16)


@pytest.mark.slow
class TestDriverPipelineTensorParallel:
    """3-D composition: (data=2, pipe=2, model=2) — the stacked layer axis
    shards over 'pipe' AND the inner Megatron dims over 'model'
    (bert.pp_tp_param_specs); numerics must match the dense data=2 run."""

    def test_matches_dense_run(self, devices):
        run = TestDriverPipelineParallel()
        dense = run._run(devices[:2], {"data": 2})
        both = run._run(devices[:8], {"data": 2, "pipe": 2, "model": 2})
        np.testing.assert_allclose(both["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)
        specs = [str(l.sharding.spec) for l in
                 jax.tree_util.tree_leaves(both["state"].params)]
        assert any("pipe" in s and "model" in s for s in specs)

    def test_driver_fsdp_pp_matches_dense(self, devices):
        """ZeRO-3 x GPipe (VERDICT r3 'next' #4): params shard over
        'fsdp' on a free dim AND over 'pipe' on the stacked layer dim;
        the batch splits over fsdp, microbatches over the pipe schedule —
        numerics must still match the dense data=2 run."""
        run = TestDriverPipelineParallel()
        dense = run._run(devices[:2], {"data": 2})
        both = run._run(devices[:8], {"data": 2, "fsdp": 2, "pipe": 2})
        np.testing.assert_allclose(both["global_train_losses"],
                                   dense["global_train_losses"], rtol=2e-3)
        specs = [str(l.sharding.spec) for l in
                 jax.tree_util.tree_leaves(both["state"].params)]
        assert any("pipe" in s and "fsdp" in s for s in specs)

    def test_pp_tp_specs_pattern(self):
        """Stacked leaves get ('pipe', <megatron parts>); the vocab-parallel
        decode outside the stack keeps its plain TP spec."""
        from jax.sharding import PartitionSpec as P
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models.bert import (
            pp_tp_param_specs,
        )
        model = get_model("bert_tiny", num_classes=96, scan_layers=True)
        x = jnp.zeros((2, 16), jnp.int32)
        variables = jax.eval_shape(
            lambda k: model.init(k, x, train=False), jax.random.key(0))
        specs = pp_tp_param_specs(variables["params"], pipe_axis="pipe",
                                  axis="model")
        flat = {jax.tree_util.keystr(p): s for p, s in
                jax.tree_util.tree_leaves_with_path(
                    specs, is_leaf=lambda s: isinstance(s, P))}
        qkv = next(s for k, s in flat.items()
                   if "layers" in k and "qkv" in k and "kernel" in k)
        assert qkv[0] == "pipe" and "model" in qkv
        dec = next(s for k, s in flat.items()
                   if "mlm_decoder" in k and "kernel" in k)
        assert dec == P(None, "model")
