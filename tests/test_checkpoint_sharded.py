"""Checkpoint/resume with PHYSICALLY SHARDED TrainState (FSDP / TP).

``restore_checkpoint`` must re-shard restored host arrays onto the
template's placement; these tests prove the round-trip keeps ZeRO-3 and
Megatron shardings intact and that a resumed sharded driver run continues
identically to an uninterrupted one.
"""

import numpy as np

import jax
import pytest

from learning_deep_neural_network_in_distributed_computing_environment_tpu import checkpoint as C
from learning_deep_neural_network_in_distributed_computing_environment_tpu.config import Config
from learning_deep_neural_network_in_distributed_computing_environment_tpu.driver import train_global
from learning_deep_neural_network_in_distributed_computing_environment_tpu.mesh import build_mesh


def _kw(tmp_path, **extra):
    kw = dict(model="mlp", dataset="mnist", epochs_local=1, batch_size=16,
              limit_train_samples=400, limit_eval_samples=50,
              compute_dtype="float32", augment=False,
              aggregation_by="weights", checkpoint_dir=str(tmp_path),
              checkpoint_every=1, seed=5)
    kw.update(extra)
    return kw


@pytest.mark.slow
class TestShardedResume:
    def test_fsdp_state_roundtrip_exact(self, devices, tmp_path):
        """save -> restore of a ZeRO-3-sharded TrainState is bit-exact and
        lands back on the fsdp-sharded placement."""
        from functools import partial
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import get_model
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.parallel.fsdp import fsdp_param_specs
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import LocalSGDEngine
        mesh = build_mesh({"data": 2, "fsdp": 2}, devices[:4])
        cfg = Config(model="mlp", epochs_local=1, batch_size=8,
                     compute_dtype="float32", augment=False)
        engine = LocalSGDEngine(
            get_model("mlp", num_classes=10), mesh, cfg,
            param_specs_fn=partial(fsdp_param_specs, axis="fsdp",
                                   axis_size=2))
        x = np.zeros((8, 28, 28, 1), np.float32)
        state = engine.init_state(jax.random.key(0), x)
        path = C.save_checkpoint(str(tmp_path), state, global_epoch=1)
        template = engine.init_state(jax.random.key(9), x)
        restored, epoch = C.restore_checkpoint(path, template)
        assert epoch == 1
        for a, b in zip(jax.tree_util.tree_leaves(state.params),
                        jax.tree_util.tree_leaves(restored.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            assert b.sharding.spec == a.sharding.spec  # placement kept

    def test_fsdp_resume_continues(self, devices, tmp_path):
        """Driver resume on a (data, fsdp) mesh: the restored run picks up
        at the cursor and keeps training on sharded state.  (Numerical
        identity with an uninterrupted run is NOT expected: ratios come
        from a wall-clock probe and shards are re-drawn per round.)"""
        mesh = build_mesh({"data": 2, "fsdp": 2}, devices[:4])
        kw = _kw(tmp_path)
        train_global(Config(epochs_global=2, **kw), mesh=mesh,
                     progress=False)
        res = train_global(Config(epochs_global=4, resume=True, **kw),
                           mesh=mesh, progress=False)
        assert len(res["global_train_losses"]) == 2
        assert np.isfinite(res["global_train_losses"]).all()
        # the resumed final state is still fsdp-sharded
        specs = [str(l.sharding.spec) for l in
                 jax.tree_util.tree_leaves(res["state"].params)]
        assert any("fsdp" in s for s in specs)

    def test_tp_resume_runs_and_stays_sharded(self, devices, tmp_path):
        mesh = build_mesh({"data": 2, "model": 2}, devices[:4])
        kw = _kw(tmp_path, model="bert_tiny", dataset="synthetic_mlm",
                 batch_size=8, limit_train_samples=128,
                 limit_eval_samples=32)
        train_global(Config(epochs_global=1, **kw), mesh=mesh,
                     progress=False)
        res = train_global(Config(epochs_global=2, resume=True, **kw),
                           mesh=mesh, progress=False)
        assert len(res["global_train_losses"]) == 1
        assert np.isfinite(res["global_train_losses"]).all()
        specs = [str(l.sharding.spec) for l in
                 jax.tree_util.tree_leaves(res["state"].params)]
        assert any("model" in s for s in specs)


class TestResidentCrcFallback:
    """ISSUE 12 satellite: the PR 8 crc32 corrupt-newest-epoch fallback
    was untested under ``param_residency=resident`` shard layouts — the
    1/N bucket rows are the storage unit there, so a corrupt resident
    shard must drop its epoch from the committed listing exactly like a
    replicated one, and the fallback epoch must restore the resident
    rows bitwise (buddy rows are stripped from the save and re-derived
    on restore)."""

    def _resident_engine(self, mesh):
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.models import (
            get_model,
        )
        from learning_deep_neural_network_in_distributed_computing_environment_tpu.train import (
            LocalSGDEngine,
        )
        cfg = Config(model="mlp", epochs_local=1, batch_size=8,
                     compute_dtype="float32", augment=False,
                     aggregation_by="weights", sync_mode="sharded")
        eng = LocalSGDEngine(get_model("mlp", num_classes=10, hidden=8),
                             mesh, cfg)
        assert eng.param_residency == "resident" and eng.buddy_on
        return eng

    def test_corrupt_newest_resident_epoch_falls_back_bitwise(
            self, mesh8, tmp_path):
        import os
        import json
        eng = self._resident_engine(mesh8)
        s1 = eng.init_state(jax.random.key(0),
                            np.zeros((8, 28, 28, 1), np.float32))
        s2 = eng.init_state(jax.random.key(7),
                            np.zeros((8, 28, 28, 1), np.float32))
        ck = C.CheckpointEngine(str(tmp_path), async_write=False)
        ck.save(s1, 1)
        ck.save(s2, 2)
        # the save stripped the derived buddy rows: no .buddy leaves
        manifest = json.load(
            open(tmp_path / "ckpt_2" / C.MANIFEST))
        assert all(not k.startswith(".buddy")
                   for k in manifest["leaves"])
        assert any(k.startswith(".params_resident[")
                   for k in manifest["leaves"])
        # bit rot that PRESERVES the byte size: crc32 must catch it
        sh = tmp_path / "ckpt_2" / "shard_0.msgpack"
        raw = bytearray(sh.read_bytes())
        raw[len(raw) // 2] ^= 0xFF
        sh.write_bytes(bytes(raw))
        assert C.committed_epochs(str(tmp_path)) == [1]
        latest = C.latest_checkpoint(str(tmp_path))
        assert latest.endswith("ckpt_1")
        template = eng.init_state(jax.random.key(3),
                                  np.zeros((8, 28, 28, 1), np.float32))
        restored, epoch = C.restore_checkpoint(
            latest, template, params_template=eng.params_template,
            bucket_bytes=eng.sync_bucket_bytes)
        assert epoch == 1
        assert restored.params is None
        for k, v in jax.device_get(s1.params_resident).items():
            np.testing.assert_array_equal(
                np.asarray(v),
                np.asarray(jax.device_get(
                    restored.params_resident)[k]))
        # the restore template's buddy is stripped too (derived state);
        # the engine surface rebuilds it bitwise from the restored rows
        assert restored.buddy is None
        refreshed = eng.refresh_buddy(restored)
        for name, bud in jax.device_get(s1.buddy).items():
            for comp, rows in bud.items():
                np.testing.assert_array_equal(
                    np.asarray(rows),
                    np.asarray(jax.device_get(
                        refreshed.buddy)[name][comp]))
